"""Summarise an xprof trace by op and by source line.

Companion to ``dashboard.profile_trace`` (and any ``jax.profiler`` trace):
reads the ``*.trace.json.gz`` a capture writes and prints hardware-measured
device-op durations aggregated two ways —

* by SOURCE line (``file.py:123``) — where your program's time goes;
* by HLO op name — what XLA turned it into.

This is the analysis loop behind the README's per-op table: capture once
(``python tools/w2v_profile.py --trace DIR`` or ``with
profile_trace(DIR): ...``), then ``python tools/trace_summary.py DIR``.
Wall-clock micro-benchmarks are unreliable on tunneled devices (dispatch
acks return early); the trace's ``device_duration_ps`` values come from
the hardware counters and are the trustworthy number.

Usage: python tools/trace_summary.py TRACE_DIR [--top 20] [--by op|source]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def load_events(trace_dir: str):
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    files = sorted(glob.glob(pattern, recursive=True))
    if not files:
        sys.exit(f"no *.trace.json.gz under {trace_dir}")
    events = []
    for path in files:
        with gzip.open(path) as f:
            events.extend(json.load(f).get("traceEvents", []))
    return events


def summarize(events, by: str = "source"):
    dur = collections.Counter()
    count = collections.Counter()
    label = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args", {})
        if "device_duration_ps" not in args:
            continue
        name = e.get("name", "")
        if "while" in name or name.startswith("jit_"):
            continue   # wrapper events (while loops, whole-module jit
            #            executions) already include their children
        if by == "source":
            key = args.get("source", "")
            if not key:
                continue
            label.setdefault(key, set()).add(
                args.get("tf_op", "").split("/")[-1][:40])
        else:
            key = e.get("name", "?")
            label.setdefault(key, set()).add(
                args.get("source", "").split("/")[-1])
        dur[key] += int(args["device_duration_ps"]) / 1e9   # ps -> ms
        count[key] += 1
    return dur, count, label


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--by", choices=["source", "op"], default="source")
    args = ap.parse_args(argv)

    events = load_events(args.trace_dir)
    dur, count, label = summarize(events, args.by)
    total = sum(dur.values())
    print(f"device time total: {total:.2f} ms "
          f"({sum(count.values())} op executions)")
    print(f"{'ms':>10} {'%':>6} {'n':>6}  {args.by}")
    for key, d in dur.most_common(args.top):
        tags = ", ".join(sorted(label[key])[:2])
        short = key if args.by == "op" else "/".join(key.split("/")[-2:])
        print(f"{d:10.2f} {d / total * 100:6.1f} {count[key]:6d}  "
              f"{short}  [{tags}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
