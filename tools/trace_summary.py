"""Summarise an xprof trace by op/source — and explain per-request time.

Companion to ``dashboard.profile_trace`` (and any ``jax.profiler`` trace):
reads the ``*.trace.json.gz`` a capture writes and prints hardware-measured
device-op durations aggregated two ways —

* by SOURCE line (``file.py:123``) — where your program's time goes;
* by HLO op name — what XLA turned it into.

This is the analysis loop behind the README's per-op table: capture once
(``python tools/w2v_profile.py --trace DIR`` or ``with
profile_trace(DIR): ...``), then ``python tools/trace_summary.py DIR``.
Wall-clock micro-benchmarks are unreliable on tunneled devices (dispatch
acks return early); the trace's ``device_duration_ps`` values come from
the hardware counters and are the trustworthy number.

``--host-trace FILE`` adds the REQUEST dimension (docs/OBSERVABILITY.md):
FILE is a Chrome trace JSON from ``multiverso_tpu.trace`` (e.g.
``tools/serving_bench.py --trace``). Per request (one root span per
trace id) the report breaks host wall time into queue wait, admission/
prefill, batch execution and decode iterations — the stages that explain
a p99 outlier. Given BOTH a host trace and an xprof TRACE_DIR, the two
are merged by time range: device-op time whose timeline falls inside a
request's root-span window is attributed to that request (the captures
must cover the same run; the tool aligns the two clocks by their first
events, so co-captured traces line up within scheduling jitter).

Usage::

    python tools/trace_summary.py TRACE_DIR [--top 20] [--by op|source]
    python tools/trace_summary.py --host-trace serve.json [TRACE_DIR]
        [--top 20] [--sort total|queue|device] [--slo-ms 250]

``--slo-ms`` flags (``!``) and counts requests whose total exceeds the
objective; on tail-sampled captures (``-trace_tail``) a ``keep`` column
says why each retained trace survived the sampler (slo/error/head).
Ledger-enabled captures (``-cost_ledger``) add ``tenant``/``cost``
columns from each request's ``acct.request`` accounting span —
attribution and price next to the latency breakdown.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def load_events(trace_dir: str):
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    files = sorted(glob.glob(pattern, recursive=True))
    if not files:
        sys.exit(f"no *.trace.json.gz under {trace_dir}")
    events = []
    for path in files:
        with gzip.open(path) as f:
            events.extend(json.load(f).get("traceEvents", []))
    return events


def summarize(events, by: str = "source"):
    dur = collections.Counter()
    count = collections.Counter()
    label = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args", {})
        if "device_duration_ps" not in args:
            continue
        name = e.get("name", "")
        if "while" in name or name.startswith("jit_"):
            continue   # wrapper events (while loops, whole-module jit
            #            executions) already include their children
        if by == "source":
            key = args.get("source", "")
            if not key:
                continue
            label.setdefault(key, set()).add(
                args.get("tf_op", "").split("/")[-1][:40])
        else:
            key = e.get("name", "?")
            label.setdefault(key, set()).add(
                args.get("source", "").split("/")[-1])
        dur[key] += int(args["device_duration_ps"]) / 1e9   # ps -> ms
        count[key] += 1
    return dur, count, label


def load_host_spans(path: str):
    """Rebuild spans from a ``multiverso_tpu.trace`` Chrome export:
    matched B/E pairs per (pid, tid) track -> span dicts."""
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    stacks: dict = {}
    spans = []
    for e in events:
        ph = e.get("ph")
        key = (e.get("pid"), e.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(e)
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                continue
            b = stack.pop()
            args = b.get("args", {})
            spans.append({
                "name": b.get("name", "?"),
                "ts": float(b.get("ts", 0.0)),
                "dur": float(e.get("ts", 0.0)) - float(b.get("ts", 0.0)),
                "trace_id": args.get("trace_id"),
                "parent_id": args.get("parent_id"),
                # the recording process: os.getpid() in a single-node
                # export, the node rank in the obs-plane collector's
                # merged fleet doc — the report's grouping key half
                "node": e.get("pid"),
                "args": args,
            })
    return spans


# child span names folded into per-request report columns. NB
# decode.prefill_chunk spans lie INSIDE their decode.admit window —
# prefill_ms is the dispatch-side slice of admit_ms, not extra time
_STAGE_COLUMNS = (
    ("queue_ms", ("queue.wait",)),
    ("admit_ms", ("decode.admit",)),
    ("prefill_ms", ("decode.prefill_chunk",)),
    ("exec_ms", ("batch.exec",)),
    ("decode_ms", ("decode.iter",)),
)


def request_report(spans, device_events=None):
    """Per-request rows from host spans (+ optional device-time merge).

    A request = one root span (no parent_id) and every span sharing its
    **(node, trace id)** — node being the recording pid (the node rank
    in an obs-plane merged fleet doc). Grouping by trace id alone broke
    on multi-process documents: two nodes' trace ids can collide (the
    rows silently vanished under the != 1 roots guard), and a
    cross-process ``bus.publish``/``bus.apply`` pair SHARES one trace id
    by design — per-node grouping keeps each node's half its own row,
    and the ``node`` column says which replica served what. Device
    events (xprof, ``device_duration_ps``) are merged BY TIME RANGE:
    the two timelines are aligned on their first events, then device-op
    time inside a request's window is attributed to it (overlapping
    requests both count a shared interval — attribution, not
    accounting).
    """
    by_trace: dict = {}
    for sp in spans:
        if sp["trace_id"] is not None:
            by_trace.setdefault((sp.get("node"), sp["trace_id"]),
                                []).append(sp)
    device = []
    offset = 0.0
    if device_events:
        xs = [e for e in device_events
              if e.get("ph") == "X"
              and "device_duration_ps" in e.get("args", {})]
        if xs and spans:
            offset = (min(s["ts"] for s in spans)
                      - min(float(e.get("ts", 0.0)) for e in xs))
        device = [(float(e["ts"]) + offset,
                   float(e["ts"]) + offset + float(e.get("dur", 0.0)),
                   int(e["args"]["device_duration_ps"]) / 1e9) for e in xs]
    rows = []
    for (node, trace_id), group in by_trace.items():
        roots = [s for s in group if s["parent_id"] is None]
        if len(roots) != 1:
            continue            # cross-process fragments / partial capture
        root = roots[0]
        row = {
            "trace_id": trace_id,
            "node": node,
            "name": root["name"],
            "model": root["args"].get("model", ""),
            "total_ms": root["dur"] / 1e3,
            "iters": sum(s["name"] == "decode.iter" for s in group),
            # present on tail-sampled captures: WHY this trace survived
            # the sampler (slo / error / head) — a report full of "head"
            # rows means the SLO never breached
            "keep": root["args"].get("tail_keep", ""),
        }
        for col, names in _STAGE_COLUMNS:
            row[col] = sum(s["dur"] for s in group
                           if s["name"] in names) / 1e3
        # paged-KV admissions annotate their reservation: blocks held
        # and the pool's free count at admit time — a fat queue_ms next
        # to a small pool_free says the request waited for BLOCKS, not
        # for a slot
        admits = [s for s in group if s["name"] == "decode.admit"]
        if admits and "blocks" in admits[0]["args"]:
            row["blocks"] = admits[0]["args"]["blocks"]
            row["pool_free"] = admits[0]["args"].get("pool_free")
        # prefix-cache engines annotate the admit span with the blocks
        # matched and the prefill tokens they saved: a near-zero
        # admit/prefill column next to a fat "saved" one says this
        # request's TTFT came from the cache, not from prefill work
        if admits and "prefix_hit_blocks" in admits[0]["args"]:
            row["prefix_hit_blocks"] = admits[0]["args"]["prefix_hit_blocks"]
            row["prefill_tokens_saved"] = admits[0]["args"].get(
                "prefill_tokens_saved", 0)
        # sharded-decode engines annotate the admit span with the decode
        # mesh width: the report then says which tensor-parallel config
        # served each row (replicated engines omit it — no column)
        if admits and "decode_tp" in admits[0]["args"]:
            row["decode_tp"] = admits[0]["args"]["decode_tp"]
        # sequence-parallel engines (-prefill_sp) annotate every
        # prefill_chunk span with the routing decision: the report's sp
        # column then says which prompts ran the seqpar program (and
        # with which backend) versus riding the single-lane path under
        # the threshold ("off"); engines without the flag omit the
        # column entirely
        sp_chunks = [s for s in group
                     if s["name"] == "decode.prefill_chunk"
                     and "sp" in s["args"]]
        if sp_chunks:
            row["sp"] = (sp_chunks[0]["args"].get("sp_backend", "?")
                         if any(c["args"].get("sp") for c in sp_chunks)
                         else "off")
        # quantized-KV engines annotate the admit span with the pool
        # encoding: the report then says which requests were served off
        # int8 pools (fp engines omit it — no column), the first thing
        # to check when a fleet's outputs drift between replicas
        if admits and "kv_quant" in admits[0]["args"]:
            row["kv_quant"] = admits[0]["args"]["kv_quant"]
        # preempted-and-resumed requests: decode.preempt spans count the
        # evictions and the resume's admit span carries the running
        # total — a fat total_ms next to a nonzero preempt column says
        # this request paid for someone else's burst
        preempts = sum(s["name"] == "decode.preempt" for s in group)
        resumed = [a for a in admits if "preempted" in a["args"]]
        if preempts or resumed:
            row["preempted"] = (resumed[-1]["args"]["preempted"]
                                if resumed else preempts)
        # disaggregated requests: the decode.admit and kv.transfer
        # spans carry the transfer-plane accounting — blocks shipped,
        # raw K/V bytes moved, and blocks that dedup'd instead of
        # crossing the wire (a fat xfkb next to a zero dedup column
        # says the decode side's cache was cold for this prefix)
        xfers = [s for s in group if s["name"] == "kv.transfer"]
        annotated = ([a for a in admits if "xfer_blocks" in a["args"]]
                     + [x for x in xfers if "xfer_blocks" in x["args"]])
        if annotated:
            src = annotated[0]["args"]
            row["xfer_blocks"] = src["xfer_blocks"]
            row["xfer_bytes"] = src.get("xfer_bytes", 0)
            row["dedup_blocks"] = src.get("dedup_blocks", 0)
        # ledger-enabled engines (-cost_ledger) record one acct.request
        # span per finalized request: the tenant the usage was
        # attributed to and the folded cost units — the report then
        # says WHO each tail outlier belongs to and what it cost
        accts = [s for s in group if s["name"] == "acct.request"]
        if accts and "tenant" in accts[0]["args"]:
            row["tenant"] = accts[0]["args"]["tenant"]
            row["cost"] = accts[0]["args"].get("cost", 0.0)
        if device:
            w0, w1 = root["ts"], root["ts"] + root["dur"]
            row["device_ms"] = sum(
                d for (t0, t1, d) in device if t0 < w1 and t1 > w0)
        rows.append(row)
    return rows


def print_request_report(rows, top: int, sort: str,
                         slo_ms: float = 0.0) -> None:
    key = {"total": "total_ms", "queue": "queue_ms",
           "device": "device_ms"}.get(sort, "total_ms")
    rows = sorted(rows, key=lambda r: r.get(key, 0.0), reverse=True)
    has_dev = any("device_ms" in r for r in rows)
    has_blocks = any("blocks" in r for r in rows)
    has_prefix = any("prefix_hit_blocks" in r for r in rows)
    has_tp = any("decode_tp" in r for r in rows)
    has_quant = any("kv_quant" in r for r in rows)
    has_sp = any("sp" in r for r in rows)
    has_preempt = any("preempted" in r for r in rows)
    has_xfer = any("xfer_blocks" in r for r in rows)
    has_tenant = any("tenant" in r for r in rows)
    has_keep = any(r.get("keep") for r in rows)
    # the node column ships as soon as the doc holds more than one
    # recording process (an obs-plane merged fleet trace); single-node
    # reports keep their classic layout
    has_node = len({r.get("node") for r in rows}) > 1
    breaches = (sum(r["total_ms"] > slo_ms for r in rows) if slo_ms > 0
                else 0)
    head = f"{len(rows)} request(s); slowest by {key}"
    if slo_ms > 0:
        head += (f"; {breaches} over the {slo_ms:g} ms SLO "
                 f"(flagged '!')")
    print(head + ":")
    hdr = (f"{'total':>9} {'queue':>8} {'admit':>8} {'prefill':>8} "
           f"{'exec':>8} {'decode':>8} {'iters':>6}")
    if has_node:
        hdr += f" {'node':>6}"
    if has_blocks:
        hdr += f" {'blocks':>7} {'pfree':>6}"
    if has_prefix:
        hdr += f" {'pfxhit':>7} {'saved':>6}"
    if has_tp:
        hdr += f" {'tp':>3}"
    if has_quant:
        hdr += f" {'quant':>6}"
    if has_sp:
        hdr += f" {'sp':>8}"
    if has_preempt:
        hdr += f" {'preempt':>8}"
    if has_xfer:
        hdr += f" {'xfblk':>6} {'xfkb':>8} {'dedup':>6}"
    if has_tenant:
        hdr += f" {'tenant':>10} {'cost':>9}"
    if has_dev:
        hdr += f" {'device':>9}"
    if has_keep:
        hdr += f" {'keep':>6}"
    print(hdr + "  trace_id [model]")
    for r in rows[:top]:
        flag = "!" if slo_ms > 0 and r["total_ms"] > slo_ms else " "
        line = (f"{r['total_ms']:8.3f}{flag} {r['queue_ms']:8.3f} "
                f"{r['admit_ms']:8.3f} {r.get('prefill_ms', 0.0):8.3f} "
                f"{r['exec_ms']:8.3f} "
                f"{r['decode_ms']:8.3f} {r['iters']:6d}")
        if has_node:
            line += f" {str(r.get('node', '-')):>6}"
        if has_blocks:
            line += (f" {str(r.get('blocks', '-')):>7} "
                     f"{str(r.get('pool_free', '-')):>6}")
        if has_prefix:
            line += (f" {str(r.get('prefix_hit_blocks', '-')):>7} "
                     f"{str(r.get('prefill_tokens_saved', '-')):>6}")
        if has_tp:
            line += f" {str(r.get('decode_tp', '-')):>3}"
        if has_quant:
            line += f" {str(r.get('kv_quant', '-')):>6}"
        if has_sp:
            line += f" {str(r.get('sp', '-')):>8}"
        if has_preempt:
            line += f" {str(r.get('preempted', '-')):>8}"
        if has_xfer:
            if "xfer_blocks" in r:
                line += (f" {r['xfer_blocks']:6d} "
                         f"{r.get('xfer_bytes', 0) / 1024.0:8.1f} "
                         f"{r.get('dedup_blocks', 0):6d}")
            else:
                line += f" {'-':>6} {'-':>8} {'-':>6}"
        if has_tenant:
            if "tenant" in r:
                line += (f" {str(r['tenant'])[:10]:>10} "
                         f"{r.get('cost', 0.0):9.3f}")
            else:
                line += f" {'-':>10} {'-':>9}"
        if has_dev:
            line += f" {r.get('device_ms', 0.0):9.3f}"
        if has_keep:
            line += f" {r.get('keep') or '-':>6}"
        # non-request roots (snapshot.pin, table.add, bus.publish) label
        # themselves by span name instead of a model
        print(line + f"  {r['trace_id']} [{r['model'] or r['name']}]")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", nargs="?", default=None,
                    help="xprof capture directory (*.trace.json.gz)")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--by", choices=["source", "op"], default="source")
    ap.add_argument("--host-trace", default=None,
                    help="multiverso_tpu.trace Chrome JSON: per-request "
                         "host breakdown (+ device merge with TRACE_DIR)")
    ap.add_argument("--sort", choices=["total", "queue", "device"],
                    default="total", help="request-report sort column")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="flag requests whose total exceeds this latency "
                         "SLO and count the breaches (0 = off)")
    args = ap.parse_args(argv)

    if args.host_trace is None and args.trace_dir is None:
        ap.error("need an xprof TRACE_DIR, a --host-trace file, or both")

    events = load_events(args.trace_dir) if args.trace_dir else None
    if args.host_trace is not None:
        spans = load_host_spans(args.host_trace)
        rows = request_report(spans, events)
        print_request_report(rows, args.top, args.sort, args.slo_ms)
        if events is None:
            return 0
        print()
    dur, count, label = summarize(events, args.by)
    total = sum(dur.values())
    print(f"device time total: {total:.2f} ms "
          f"({sum(count.values())} op executions)")
    print(f"{'ms':>10} {'%':>6} {'n':>6}  {args.by}")
    for key, d in dur.most_common(args.top):
        tags = ", ".join(sorted(label[key])[:2])
        short = key if args.by == "op" else "/".join(key.split("/")[-2:])
        print(f"{d:10.2f} {d / total * 100:6.1f} {count[key]:6d}  "
              f"{short}  [{tags}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
