"""Binding-level data-parallel benchmark driver (VERDICT r1 item 7).

Reproduces the SHAPE of the reference's headline benchmark table
(``binding/python/docs/BENCHMARK.md:33-57``: CIFAR-10 ResNet-32 through the
Python binding, 1-worker baseline / +multiverso overhead / 4-worker
speedup) on this environment:

* rows 1-2 run ResNet-32 (464k params) on the real TPU chip — no-MV
  baseline vs MV with sync every minibatch (binding overhead);
* rows 3-4 run the 4-process data-parallel leg on CPU (the only way to get
  4 real processes here): 1-process baseline vs 4 processes through
  ``jax_ext.MVNetParamManager``, same total work, reporting the speedup.

Writes ``docs/BENCHMARK.md``. Dataset is synthetic CIFAR-shaped (no
egress); accuracies are comparable only within this table.

Usage: python tools/bench_binding.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLE = os.path.join(_REPO, "binding", "python", "examples",
                        "cifar_resnet.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse_result(out: str):
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"no RESULT line in output:\n{out[-2000:]}")


def run_single(args, platform: str, timeout=3600):
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=1")
        # sitecustomize pins the TPU plugin; neutralise it for CPU legs
        code = ("import sys; sys.path.insert(0, %r); import jax; "
                "jax.config.update('jax_platforms','cpu'); "
                "sys.argv = ['cifar_resnet'] + %r; "
                "import cifar_resnet; sys.exit(cifar_resnet.main())"
                % (os.path.dirname(_EXAMPLE), args))
        cmd = [sys.executable, "-c", code]
    else:
        cmd = [sys.executable, _EXAMPLE] + args
    out = subprocess.run(cmd, env=env, cwd=os.path.dirname(_EXAMPLE),
                         capture_output=True, text=True, timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(f"run failed:\n{out.stdout[-2000:]}\n"
                           f"{out.stderr[-2000:]}")
    return _parse_result(out.stdout + out.stderr)


def run_group(args, n: int, timeout=3600):
    port = _free_port()
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "MV_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "MV_NUM_PROCESSES": str(n),
            "MV_PROCESS_ID": str(rank),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        code = ("import sys; sys.path.insert(0, %r); import jax; "
                "jax.config.update('jax_platforms','cpu'); "
                "sys.argv = ['cifar_resnet'] + %r; "
                "import cifar_resnet; sys.exit(cifar_resnet.main())"
                % (os.path.dirname(_EXAMPLE), args))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            cwd=os.path.dirname(_EXAMPLE),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    results = []
    for rank, proc in enumerate(procs):
        out, _ = proc.communicate(timeout=timeout)
        if proc.returncode != 0:
            for p in procs:
                p.kill()
            raise RuntimeError(f"rank {rank} failed:\n{out[-2500:]}")
        results.append(_parse_result(out))
    return results


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(_REPO, "docs",
                                                  "BENCHMARK.md"))
    args = ap.parse_args(argv)

    if args.quick:
        tpu_args = ["-epochs", "2", "-train", "2048", "-test", "512"]
        cpu_args = ["-epochs", "2", "-train", "1024", "-test", "256",
                    "-n", "1"]
    else:
        tpu_args = ["-epochs", "3", "-train", "10000", "-test", "2000"]
        cpu_args = ["-epochs", "3", "-train", "2048", "-test", "512",
                    "-n", "1"]

    rows = []
    print("[1/4] TPU 1 proc, no multiverso ...", flush=True)
    rows.append(("1 proc x 1 TPU chip, no multiverso",
                 run_single(tpu_args, "tpu")))
    print("[2/4] TPU 1 proc, multiverso sync=1 ...", flush=True)
    rows.append(("1 proc x 1 TPU chip, multiverso, sync every minibatch",
                 run_single(tpu_args + ["-mv", "1", "-sync_every", "1"],
                            "tpu")))
    print("[3/4] CPU 1 proc, no multiverso ...", flush=True)
    rows.append(("1 proc (CPU), no multiverso", run_single(cpu_args, "cpu")))
    print("[4/4] CPU 4 procs, multiverso sync=1 ...", flush=True)
    group = run_group(cpu_args + ["-mv", "1", "-sync_every", "1"], 4)
    rows.append(("4 procs (CPU), multiverso, sync every minibatch",
                 group[0]))

    cpu_base = rows[2][1]["sec_per_epoch"]
    cpu_dp = rows[3][1]["sec_per_epoch"]
    ncores = os.cpu_count() or 1
    lines = [
        "# Binding benchmark: CIFAR-class ResNet, data-parallel",
        "",
        "Shape-reproduction of the reference's headline table",
        "(`binding/python/docs/BENCHMARK.md:33-57` in the reference:",
        "CIFAR-10 ResNet-32 via the Python binding param manager).",
        "Produced by `tools/bench_binding.py`; model/dataset details in",
        "`binding/python/examples/cifar_resnet.py` (synthetic CIFAR-shaped",
        "data — no egress; accuracies comparable within this table only).",
        "",
        "| configuration | model | params | sec/epoch | test acc |",
        "|---|---|---|---|---|",
    ]
    for name, r in rows:
        lines.append(
            f"| {name} | ResNet-{r['depth']} | {r['params']:,} "
            f"| {r['sec_per_epoch']} | {r['test_acc']:.3f} |")
    lines += [
        "",
        "Environment caveats, so these rows are read correctly:",
        "",
        f"* this box exposes **{ncores} CPU core(s)**, so the 4-process leg",
        "  timeshares one core — the reference's 3.40x/4-GPU speedup is",
        "  physically unreachable here. What the CPU pair DOES measure is",
        "  the binding's data-parallel overhead: 4 processes doing the same",
        "  total work through `MVNetParamManager` (sync every minibatch,",
        f"  aggregation + barrier per step) cost {cpu_dp / cpu_base:.2f}x "
        f"the 1-process wall",
        "  time — i.e. the sync machinery adds "
        f"~{max(cpu_dp / cpu_base - 1, 0) * 100:.0f}% on top of pure",
        "  compute. On independent accelerators (the reference's setup,",
        "  or one process per TPU chip) the same path data-parallelises",
        "  the compute: see `tests/test_multiprocess.py` and",
        "  `docs/DISTRIBUTED.md` for the multi-chip story.",
        "* the TPU chip is reached through a network tunnel in this",
        "  environment: the +multiverso TPU row pays per-sync host<->device",
        "  round trips over that tunnel (hundreds of ms each), which a real",
        "  TPU-VM (PCIe-local chip) would not.",
        "",
    ]
    text = "\n".join(lines)
    with open(args.out, "w") as f:
        f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
