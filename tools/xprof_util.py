"""Shared xprof device-time measurement for the perf tools.

Wall clocks are unreliable on a tunneled device (dispatch acks return
early) and repeated start_trace/stop_trace in one process hangs — so
every measurement is ONE trace (callers run one measurement per
subprocess) and the reported time is hardware ``device_duration_ps``.

Accounting rule (one place, on purpose): sum the ``jit_*`` program
spans. This CHANGED the methodology in round 3 — the tools previously
summed the non-``jit_``/non-``while`` leaf ops, which double-counts
multi-level traces (per-run parent rows + leaves) on big programs and
reads lower than the program span on single-op jits (the r3 regeneration
of TPU_VALIDATE.json re-measured every row under spans). The program
span covers the whole dispatched step on device, for single-op jits and
full train steps alike, and is the number wall-clock comparisons
reproduce.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
import tempfile


def trace_device_ms(run_fn, iters: int = 5) -> float:
    """Average device ms per call of ``run_fn`` over ``iters`` traced calls.

    ``run_fn()`` must dispatch the program under test and return a value
    whose completion the caller's final fetch forces; this helper blocks
    via ``jax.block_until_ready`` + a scalar fetch after the loop.
    Call the function once BEFORE this (compile outside the trace).
    """
    import jax

    trace_dir = tempfile.mkdtemp(prefix="xprof_")
    jax.profiler.start_trace(trace_dir)
    out = None
    for _ in range(iters):
        out = run_fn()
    jax.block_until_ready(out)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(leaf.reshape(-1)[0])
    jax.profiler.stop_trace()
    path = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                     recursive=True)[0]
    with gzip.open(path) as fh:
        events = json.load(fh)["traceEvents"]
    total = sum(int(e["args"]["device_duration_ps"]) / 1e9 for e in events
                if e.get("ph") == "X"
                and "device_duration_ps" in e.get("args", {})
                and e.get("name", "").startswith("jit_"))
    shutil.rmtree(trace_dir, ignore_errors=True)
    return total / iters
