"""Fleet ops center: render ObsCollector state from agent report archives.

The fleet observability plane (``multiverso_tpu/serving/obs_plane.py``,
``-obs_plane``) aggregates live inside the rank-0 collector; this tool
is the OFFLINE half — point it at the per-node report archives the
agents write (``-obs_jsonl=PATH`` appends one JSON line per shipped
report, suffixed ``.<rank>`` in multi-process sessions), and it replays
them through a fresh :class:`ObsCollector` to answer the fleet
questions after the fact:

* **default** — the fleet table: one row per node (liveness, reports,
  tok/s, live sequences, watchdog trips, worst SLO burn), fleet-merged
  histogram percentiles (log-bucketed, documented ±9.05% bound), and
  fleet SLO burn. A node whose last report wall-timestamp trails the
  fleet's newest by more than ``--silent-after`` (default 2x the median
  report interval) renders **SILENT** — the offline analogue of the
  live collector's DEGRADED flag. When a node's registry carries the
  serving-fleet router's per-replica gauges
  (``FLEET_REPLICA_STATE/FLEET_INFLIGHT/FLEET_HB_AGE_MS/``
  ``FLEET_SNAPSHOT_VERSION``), the table additionally renders one row
  per decode REPLICA — lifecycle state (UP/PROBING/DEAD), serving
  role (``role``: unified / prefill / decode from ``FLEET_ROLE``; a
  disaggregated fleet's split at a glance — "-" = an archive predating
  the gauge), in-flight count, heartbeat age, the SERVED snapshot
  version (``snap_v``; a fleet serving divergent or frozen versions —
  a dead or zombie trainer — is visible at a glance), and the engine's
  cumulative preemption count (``preempts``; overload churn per
  replica — a replica preempting while its siblings idle is a routing
  or pool-sizing problem). -1 in a numeric column = an archive
  predating its gauge (docs/SERVING.md "Serving fleet" / "Overload and
  preemption" / "Disaggregated prefill/decode", docs/DISTRIBUTED.md
  "Durability").
* ``--tenants`` — the fleet tenant-accounting table: one row per
  (engine, tenant), biggest spender first, assembled from the engine
  cost ledgers' ``TENANT_*[engine.tenant]`` instruments
  (``-cost_ledger``): requests, prefill/decode tokens, KV
  block-seconds, transfer bytes, folded cost units, fleet-merged
  completion-latency p99, and the SLO breach fraction against
  ``TENANT_SLO_MS`` ("-" = no SLO registered or an archive predating
  the ledger; docs/OBSERVABILITY.md "Tenant accounting").
* ``--prom`` — the merged registry as one Prometheus text exposition,
  every sample carrying a ``node`` label.
* ``--trace OUT.json`` — the merged cross-process Perfetto document:
  one process track per node, every node's tail-kept spans rebased onto
  the shared epoch-µs timebase (open next to an xprof capture).

Usage::

    JAX_PLATFORMS=cpu python tools/opscenter.py reports.jsonl.0 \
        reports.jsonl.1 reports.jsonl.2 [--prom] [--tenants]
        [--trace merged.json] [--silent-after 2.5]

Reading the table: docs/OBSERVABILITY.md "Fleet plane".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def load_reports(paths: List[str]) -> Tuple[List[dict], List[float]]:
    """All reports from every archive, sorted by sender wall timestamp
    (replay order must respect time so "latest row wins" holds), plus
    the observed report intervals (the silent-threshold default)."""
    reports: List[dict] = []
    intervals: List[float] = []
    for path in paths:
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rep = json.loads(line)
                except json.JSONDecodeError as exc:
                    print(f"opscenter: {path}:{i + 1}: {exc}",
                          file=sys.stderr)
                    continue
                if not isinstance(rep, dict) or "node" not in rep:
                    continue
                reports.append(rep)
                dt = rep.get("interval_s")
                if isinstance(dt, (int, float)) and dt > 0:
                    intervals.append(float(dt))
    reports.sort(key=lambda r: r.get("ts", 0.0))
    return reports, intervals


def build_collector(reports: List[dict]):
    from multiverso_tpu.serving.obs_plane import ObsCollector

    col = ObsCollector(name="opscenter")
    for rep in reports:
        col.ingest(int(rep["node"]), rep)
    return col


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet table / merged Prometheus / merged Perfetto "
                    "from obs-plane report archives (-obs_jsonl)")
    ap.add_argument("reports", nargs="+",
                    help="per-node report JSONL archives (one per node; "
                         "-obs_jsonl writes them)")
    ap.add_argument("--prom", action="store_true",
                    help="print the merged registry as Prometheus text "
                         "(node label per sample) instead of the table")
    ap.add_argument("--tenants", action="store_true",
                    help="print the per-tenant cost-attribution table "
                         "(engine cost ledgers merged fleet-wide) "
                         "instead of the node table")
    ap.add_argument("--trace", default="",
                    help="write the merged cross-process Perfetto doc "
                         "here (one process track per node)")
    ap.add_argument("--silent-after", type=float, default=0.0,
                    help="flag a node SILENT when its last report trails "
                         "the fleet's newest by this many seconds "
                         "(0 = 2x the median observed report interval)")
    args = ap.parse_args(argv)
    try:
        reports, intervals = load_reports(args.reports)
    except OSError as exc:
        print(f"opscenter: {exc}", file=sys.stderr)
        return 2
    if not reports:
        print("opscenter: no reports found in the archive(s)",
              file=sys.stderr)
        return 2
    col = build_collector(reports)
    silent_after = args.silent_after
    if silent_after <= 0:
        med = sorted(intervals)[len(intervals) // 2] if intervals else 1.0
        silent_after = 2.0 * med
    if args.trace:
        from multiverso_tpu.trace import validate_chrome_events

        doc = col.export_chrome(args.trace)
        summary = validate_chrome_events(doc["traceEvents"])
        print(f"merged trace: {args.trace} — {summary['spans']} span(s), "
              f"{summary['traces']} trace(s) across "
              f"{doc['otherData']['nodes']} node(s)")
    if args.prom:
        sys.stdout.write(col.prometheus())
    elif args.tenants:
        table = col.tenants_table()
        if not table:
            print("opscenter: no tenant-ledger rows in the archive(s) "
                  "(engines run without -cost_ledger?)", file=sys.stderr)
            return 2
        print(table)
    else:
        print(col.table(silent_after_s=silent_after))
    return 0


if __name__ == "__main__":
    sys.exit(main())
