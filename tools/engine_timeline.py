"""Render utilization / bubble analysis from a flight-recorder dump.

Input is the JSONL a :class:`serving.flight_recorder.FlightRecorder`
writes (``engine.recorder.export_jsonl(path)``, a watchdog bundle's
``ring.jsonl``, or ``tools/serving_bench.py --flight FILE``): one meta
line, then one record per engine iteration. This tool answers the
post-hoc capacity questions the ring exists for:

* **where did the wall time go** — busy vs idle fraction over the
  window, the largest idle gaps (bubbles) with their timestamps, and a
  bucketed utilization strip so a ramp/stall is visible at a glance;
* **where did the FLOPs go** — prefill-vs-decode token share, overall
  and per time bucket (a prefill-heavy stripe is an admission wave, a
  decode-only tail is the drain);
* **what was the engine holding** — mean/peak live slots, queue depth
  and max queue age per bucket, pool occupancy when paged;
* **was speculation earning its keep** — drafts verified vs accepted
  per bucket as an acceptance-rate strip (spec engines only; pre-PR-11
  dumps and ``spec_k=0`` rings render without it).

Usage::

    python tools/engine_timeline.py RING.jsonl [--buckets 40]
        [--top-gaps 5]
    python tools/engine_timeline.py --merge RING0.jsonl RING1.jsonl ...
        [--buckets 60]

``--merge`` takes one ring dump per replica and renders their
utilization strips ALIGNED on a shared timebase: each dump's monotonic
timestamps rebase to epoch through the anchor its meta line carries
(``anchor_epoch_s``/``anchor_mono_s``), so a stall on node 1 lines up
column-for-column with the admission wave on node 0 that caused it —
the fleet-level "where did the wall go" view the obs plane's collector
feeds on. Dumps predating the anchor fields still render (aligned at
their own window start, flagged ``~`` for approximate) — the PR 8/11
old-dump tolerance pattern.

Pure host-side (no jax): loadable against a dump from any run,
including one scraped out of a dead replica's watchdog bundle.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Tuple

# the wall/busy/gap digest lives in ONE place — flight_recorder.py. That
# module is stdlib-only, but importing it through the package would drag
# jax in, so load the file itself (works against a bare checkout).
_FR_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "multiverso_tpu", "serving", "flight_recorder.py")
_spec = importlib.util.spec_from_file_location("_mv_flight_recorder",
                                               _FR_PATH)
_flight_recorder = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_flight_recorder)
window_digest = _flight_recorder.window_digest


def load_ring(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Parse a flight-recorder JSONL dump -> (meta, records oldest first)."""
    meta: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if i == 0 and "flight_recorder" in row:
                meta = row["flight_recorder"]
                continue
            records.append(row)
    return meta, records


def timeline_report(records: List[Dict[str, Any]], buckets: int = 40,
                    top_gaps: int = 5) -> Dict[str, Any]:
    """Digest a record list into the report dict ``render`` prints.

    The window opens when the first retained iteration's work began
    (``ts - busy_ms``) and closes at the last record; every bucket
    aggregates the iterations whose record timestamp falls inside it.
    """
    digest = window_digest(records)
    report = {"iterations": len(records), **digest,
              "gaps": digest["gaps"][:top_gaps], "buckets": []}
    report.pop("max_idle_gap_ms")
    # prefix-cache effectiveness over time: the shared-block count rides
    # every record since the prefix-caching PR (-1 on contiguous engines;
    # absent in older dumps — both render as "no cache data")
    report["peak_shared"] = max(
        (r.get("pool_shared", -1) for r in records), default=-1)
    # speculative decoding: drafts verified/accepted ride every record
    # since the spec-decode PR (-1 on spec_k=0 engines; absent in older
    # dumps — both render as "no spec data" and skip the strip)
    spec_prop = sum(max(0, r.get("spec_proposed", -1)) for r in records)
    spec_acc = sum(max(0, r.get("spec_accepted", -1)) for r in records)
    report["spec_enabled"] = any(
        r.get("spec_proposed", -1) >= 0 for r in records)
    report["spec_proposed"] = spec_prop
    report["spec_accepted"] = spec_acc
    report["acceptance_rate"] = (spec_acc / spec_prop if spec_prop
                                 else 0.0)
    if not records:
        return report
    t0 = records[0]["ts"] - records[0]["busy_ms"] / 1e3
    wall = digest["wall_s"]

    n_buckets = max(1, min(int(buckets), len(records)))
    width = wall / n_buckets
    rows: List[Dict[str, Any]] = [
        {"t_s": round(b * width, 6), "iters": 0, "busy_ms": 0.0,
         "prefill_toks": 0, "decode_toks": 0, "live_sum": 0, "live_max": 0,
         "queue_max": 0, "queue_age_ms_max": 0.0, "shared_max": -1,
         "spec_proposed": 0, "spec_accepted": 0}
        for b in range(n_buckets)]
    for r in records:
        b = min(n_buckets - 1, int((r["ts"] - t0) / width))
        row = rows[b]
        row["iters"] += 1
        row["busy_ms"] += r["busy_ms"]
        row["prefill_toks"] += r["prefill_toks"]
        row["decode_toks"] += r["decode_toks"]
        row["live_sum"] += r["live"] + r["reserved"]
        row["live_max"] = max(row["live_max"], r["live"] + r["reserved"])
        row["queue_max"] = max(row["queue_max"], r["queue"])
        row["queue_age_ms_max"] = max(row["queue_age_ms_max"],
                                      r["queue_age_ms"])
        row["shared_max"] = max(row["shared_max"],
                                r.get("pool_shared", -1))
        row["spec_proposed"] += max(0, r.get("spec_proposed", -1))
        row["spec_accepted"] += max(0, r.get("spec_accepted", -1))
    for row in rows:
        row["busy_frac"] = min(1.0, row["busy_ms"] / (width * 1e3))
        row["live_mean"] = (row["live_sum"] / row["iters"]
                            if row["iters"] else 0.0)
        toks = row["prefill_toks"] + row["decode_toks"]
        row["prefill_share"] = row["prefill_toks"] / toks if toks else 0.0
        row["acceptance_rate"] = (row["spec_accepted"]
                                  / row["spec_proposed"]
                                  if row["spec_proposed"] else 0.0)
        del row["live_sum"]
    report["buckets"] = rows
    return report


def merge_report(dumps, buckets: int = 60):
    """Digest N ``(meta, records)`` dumps onto ONE shared timebase.

    Anchored dumps (meta carries ``anchor_epoch_s``/``anchor_mono_s``)
    rebase record timestamps to epoch seconds, so replicas align by
    wall time; un-anchored (old) dumps can't — they align at the shared
    window's origin and are marked ``aligned: "origin"`` so the render
    flags them approximate instead of crashing or silently lying.

    Returns ``{"wall_s", "t0_epoch_s", "nodes": [{"name", "aligned",
    "iterations", "busy_frac", "prefill_tokens", "decode_tokens",
    "peak_live", "strip": [busy_frac per bucket]}]}``.
    """
    rebased = []
    for meta, records in dumps:
        name = meta.get("name", "") or f"engine{len(rebased)}"
        wall = meta.get("anchor_epoch_s")
        mono = meta.get("anchor_mono_s")
        anchored = isinstance(wall, (int, float)) and isinstance(
            mono, (int, float))
        recs = [dict(r) for r in records]
        if anchored:
            for r in recs:
                r["ts"] = wall + (r["ts"] - mono)
        rebased.append((name, anchored, recs))
    # shared window: earliest work start to latest record, over the
    # ANCHORED dumps; origin-aligned dumps shift to start at t0
    starts = [r[0]["ts"] - r[0]["busy_ms"] / 1e3
              for _, anchored, r in rebased if anchored and r]
    t0 = min(starts) if starts else 0.0
    for name, anchored, recs in rebased:
        if not anchored and recs:
            off = t0 - (recs[0]["ts"] - recs[0]["busy_ms"] / 1e3)
            for r in recs:
                r["ts"] += off
    end = max((r[-1]["ts"] for _, _, r in rebased if r), default=t0)
    wall = max(end - t0, 1e-9)
    n_buckets = max(1, int(buckets))
    width = wall / n_buckets
    nodes = []
    for name, anchored, recs in rebased:
        strip = [0.0] * n_buckets
        for r in recs:
            b = min(n_buckets - 1, max(0, int((r["ts"] - t0) / width)))
            strip[b] += r["busy_ms"]
        digest = window_digest(recs)
        nodes.append({
            "name": name,
            "aligned": "epoch" if anchored else "origin",
            "iterations": len(recs),
            "busy_frac": digest["busy_frac"],
            "prefill_tokens": digest["prefill_tokens"],
            "decode_tokens": digest["decode_tokens"],
            "peak_live": digest["peak_live"],
            "strip": [min(1.0, s / (width * 1e3)) for s in strip],
        })
    return {"wall_s": wall, "t0_epoch_s": t0, "buckets": n_buckets,
            "nodes": nodes}


def render_merge(report) -> str:
    """Aligned per-node utilization strips + a per-node summary table."""
    lines = [
        f"fleet timeline: {len(report['nodes'])} node(s) over "
        f"{report['wall_s']:.3f}s shared window "
        f"({report['wall_s'] / report['buckets']:.3f}s per column; "
        f"scale '{_BARS[0]}'=0 .. '{_BARS[-1]}'=1; '~' = old dump, "
        f"origin-aligned)"]
    width = max((len(n["name"]) for n in report["nodes"]), default=4)
    for n in report["nodes"]:
        strip = "".join(_bar(f) for f in n["strip"])
        flag = " " if n["aligned"] == "epoch" else "~"
        lines.append(f"{n['name']:>{width}}{flag}|{strip}|")
    lines.append(f"{'node':>{width}} {'iters':>7} {'busy':>6} "
                 f"{'prefill':>8} {'decode':>8} {'peak':>5}")
    for n in report["nodes"]:
        lines.append(
            f"{n['name']:>{width}} {n['iterations']:>7} "
            f"{n['busy_frac']:>6.1%} {n['prefill_tokens']:>8} "
            f"{n['decode_tokens']:>8} {n['peak_live']:>5}")
    return "\n".join(lines)


_BARS = " .:-=+*#%@"


def _bar(frac: float) -> str:
    """One glyph per bucket, darker = higher."""
    level = min(len(_BARS) - 1, int(frac * (len(_BARS) - 1) + 0.5))
    return _BARS[level]


def render(report: Dict[str, Any], name: str = "") -> str:
    lines: List[str] = []
    lines.append(
        f"engine timeline{f' [{name}]' if name else ''}: "
        f"{report['iterations']} iterations over {report['wall_s']:.3f}s "
        f"— busy {report['busy_frac']:.1%}, idle {report['idle_frac']:.1%}")
    total = report["prefill_tokens"] + report["decode_tokens"]
    lines.append(
        f"tokens: {report['prefill_tokens']} prefill / "
        f"{report['decode_tokens']} decode ({report['prefill_share']:.1%} "
        f"prefill share of {total}); {report['steps']} fused steps, "
        f"mean {report['mean_step_ms']:.3f} ms; peak live "
        f"{report['peak_live']}"
        + (f"; peak shared KV blocks {report['peak_shared']}"
           if report.get("peak_shared", -1) >= 0 else ""))
    if report.get("spec_enabled"):
        lines.append(
            f"speculation: {report['spec_proposed']} drafts verified, "
            f"{report['spec_accepted']} accepted "
            f"({report['acceptance_rate']:.1%} acceptance)")
    if report["gaps"]:
        worst = ", ".join(f"{g['gap_ms']:.1f}ms@{g['t_s']:.3f}s"
                          for g in report["gaps"])
        lines.append(f"largest bubbles: {worst}")
    if report["buckets"]:
        util = "".join(_bar(b["busy_frac"]) for b in report["buckets"])
        pf = "".join(_bar(b["prefill_share"]) for b in report["buckets"])
        lines.append(f"utilization   |{util}|")
        lines.append(f"prefill share |{pf}|   "
                     f"(scale: '{_BARS[0]}'=0 .. '{_BARS[-1]}'=1, "
                     f"{report['wall_s'] / len(report['buckets']):.3f}s "
                     f"per column)")
        has_spec = report.get("spec_enabled", False)
        if has_spec:
            # acceptance over time: a fading strip is the drafter losing
            # the tail (e.g. traffic left its repetitive regime)
            acc = "".join(_bar(b["acceptance_rate"])
                          for b in report["buckets"])
            lines.append(f"acceptance    |{acc}|")
        has_shared = report.get("peak_shared", -1) >= 0
        lines.append(f"{'t_s':>8} {'iters':>6} {'busy':>6} {'live':>6} "
                     f"{'qmax':>5} {'qage_ms':>8} {'prefill':>8} "
                     f"{'decode':>8}"
                     + (f" {'shared':>7}" if has_shared else "")
                     + (f" {'accept':>7}" if has_spec else ""))
        for b in report["buckets"]:
            if not b["iters"]:
                continue
            line = (
                f"{b['t_s']:8.3f} {b['iters']:6d} {b['busy_frac']:6.1%} "
                f"{b['live_mean']:6.2f} {b['queue_max']:5d} "
                f"{b['queue_age_ms_max']:8.1f} {b['prefill_toks']:8d} "
                f"{b['decode_toks']:8d}")
            if has_shared:
                line += f" {max(0, b.get('shared_max', 0)):7d}"
            if has_spec:
                line += f" {b['acceptance_rate']:7.1%}"
            lines.append(line)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="utilization/bubble report from a flight-recorder dump")
    ap.add_argument("ring", nargs="+",
                    help="flight-recorder JSONL (engine."
                         "recorder.export_jsonl / watchdog bundle "
                         "ring.jsonl); several with --merge")
    ap.add_argument("--merge", action="store_true",
                    help="render the dumps (one per replica) as aligned "
                         "per-node utilization strips on a shared "
                         "timebase")
    ap.add_argument("--buckets", type=int, default=None,
                    help="timeline columns (default 40; 60 with --merge)")
    ap.add_argument("--top-gaps", type=int, default=5,
                    help="largest idle bubbles to list (default 5)")
    args = ap.parse_args(argv)
    if len(args.ring) > 1 and not args.merge:
        ap.error("multiple dumps need --merge")
    buckets = args.buckets if args.buckets is not None else (
        60 if args.merge else 40)
    try:
        dumps = [load_ring(path) for path in args.ring]
    except (OSError, json.JSONDecodeError) as exc:
        print(f"engine_timeline: {exc}", file=sys.stderr)
        return 2
    if args.merge:
        dumps = [(m, r) for m, r in dumps if r]
        if not dumps:
            print("engine_timeline: no dump holds records",
                  file=sys.stderr)
            return 2
        print(render_merge(merge_report(dumps, buckets)))
        return 0
    meta, records = dumps[0]
    if not records:
        print("engine_timeline: dump holds no records", file=sys.stderr)
        return 2
    report = timeline_report(records, buckets, args.top_gaps)
    print(render(report, meta.get("name", "")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
