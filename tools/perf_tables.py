"""Dense/sparse matrix-table performance harness.

Port of the reference ``TestDensePerf`` / ``TestSparsePerf`` drivers
(``Test/main.cpp:343-497`` in the Multiverso reference): a 1M x 50 float
matrix table, timed rounds of whole-table Get, %-sparse row Add, and Get
again, printing per-op wall times and the Dashboard dump at the end.

Usage:
    python tools/perf_tables.py [dense|sparse|device|lightlda]
                                [-rows=1000000] [-cols=50] [-rounds=10]
                                [-percent=1.0] [-workers=4] [-doc_words=2048]

``lightlda`` drives the sparse-matrix path the way LightLDA drove the
reference (BASELINE config 4): a 1M-row word-topic count table with
``workers`` simulated samplers, each round pushing zipf-distributed
touched-row count deltas (``add_rows`` with per-worker AddOptions — the
server-side dirty-bit update, ``src/table/sparse_matrix_table.cpp:200``)
and pulling only the rows OTHER workers dirtied since its last pull
(``get_dirty_rows`` — ``UpdateGetState``, ``:226``). Prints per-op times,
pushed/pulled row rates and the wire-compression ratio of the touched-row
representation vs a dense whole-table push.

``sparse`` adds only ``percent``%% of rows per round (the touched-row wire
path); ``dense`` adds the whole table. Both move data host<->device every
round, like the reference's user buffers. ``device`` times the jitted
update/lookup programs on pre-staged device arrays — the table-update
bandwidth the chip itself sustains, independent of the host link (on a
tunneled/remote device the host path measures the tunnel, not the table).
Runs on whatever devices the process sees (one real TPU chip, or CPU with
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import multiverso_tpu as mv
from multiverso_tpu.dashboard import Dashboard


def main(argv) -> int:
    mode = "dense"
    args = []
    for a in argv[1:]:
        if a in ("dense", "sparse", "device", "lightlda"):
            mode = a
        else:
            args.append(a)
    mv.define_int("rows", 1_000_000, "table rows")
    mv.define_int("cols", 50, "table cols")
    mv.define_int("rounds", 10, "timed rounds")
    mv.define_float("percent", 1.0, "rows touched per sparse add (%)")
    mv.define_int("workers", 4, "lightlda: simulated sampler workers")
    mv.define_int("doc_words", 2048, "lightlda: distinct words per push")
    mv.init(["perf"] + args)
    rows, cols = mv.get_flag("rows"), mv.get_flag("cols")
    rounds = mv.get_flag("rounds")

    if mode == "lightlda":
        return _lightlda(rows, cols, rounds)

    table = mv.create_table("matrix", rows, cols, name="perf_matrix")
    rng = np.random.default_rng(0)

    n_touch = max(1, int(rows * mv.get_flag("percent") / 100.0))

    # warm up the host-path jitted ops with the timed shapes (first compile
    # is not the steady state; row ops bucket by id-set size, so warm with
    # n_touch). The device mode warms its own programs inside pipelined()
    # and must not pay host-link round trips here.
    if mode == "dense":
        table.get()
        table.add(np.zeros((rows, cols), np.float32))
    elif mode == "sparse":
        table.get()
        warm_ids = np.arange(n_touch, dtype=np.int32)
        table.add_rows(warm_ids, np.zeros((n_touch, cols), np.float32))
        table.get_rows(warm_ids)

    def timed(label, fn, op_bytes):
        t0 = time.perf_counter()
        for _ in range(rounds):
            fn()
        dt = (time.perf_counter() - t0) / rounds
        print(f"{label:28s} {dt * 1e3:10.2f} ms/round "
              f"({op_bytes / 1e6 / dt:.0f} MB/s)")
        return dt

    print(f"[{mode}] matrix {rows}x{cols} float32 "
          f"({rows * cols * 4 / 1e6:.0f} MB), {rounds} rounds, "
          f"mesh {dict(mv.session().mesh.shape)}")

    table_bytes = rows * cols * 4

    if mode == "device":
        import jax
        import jax.numpy as jnp

        from multiverso_tpu.tables import _rowops
        from multiverso_tpu.tables.base import _option_scalars
        from multiverso_tpu.updaters import AddOption

        opt = _option_scalars(AddOption(), table.dtype)
        delta_dev = jax.device_put(
            rng.standard_normal((rows, cols)).astype(np.float32),
            table.sharding)

        def dev_add():
            table._data, table._ustate = table._apply_fn(
                table._data, table._ustate, delta_dev, *opt)

        ids = rng.choice(rows, size=n_touch, replace=False).astype(np.int32)
        size = _rowops.bucket_size(n_touch)
        padded_ids, rmask = _rowops.pad_ids(ids, n_touch, size)
        padded_vals = _rowops.pad_values(
            rng.standard_normal((n_touch, cols)).astype(np.float32),
            n_touch, size)
        ids_dev = jnp.asarray(padded_ids)
        vals_dev = jnp.asarray(padded_vals)
        mask_dev = jnp.asarray(rmask)

        def dev_add_rows():
            table._data, table._ustate = table._row_apply(
                table._data, table._ustate, ids_dev, vals_dev, mask_dev,
                *opt)

        last_gather = [None]

        def dev_get_rows():
            last_gather[0] = table._row_gather(table._data, ids_dev)

        def drain():
            """Force the queued chain: fetch a scalar that depends on the
            final state (block_until_ready alone can return before a
            remote/tunneled device has drained its dispatch queue)."""
            src = (last_gather[0] if last_gather[0] is not None
                   else table._data)
            return float(jnp.sum(src[0]))

        def pipelined(label, fn, op_bytes):
            """Queue ``rounds`` dispatches, sync once: measures device
            throughput with per-dispatch latency amortised (a remote/
            tunneled device adds ~100ms per synchronous round trip)."""
            fn()                         # compile
            drain()
            t0 = time.perf_counter()
            for _ in range(rounds):
                fn()
            drain()
            dt = (time.perf_counter() - t0) / rounds
            print(f"{label:34s} {dt * 1e3:10.2f} ms/round "
                  f"({op_bytes / 1e6 / dt:.0f} MB/s)")

        touched_bytes = n_touch * cols * 4
        print(f"touched rows per row-op: {n_touch}")
        pipelined("device add (whole table)", dev_add, table_bytes)
        pipelined(f"device add_rows ({mv.get_flag('percent')}% rows)",
                  dev_add_rows, touched_bytes)
        pipelined(f"device get_rows ({mv.get_flag('percent')}% rows)",
                  dev_get_rows, touched_bytes)
        Dashboard.display()
        mv.shutdown()
        return 0

    timed("get (whole table)", table.get, table_bytes)

    if mode == "dense":
        delta = rng.standard_normal((rows, cols)).astype(np.float32)
        timed("add (whole table)", lambda: table.add(delta), table_bytes)
    else:
        ids = rng.choice(rows, size=n_touch, replace=False).astype(np.int32)
        vals = rng.standard_normal((n_touch, cols)).astype(np.float32)
        touched_bytes = n_touch * cols * 4
        print(f"touched rows per add: {n_touch}")
        timed(f"add_rows ({mv.get_flag('percent')}% rows)",
              lambda: table.add_rows(ids, vals), touched_bytes)
        timed(f"get_rows ({mv.get_flag('percent')}% rows)",
              lambda: table.get_rows(ids), touched_bytes)

    timed("get (whole table, after)", table.get, table_bytes)

    Dashboard.display()
    mv.shutdown()
    return 0


def _lightlda(rows: int, cols: int, rounds: int) -> int:
    """LightLDA-shaped sparse workload (reference BASELINE config 4).

    Word-topic count table [vocab, topics]; per round each simulated worker
    pushes count deltas for a zipf "document batch" of distinct words and
    pulls the rows the OTHER workers dirtied — the filtered pull the
    reference implements with per-worker dirty bitmaps + SparseFilter
    (``src/table/sparse_matrix_table.cpp:145-309``).
    """
    import time as _time

    from multiverso_tpu.updaters import AddOption

    workers = mv.get_flag("workers")
    doc_words = mv.get_flag("doc_words")
    table = mv.create_table("matrix", rows, cols, name="word_topic",
                            is_sparse=True, num_sim_workers=workers)
    rng = np.random.default_rng(0)
    # zipf word law over the vocab, like a real corpus
    ranks = np.arange(1, rows + 1)
    probs = 1.0 / ranks
    probs /= probs.sum()

    print(f"[lightlda] word-topic {rows}x{cols} f32, {workers} workers, "
          f"{doc_words} words/push, {rounds} rounds, "
          f"mesh {dict(mv.session().mesh.shape)}")

    # pre-draw each worker/round's word set (host sampling is not the
    # thing under test) + topic count deltas (+1 new topic / -1 old topic)
    pushes = []
    for r in range(rounds):
        per_worker = []
        for w in range(workers):
            ids = np.unique(rng.choice(rows, size=doc_words, p=probs)
                            ).astype(np.int32)
            vals = np.zeros((ids.size, cols), np.float32)
            new_t = rng.integers(0, cols, ids.size)
            old_t = rng.integers(0, cols, ids.size)
            vals[np.arange(ids.size), new_t] += 1.0
            vals[np.arange(ids.size), old_t] -= 1.0
            per_worker.append((ids, vals))
        pushes.append(per_worker)

    # warm the bucketed row ops
    ids0, vals0 = pushes[0][0]
    table.add_rows(ids0, np.zeros_like(vals0), AddOption(worker_id=0))
    for w in range(workers):
        table.get_dirty_rows(w)

    def run_blocking():
        """Reference LightLDA loop shape: push, then a BLOCKING filtered
        pull per worker — every pull pays a full host<->device round
        trip before the next worker proceeds."""
        pushed = pulled = 0
        push_t = pull_t = 0.0
        t0 = _time.perf_counter()
        for r in range(rounds):
            for w in range(workers):
                ids, vals = pushes[r][w]
                t1 = _time.perf_counter()
                table.add_rows(ids, vals, AddOption(worker_id=w))
                push_t += _time.perf_counter() - t1
                pushed += ids.size
            for w in range(workers):
                t1 = _time.perf_counter()
                dirty_ids, dirty_rows = table.get_dirty_rows(w)
                pull_t += _time.perf_counter() - t1
                pulled += dirty_ids.size
        return _time.perf_counter() - t0, push_t, pull_t, pushed, pulled

    def run_pipelined():
        """Reference ``GetPipelineTable`` pattern (``ps_model.cpp:236``)
        on :class:`parallel.PipelinedGetter` (the ``ASyncBuffer``
        double-buffer): round r's pulls run on background threads while
        round r+1's pushes dispatch, and the workers' pulls overlap each
        other — the host-link round trips that dominate the blocking
        loop ride concurrently."""
        from multiverso_tpu.parallel import PipelinedGetter

        getters = [PipelinedGetter(table.get_dirty_rows)
                   for _ in range(workers)]
        pushed = pulled = 0
        t0 = _time.perf_counter()
        for w in range(workers):                   # round 0 pushes
            ids, vals = pushes[0][w]
            table.add_rows(ids, vals, AddOption(worker_id=w))
            pushed += ids.size
        for w in range(workers):                   # start round 0 pulls
            getters[w].prime(w)
        for r in range(1, rounds):
            for w in range(workers):               # overlaps r-1 pulls
                ids, vals = pushes[r][w]
                table.add_rows(ids, vals, AddOption(worker_id=w))
                pushed += ids.size
            for w in range(workers):               # collect r-1, start r
                dirty_ids, _ = getters[w].get(w)
                pulled += dirty_ids.size
        for w in range(workers):                   # collect the last round
            dirty_ids, _ = getters[w].get()
            pulled += dirty_ids.size
        return _time.perf_counter() - t0, pushed, pulled

    total, push_t, pull_t, pushed, pulled = run_blocking()
    p_total, p_pushed, p_pulled = run_pipelined()

    dense_bytes = rows * cols * 4
    # measured mean rows per push (unique zipf draws < doc_words)
    rows_per_push = pushed / (rounds * workers)
    push_bytes = rows_per_push * (cols * 4 + 4)   # touched rows + ids
    print(f"push: {pushed} rows in {push_t:.2f}s "
          f"({pushed / max(push_t, 1e-9):,.0f} rows/s)")
    print(f"filtered pull: {pulled} dirty rows in {pull_t:.2f}s "
          f"({pulled / max(pull_t, 1e-9):,.0f} rows/s)")
    print(f"wire: touched-row push = {push_bytes / 1e6:.1f} MB vs dense "
          f"{dense_bytes / 1e6:.0f} MB ({dense_bytes / push_bytes:,.0f}x "
          f"smaller)")
    print(f"total (blocking): {rounds} rounds x {workers} workers in "
          f"{total:.2f}s ({rounds * workers / total:.1f} "
          f"worker-iterations/s)")
    # background pulls may coalesce two rounds' dirty rows (the pull races
    # the next round's pushes); report both pulled counts so the speedup
    # can be read against equal work — a large delta would mean the win is
    # partly "fewer rows moved", not overlap
    work_delta = abs(pulled - p_pulled) / max(pulled, 1)
    print(f"total (pipelined): {rounds} rounds x {workers} workers in "
          f"{p_total:.2f}s ({rounds * workers / p_total:.1f} "
          f"worker-iterations/s) — {total / p_total:.2f}x vs blocking "
          f"(double-buffered get_dirty_rows; pulled {p_pulled} rows vs "
          f"blocking {pulled}, {work_delta * 100:.1f}% work delta"
          f"{', NOT comparable' if work_delta > 0.05 else ''})")
    # correctness probe: global count conservation (every +1 has a -1,
    # so the table sums to ~0)
    probe = float(np.sum(table.get_rows(np.arange(0, rows,
                                                  max(rows // 4096, 1)))))
    print(f"sampled count-conservation probe: {probe:+.1f}")
    Dashboard.display()
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
