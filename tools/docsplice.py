"""Marker-delimited block splicing shared by the measured-docs tools
(`scaling_bench`, `dcn_bench`, `embedding_quality`): each tool owns a
``<!-- name:begin -->…<!-- name:end -->`` block in a docs file and
re-renders ONLY that block on re-runs, so regenerated measurements never
clobber the surrounding prose."""

from __future__ import annotations

from typing import Optional


def splice(path: str, block: str, begin: str, end: str,
           anchor: Optional[str] = None) -> None:
    """Replace the ``begin``..``end`` region of ``path`` with ``block``
    (which must itself carry the markers). First insertion goes before
    ``anchor`` when given, else appends."""
    with open(path) as f:
        text = f.read()
    if begin in text and end in text and text.index(begin) < text.index(end):
        text = (text[:text.index(begin)] + block
                + text[text.index(end) + len(end):])
    elif anchor is not None and anchor in text:
        text = text.replace(anchor, block + "\n\n" + anchor)
    else:
        text = text.rstrip() + "\n\n" + block + "\n"
    with open(path, "w") as f:
        f.write(text)
