"""Closed-loop serving load generator -> one JSON line.

Drives the three serving workloads (word2vec neighbor lookup, logreg
predict, LM greedy decode) through ``serving.InferenceServer`` with N
closed-loop clients each (issue -> wait -> issue; sheds back off briefly),
and emits ONE JSON line with qps / p50 / p99 / shed_rate per workload —
the serving counterpart of bench.py's training line, so BENCH rounds can
track both sides of the train/serve stack.

Each workload is also measured with the scheduler degraded to batch=1
(same jitted workload, bucket set {1}) to price micro-batching itself:
``speedup_batched`` is saturated batched qps over batch=1 qps.

The LM decode path is additionally A/B'd on a mixed-length arrival
trace (zipf output lengths, Poisson arrivals): the continuous-batching
``DecodeEngine`` (slot KV cache, iteration-level scheduling) vs the
static micro-batched ``LMGreedyDecode`` path, both serving the same
trace. The static path locks every co-batched request through a full
``max_new`` generation (head-of-line blocking), so on mixed lengths the
engine's useful-tokens/sec should win by >= 2x (``speedup_engine``).

A second decode A/B (``lm_chunked_prefill``) prices ADMISSION: the same
engine on a long-prompt trace with chunked (``prefill_token_budget``)
vs monolithic whole-prompt prefill — chunking must cut ITL p99 by >= 2x
(``itl_p99_speedup``) while useful tokens/sec stays within ~10%
(``tokens_per_s_ratio``); ``tools/bench_compare.py`` diffs two bench
lines and gates regressions on exactly these numbers.

A third decode A/B (``lm_paged_kv``) prices the CACHE LAYOUT: paged
(block pool + block tables) vs contiguous slot strips at an EQUAL
KV-bytes budget on a zipf/Poisson burst trace. The paged side must
hold >= 2x the concurrent sequences (``capacity_seqs``) or deliver
>= 1.5x useful tok/s at a lower shed rate; ``kv_bytes_per_seq`` and
``capacity_seqs`` ride the bench_compare gate with direction-aware
thresholds.

A fourth decode A/B (``lm_prefix_cache``) prices CONTENT REUSE: the
same paged engine, same pool bytes, serving a shared-prefix zipf trace
(a few hot system prefixes + unique tails) with content-addressed
prefix caching on vs off. The cached side must hold strictly more
concurrent sequences (``capacity_seqs``) and skip most of its prefill
tokens (``prefill_tokens_saved``, ``prefix_hit_rate`` — all three ride
the bench_compare gate); saturated tok/s and TTFT columns archive as
gate-exempt ``_info`` per the 2-CPU noise-floor rule.

A fifth decode A/B (``lm_spec_decode``) prices SPECULATION: the same
paged engine, same pool, serving a repetitive-tail trace (motif-tiled
prompts whose greedy continuations cycle) with n-gram prompt-lookup
speculative decoding (``spec_k=4``) vs the plain one-token engine.
Outputs are token-identical by construction; the win is amortization —
per-iteration fixed costs divide over up to K+1 emitted tokens — so
useful tok/s and ``accepted_per_step`` ride the bench_compare gate
while ``acceptance_rate`` (a property of the trace, not the code)
archives as ``_info``.

A sixth decode A/B (``lm_sharded_decode``) prices the DECODE MESH:
tp=2 tensor-parallel decode (heads/MLP/K-V pools sharded, params
resharded once per pin, programs compiled once against matched
shardings) vs the tp=1 single-device replica, same model and pool
bytes. Gated: ``kv_bytes_per_device`` (down) and
``decode_step_retraces`` (zero-baseline — the PR 2 ~10x partitioner
drag must stay out of the hot loop); tok/s and step wall archive as
``_info``. Runs only when >= 2 devices are visible (``--devices N`` /
the multichip dryrun harness) and archives a skip marker otherwise.

The black box stays ON for the whole bench: the per-engine flight
recorder (always-on iteration ring), the stall/leak watchdog (a clean
bench must report ZERO trips — ``observability.watchdog_trips`` rides
the ``bench_compare`` gate), and tail-sampled tracing
(``-trace_tail``: only SLO-breaching/errored/1-in-N request trees are
retained, which is what makes leaving ``-trace`` on affordable). A
fourth A/B (``observability``) prices that posture: the same decode
trace served with tracing disabled vs tail-sampled tracing on, both
archived as gate-exempt ``_info`` columns — on the 2-CPU container the
delta must sit inside the scheduling-noise floor.

An ``obs_plane`` A/B prices the FLEET observability plane the same
way: the warm engine serves the same trace with no agents vs a REAL
two-rank plane at 100 ms reports — a wire publisher shipping full
reports over localhost p2p sockets to a collector rank that drains,
acks and merges. tok/s columns are ``_info``; the publisher's
``obs_dropped_reports`` rides the bench_compare zero-baseline gate — a
drop with a live, acking collector means the bounded-window/ack
machinery broke, a bug.

An ``accounting`` A/B prices and PROVES the per-tenant cost ledger
(``-cost_ledger``): the same warm engine serves the trace with the
ledger detached vs attached (tok/s ``_info``), then a 3-tenant
round-robin tagged pass under a real 2-rank obs plane. Gated:
``accounting_drift`` at ZERO (the conservation identity — per-tenant
sums reconcile with the engine's own counters to the token) and the
one-trace/zero-retrace invariants on the ledger-enabled engine; the
collector's ``tenant_rows()`` (the ``opscenter --tenants`` surface)
must render all 3 tenants.

An ``lm_fleet_chaos`` A/B prices FAILURE RECOVERY: a 3-replica fleet
(real decode engines on the real ``mvserve`` wire behind the
``FleetRouter``) serves one mixed-length trace fault-free, then again
under a seeded ``kill_at_request`` chaos plan that drops one replica
mid-trace. Gated: ``requests_lost`` and
``fleet_redispatch_output_mismatches`` at ZERO (every accepted request
resolves, and replayed outputs are bit-identical to the fault-free
run — deterministic greedy decode), ``recovery_time_s`` (death
flagged -> first replayed completion, lower-better), and the
fault-free aggregate ``fleet_tokens_per_s``.

An ``lm_disagg`` A/B prices DISAGGREGATION: the same two engines at
equal hardware serve one mixed long-prompt / short-interactive trace
as a 2-replica unified fleet, then as one ``prefill`` + one ``decode``
replica behind the router's two-stage dispatch with the KV-block
transfer plane (``serving/kv_transfer.py``). Gated:
``output_mismatches`` at ZERO (splice-at-arrival is bit-exact),
``itl_p99_ratio`` (unified over disagg decode ITL p99, higher-better),
the deterministic ``kv_bytes_moved`` (every long prompt distinct,
lower-better), ``xfer_dedup_hit_rate`` (higher-better) and
``dedup_repeat_kv_bytes_moved`` (~0: re-submitting already-shipped
prompts moves no bytes — dedup-on-arrival plus the router's shipped
book). TTFT p99 and tok/s per leg archive as ``_info``
(docs/SERVING.md "Disaggregated prefill/decode").

An ``lm_trainer_chaos`` A/B prices DURABILITY (the training half's
recovery, PR 14): the same deterministic add-and-publish stream runs
fault-free and under a seeded ``kill_trainer_at_publish`` mid-stream,
then checkpoint+WAL recovery, an epoch-fenced STATE rebase over the
real ``mvparam`` wire, and one staged zombie publish. Gated:
``updates_lost`` and ``output_mismatches`` at ZERO (every acknowledged
add survives the kill bit-identically),
``epoch_fence_rejections_unexpected`` at ZERO, and
``trainer_recovery_time_s`` (restart begin -> subscriber re-converged,
lower-better); the staleness peak and WAL replay volume are ``_info``
(docs/DISTRIBUTED.md "Durability").

The JSON line also archives the FULL ``Dashboard.snapshot()`` (every
Monitor/Histogram/Gauge/Counter/SLO), so a bench run preserves the
complete instrument state — not just the hand-picked fields above —
and ``--trace FILE`` additionally writes a Chrome/Perfetto trace JSON
(retained spans merged with the flight recorder's counter tracks) so a
slow bench percentile can be explained request by request
(docs/OBSERVABILITY.md). ``--flight FILE`` dumps the observability
engine's iteration ring for ``tools/engine_timeline.py``, and
``--debug_dump_dir DIR`` passes through to the watchdog (a trip during
the bench then leaves a diagnostic bundle, not just a counter).

Usage::

    JAX_PLATFORMS=cpu python tools/serving_bench.py [-duration 2.0]
        [-clients 32] [-quick] [--trace /tmp/serve_trace.json]
        [--flight /tmp/ring.jsonl] [--debug_dump_dir /tmp/dumps]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _closed_loop(server, model: str, payload_fn, duration_s: float,
                 clients: int) -> dict:
    """N clients issuing blocking predicts for ``duration_s``; returns
    qps/latency/shed stats measured OVER THE LOOP (warmup excluded)."""
    from multiverso_tpu.serving import OverloadedError

    stop = time.monotonic() + duration_s
    counts = [0] * clients
    sheds = [0] * clients

    def client(ix: int) -> None:
        rng = np.random.default_rng(ix)
        while time.monotonic() < stop:
            try:
                server.predict(model, payload_fn(rng), timeout_s=60.0)
                counts[ix] += 1
            except OverloadedError:
                sheds[ix] += 1
                time.sleep(0.0005)          # shed: back off, retry

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120)
    elapsed = time.monotonic() - t0
    done, shed = sum(counts), sum(sheds)
    stats = server.stats(model)
    return {
        "qps": round(done / elapsed, 1),
        "p50_ms": round(stats["p50_ms"], 3),
        "p99_ms": round(stats["p99_ms"], 3),
        "shed_rate": round(shed / (done + shed), 4) if done + shed else 0.0,
        "completed": done,
    }


def _decode_trace(n: int, seed: int, max_prompt: int, max_new_cap: int,
                  mean_gap_s: float, vocab: int, min_new: int = 0):
    """Mixed-length arrival trace: Poisson arrivals (exponential gaps),
    uniform prompt lengths, zipf-distributed output lengths clipped to
    the cap — most requests want a few tokens, a heavy tail wants many.
    ``min_new`` floors the generation lengths (the capacity A/B wants
    sequences that LIVE for a while, so concurrency can build)."""
    rng = np.random.default_rng(seed)
    trace, t = [], 0.0
    for _ in range(n):
        t += float(rng.exponential(mean_gap_s))
        plen = int(rng.integers(1, max_prompt + 1))
        prompt = rng.integers(1, vocab, plen).astype(np.int32)
        n_new = int(min(max_new_cap, min_new + rng.zipf(1.6)))
        trace.append((t, prompt, n_new))
    return trace


def _admission_pulse_trace(cycles: int, cycle_s: float, n_wit: int,
                           n_long: int, max_prompt: int, cap: int,
                           min_new: int, vocab: int, seed: int,
                           pulse_gap_s: float = 0.08):
    """The chunked-prefill A/B trace: witness pulses + long-prompt bursts.

    Each cycle opens with ``n_wit`` SHORT prompts (<= 8 tokens) whose
    zipf generations (floored at ``min_new`` so they outlive the burst)
    are mid-decode when, ``pulse_gap_s`` later, ``n_long`` full-length
    prompts arrive at once. A monolithic engine admits that burst as one
    fused whole-prompt prefill wave — every witness's next token waits
    the whole wave out, which is exactly the ITL spike a per-iteration
    prefill budget bounds. Cycles are spaced so the engine drains in
    between (the burst hits free slots, keeping the wave — and the A/B
    contrast — deterministic rather than occupancy-dependent).
    """
    rng = np.random.default_rng(seed)
    out = []
    for k in range(cycles):
        t0 = k * cycle_s
        for _ in range(n_wit):
            plen = int(rng.integers(1, 9))
            out.append((t0, rng.integers(1, vocab, plen).astype(np.int32),
                        int(min(cap, min_new + rng.zipf(1.6)))))
        for _ in range(n_long):
            out.append((t0 + pulse_gap_s,
                        rng.integers(1, vocab, max_prompt).astype(np.int32),
                        int(min(cap, min_new + rng.zipf(1.6)))))
    out.sort(key=lambda r: r[0])
    return out


def _play_decode_trace(server, model: str, trace, per_request_max_new: bool,
                       tenants=None):
    """Open-loop arrival playback; returns (results, elapsed_s).
    ``tenants`` (a name sequence) tags requests round-robin with a
    ``tenant`` payload key — the cost ledger's attribution id."""
    from multiverso_tpu.serving import OverloadedError

    futs = []
    t0 = time.monotonic()
    for i, (at, prompt, n_new) in enumerate(trace):
        delay = at - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        payload = ({"prompt": prompt, "max_new": n_new}
                   if per_request_max_new else prompt)
        if tenants:
            if not isinstance(payload, dict):
                payload = {"prompt": payload}
            payload["tenant"] = tenants[i % len(tenants)]
        while True:
            try:
                futs.append(server.submit(model, payload))
                break
            except OverloadedError as exc:
                # the retriable hint IS the retry policy: a permanent
                # shed (prompt + max_new can never fit the pool) would
                # spin forever — that's a bench-geometry bug, surface
                # it instead of string-matching `what`
                if not getattr(exc, "retriable", True):
                    raise
                time.sleep(0.001)
    results = [f.result(timeout=300) for f in futs]
    return results, time.monotonic() - t0


def _decode_ab(server, lm_model, quick: bool) -> dict:
    """Engine-vs-static A/B on one mixed-length trace.

    Useful tokens are the per-request zipf lengths for BOTH paths: the
    engine generates exactly that many (per-request ``max_new``); the
    static path must run its full compiled ``max_new`` for every request
    and the surplus is discarded — that surplus, plus batch-drain
    admission stalls, is precisely the head-of-line cost being priced.
    """
    from multiverso_tpu.serving import LMGreedyDecode

    max_prompt, cap = 8, 96
    n = 32 if quick else 96
    trace = _decode_trace(n, seed=7, max_prompt=max_prompt, max_new_cap=cap,
                          mean_gap_s=0.0005, vocab=lm_model.config.vocab_size)
    useful = sum(n_new for _, _, n_new in trace)

    # one prompt bucket (prompts here are all <= 8): admission compiles
    # per (batch bucket, prompt bucket), so the warmable trace set stays
    # at 4 batch buckets x 1 prompt bucket + 1 fused step
    engine = server.register_decoder(
        "lm_engine", lm_model, slots=8, max_prompt=max_prompt, max_new=cap,
        max_queue=max(64, n), prompt_buckets=(max_prompt,))
    static = LMGreedyDecode(lm_model, max_prompt=max_prompt, max_new=cap)
    static._warm_payload = lambda: np.ones(4, np.int32)
    server.register("lm_static", static, max_batch=8, deadline_ms=4.0,
                    max_queue=max(64, n), buckets=(1, 8))

    # warm both paths outside the timed trace (engine: every admission
    # bucket combo + the fused step; static: both batch buckets)
    engine.warmup()
    _play_decode_trace(server, "lm_engine",
                       [(0.0, np.ones(4, np.int32), 2)] * 4, True)
    _warm(static, server._entry("lm_static").manager, (1, 8))
    engine.reset_stats()

    _, eng_elapsed = _play_decode_trace(server, "lm_engine", trace, True)
    eng_stats = engine.stats()
    _, static_elapsed = _play_decode_trace(server, "lm_static", trace, False)
    static_stats = server.stats("lm_static")

    eng_tps = useful / eng_elapsed
    static_tps = useful / static_elapsed
    return {
        "requests": n,
        "useful_tokens": useful,
        "tokens_per_s": round(eng_tps, 1),
        "ttft_p50_ms": round(eng_stats["ttft_p50_ms"], 3),
        "ttft_p99_ms": round(eng_stats["ttft_p99_ms"], 3),
        "itl_p50_ms": round(eng_stats["itl_p50_ms"], 3),
        "slot_occupancy": round(eng_stats["slot_occupancy"], 3),
        "step_traces": eng_stats["step_traces"],
        "tokens_per_s_static": round(static_tps, 1),
        # the static path's first token only exists when the whole batch
        # drains: its reply latency IS its TTFT
        "ttft_p50_ms_static": round(static_stats["p50_ms"], 3),
        "ttft_p99_ms_static": round(static_stats["p99_ms"], 3),
        "speedup_engine": (round(eng_tps / static_tps, 2)
                           if static_tps else float("inf")),
    }


def _chunked_prefill_ab(server, lm_model, quick: bool) -> dict:
    """Chunked-vs-monolithic admission A/B on the pulse/burst trace.

    Same engine, same model, same arrival trace — the only difference is
    the admission schedule: ``prefill_token_budget``-sized chunks
    interleaved one per iteration vs one synchronous whole-prompt
    prefill wave. The number that must move is **ITL p99**: a monolithic
    long-prompt burst stalls every in-flight generation for the whole
    fused wave (~one ``prefill[batch_bucket, max_prompt]`` wall), a
    chunked one for at most one budget-sized chunk. Useful tokens/sec
    must NOT move (same FLOPs, different schedule) —
    ``tokens_per_s_ratio`` prices what the chunking costs.

    Measured on the CI container (2 CPUs; its scheduling-noise floor
    puts ~45-60 ms on ANY schedule's p99): chunked ITL p99 ~55-72 ms vs
    monolithic ~155-195 ms = **2.4-3.6x**, at 0.92-0.96x useful tok/s.
    """
    max_prompt, cap, min_new, budget = 384, 40, 20, 96
    cycles = 3 if quick else 5
    trace = _admission_pulse_trace(
        cycles=cycles, cycle_s=1.2, n_wit=2, n_long=5,
        max_prompt=max_prompt, cap=cap, min_new=min_new,
        vocab=lm_model.config.vocab_size, seed=11)
    useful = sum(n_new for _, _, n_new in trace)

    rows = {}
    for label, b in (("chunked", budget), ("monolithic", 0)):
        engine = server.register_decoder(
            f"lm_{label}", lm_model, slots=8, max_prompt=max_prompt,
            max_new=cap, max_queue=256, prompt_buckets=(8, max_prompt),
            prefill_token_budget=b)
        engine.warmup()
        _play_decode_trace(server, f"lm_{label}",
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        _, elapsed = _play_decode_trace(server, f"lm_{label}", trace, True)
        s = engine.stats()
        rows[label] = {
            "tokens_per_s": round(useful / elapsed, 1),
            "itl_p50_ms": round(s["itl_p50_ms"], 3),
            "itl_p99_ms": round(s["itl_p99_ms"], 3),
            "ttft_p50_ms": round(s["ttft_p50_ms"], 3),
            "ttft_p99_ms": round(s["ttft_p99_ms"], 3),
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
        }
    ch, mono = rows["chunked"], rows["monolithic"]
    return {
        "requests": len(trace),
        "useful_tokens": useful,
        "prefill_token_budget": budget,
        "chunked": ch,
        "monolithic": mono,
        "itl_p99_speedup": (round(mono["itl_p99_ms"] / ch["itl_p99_ms"], 2)
                            if ch["itl_p99_ms"] else float("inf")),
        "tokens_per_s_ratio": (round(ch["tokens_per_s"]
                                     / mono["tokens_per_s"], 3)
                               if mono["tokens_per_s"] else float("inf")),
    }


def _paged_kv_ab(server, lm_model, quick: bool) -> dict:
    """Paged-vs-contiguous KV cache at an EQUAL device-KV-bytes budget.

    Both engines serve the same zipf/Poisson burst trace with the same
    model and the same KV memory: the contiguous side gets
    ``contig_slots`` worst-case ``[T, D]`` strips, the paged side the
    byte-equivalent block pool (``contig_slots * T / block_size`` usable
    blocks, +1 scratch block of overhead) spread over 4x the slots.
    Short sequences hold only their reservation, so the paged engine
    packs several times more CONCURRENT sequences into the identical
    bytes — ``capacity_seqs`` (peak live sequences) and
    ``kv_bytes_per_seq`` are the headline metrics, with useful tok/s
    and shed rate saying what the extra concurrency buys. Throughput/
    capacity-led by design: on the 2-CPU CI container ITL percentiles
    sit on the ~50 ms scheduling-noise floor, so the latency columns
    here are informational only (and this section still runs before the
    closed-loop phase fills the box with client threads).
    """
    from multiverso_tpu.serving import kv_bytes_per_block

    max_prompt, cap, block_size = 32, 64, 8
    T = max_prompt + cap
    contig_slots = 4
    pool_blocks = contig_slots * (T // block_size)   # byte-equal budget
    kv_bytes = pool_blocks * kv_bytes_per_block(
        lm_model.config.n_layers, lm_model.config.d_model, block_size)
    n = 32 if quick else 64
    # near-simultaneous arrivals of long-lived generations: offered
    # concurrency far exceeds the contiguous slot count, so the A/B
    # measures what the layouts do when the KV budget is the bottleneck
    trace = _decode_trace(n, seed=13, max_prompt=max_prompt,
                          max_new_cap=cap, mean_gap_s=0.001,
                          vocab=lm_model.config.vocab_size, min_new=16)
    useful = sum(n_new for _, _, n_new in trace)

    rows = {}
    for label, kv in (("paged", dict(slots=4 * contig_slots,
                                     kv_block_size=block_size,
                                     kv_pool_blocks=pool_blocks)),
                      ("contiguous", dict(slots=contig_slots,
                                          kv_block_size=0))):
        engine = server.register_decoder(
            f"lm_pg_{label}", lm_model, max_prompt=max_prompt, max_new=cap,
            max_queue=24, prompt_buckets=(max_prompt,), **kv)
        engine.warmup()
        _play_decode_trace(server, f"lm_pg_{label}",
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        _, elapsed = _play_decode_trace(server, f"lm_pg_{label}", trace,
                                        True)
        s = engine.stats()
        cap_seqs = max(1, s["peak_live_seqs"])
        # only the CAPACITY metrics carry gate-matching names here; the
        # throughput/latency/shed columns are measured-but-informational
        # (the "_info" suffix keeps them out of bench_compare's
        # direction rules): both engines run this burst saturated on a
        # 2-CPU box whose step wall is ~linear in slots, so those
        # numbers swing 2x run-to-run — gating them would make the
        # standing gate flap on scheduler noise
        rows[label] = {
            "capacity_seqs": s["peak_live_seqs"],
            "kv_bytes_budget": kv_bytes,
            "kv_bytes_per_seq": round(kv_bytes / cap_seqs, 1),
            "tokens_per_s_info": round(useful / elapsed, 1),
            "shed_rate_info": round(s["shed_rate"], 4),
            "slot_occupancy": round(s["slot_occupancy"], 3),
            "ttft_p50_ms_info": round(s["ttft_p50_ms"], 3),
            "itl_p99_ms_info": round(s["itl_p99_ms"], 3),
            "step_traces": s["step_traces"],
        }
        if s["kv_block_size"]:                   # archive block-pool stats
            rows[label].update({k: s[k] for k in (
                "kv_block_size", "kv_pool_blocks", "kv_blocks_free",
                "kv_blocks_live", "block_allocs", "block_frees")})
    pg, ct = rows["paged"], rows["contiguous"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "paged": pg,
        "contiguous": ct,
        "capacity_ratio": (round(pg["capacity_seqs"]
                                 / ct["capacity_seqs"], 2)
                           if ct["capacity_seqs"] else float("inf")),
        "tokens_per_s_speedup_info": (
            round(pg["tokens_per_s_info"] / ct["tokens_per_s_info"], 2)
            if ct["tokens_per_s_info"] else float("inf")),
    }


def _overload_ab(server, lm_model, quick: bool) -> dict:
    """Overload-graceful serving A/B: FIFO + worst-case reservation vs
    priority + optimistic admission + preemption-with-recompute, on the
    SAME model, pool and burst trace (near-simultaneous long-lived
    generations whose live KV demand is ~2x the pool).

    The baseline leg reserves ``prompt + max_new`` up front, so the
    pool serializes it to ~3 concurrent sequences; the candidate leg
    reserves prompt blocks only, packs the slots, and preempts under
    growth pressure — ``capacity_seqs`` is the packing headline
    (gated), and the hard invariants ride zero-baseline gates:
    ``preempt_output_mismatches`` (every preempted-and-resumed
    generation must be bit-identical to the FIFO leg's un-preempted
    output of the same request — deterministic greedy decode),
    ``starved_requests`` (every accepted request resolves) and
    ``deadline_drops`` (deadlines here are sized to be met; a drop
    means scheduling broke, not traffic). Wall-clock numbers and the
    per-class p99 latencies archive as ``_info`` per the 2-CPU noise
    rule — on a box where the step wall is ~linear in live slots,
    packing more sequences trades per-token speed for capacity, and
    gating tok/s would flap."""
    from multiverso_tpu.serving import OverloadedError

    max_prompt, cap, block_size, min_new = 16, 48, 8, 24
    pool_blocks = 24     # worst case ceil((16+48)/8) = 8 blocks/request
    n = 15 if quick else 24
    rng = np.random.default_rng(17)
    arrivals, t = [], 0.0
    for i in range(n):
        t += float(rng.exponential(0.002))
        plen = int(rng.integers(4, max_prompt + 1))
        prompt = rng.integers(1, lm_model.config.vocab_size,
                              plen).astype(np.int32)
        n_new = int(min(cap, min_new + rng.zipf(1.6)))
        arrivals.append((t, prompt, n_new, int(i % 3)))   # tenant class
    useful = sum(r[2] for r in arrivals)

    rows: dict = {}
    outputs: dict = {}
    for label, preempt in (("fifo", False), ("preempt", True)):
        model = f"lm_ov_{label}"
        engine = server.register_decoder(
            model, lm_model, slots=12, max_prompt=max_prompt,
            max_new=cap, max_queue=max(64, n), kv_block_size=block_size,
            kv_pool_blocks=pool_blocks, preempt=preempt)
        engine.warmup()
        _play_decode_trace(server, model,
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        done_at: dict = {}
        futs = []
        t0 = time.monotonic()
        for i, (at, prompt, n_new, prio) in enumerate(arrivals):
            delay = at - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            payload = {"prompt": prompt, "max_new": n_new}
            if preempt:
                # the candidate leg exercises the whole surface:
                # 3 tenant classes + a deadline sized to be MET (the
                # zero-baseline deadline_drops gate is then non-vacuous
                # — any drop is the scheduler's fault, not the trace's)
                payload["priority"] = prio
                payload["deadline_s"] = 120.0
            while True:
                try:
                    fut = server.submit(model, payload)
                    break
                except OverloadedError as exc:
                    if not getattr(exc, "retriable", True):
                        raise
                    time.sleep(0.001)
            fut.add_done_callback(
                lambda f, i=i: done_at.__setitem__(i, time.monotonic()))
            futs.append((i, fut))
        outs: dict = {}
        starved = 0
        for i, fut in futs:
            try:
                outs[i] = np.asarray(fut.result(timeout=300)["result"])
            except Exception:
                starved += 1
        elapsed = time.monotonic() - t0
        outputs[label] = outs
        s = engine.stats()
        by_class: dict = {}
        for i, (at, _, _, prio) in enumerate(arrivals):
            if i in done_at:
                by_class.setdefault(prio, []).append(
                    (done_at[i] - (t0 + at)) * 1e3)
        row = {
            "capacity_seqs": s["peak_live_seqs"],
            "starved_requests": starved,
            "deadline_drops": s["deadline_drops"],
            "preemptions_info": s["preemptions"],
            "preempted_info": s["preempted"],
            "tokens_per_s_info": round(useful / elapsed, 1),
            "slot_occupancy": round(s["slot_occupancy"], 3),
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
        }
        for prio, lats in sorted(by_class.items()):
            row[f"lat_p99_class{prio}_ms_info"] = round(
                float(np.percentile(lats, 99)), 1)
        rows[label] = row
    # the recompute invariant, diffed request-by-request: the preempt
    # leg's outputs must equal the FIFO leg's (same prompts, same
    # pinned params, deterministic greedy — preemption changes the
    # SCHEDULE, never the tokens)
    mismatches = sum(
        1 for i in range(n)
        if i in outputs["fifo"] and i in outputs["preempt"]
        and not np.array_equal(outputs["fifo"][i], outputs["preempt"][i]))
    pre, fifo = rows["preempt"], rows["fifo"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "kv_pool_blocks": pool_blocks,
        "fifo": fifo,
        "preempt": pre,
        "preempt_output_mismatches": mismatches,
        "capacity_ratio": (round(pre["capacity_seqs"]
                                 / fifo["capacity_seqs"], 2)
                           if fifo["capacity_seqs"] else float("inf")),
    }


def _prefix_cache_ab(server, lm_model, quick: bool) -> dict:
    """Prefix-cache A/B: cache on vs off at EQUAL pool bytes on a
    shared-prefix zipf trace.

    The trace models production prompt traffic: a small set of long
    system prefixes with zipf popularity (most arrivals reuse the
    hottest one), each followed by a short unique tail, generating
    long-lived zipf outputs. Both engines get the IDENTICAL block pool;
    the only difference is ``prefix_cache``. With the cache on, the
    shared prefix occupies its blocks ONCE (refcounted) and every later
    arrival splices them instead of re-prefilling — so at a pool sized
    for ~2.5 uncached reservations, the cached side packs several times
    more CONCURRENT sequences (``capacity_seqs``) and skips the bulk of
    its prefill tokens (``prefill_tokens_saved``). Those two (plus
    ``prefix_hit_rate``) are the gated, capacity-led headline numbers;
    tok/s and TTFT columns are ``_info`` — the 2-CPU container's ~50 ms
    scheduling-noise floor makes latency columns flap. Four distinct
    prefixes against a pool that caches at most three keeps the
    eviction path (``prefix_evictions``) exercised, not just measured.
    """
    block_size = 8
    prefix_len, tail_max, cap, min_new = 64, 8, 24, 12
    max_prompt = prefix_len + tail_max
    pool_blocks = 30         # ~2.5 uncached 12-block reservations
    n = 24 if quick else 48
    vocab = lm_model.config.vocab_size
    rng = np.random.default_rng(17)
    prefixes = [rng.integers(1, vocab, prefix_len).astype(np.int32)
                for _ in range(4)]
    trace, t = [], 0.0
    for _ in range(n):
        t += float(rng.exponential(0.002))
        head = prefixes[min(int(rng.zipf(1.8)) - 1, len(prefixes) - 1)]
        tail = rng.integers(1, vocab,
                            int(rng.integers(1, tail_max + 1))).astype(
            np.int32)
        n_new = int(min(cap, min_new + rng.zipf(1.6)))
        trace.append((t, np.concatenate([head, tail]), n_new))
    useful = sum(n_new for _, _, n_new in trace)

    rows = {}
    for label, on in (("cache_on", True), ("cache_off", False)):
        engine = server.register_decoder(
            f"lm_pc_{label}", lm_model, slots=12, max_prompt=max_prompt,
            max_new=cap, max_queue=max(64, n),
            prompt_buckets=(max_prompt,), kv_block_size=block_size,
            kv_pool_blocks=pool_blocks, prefill_token_budget=32,
            prefix_cache=on)
        engine.warmup()
        _play_decode_trace(server, f"lm_pc_{label}",
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        _, elapsed = _play_decode_trace(server, f"lm_pc_{label}", trace,
                                        True)
        s = engine.stats()
        rows[label] = {
            "capacity_seqs": s["peak_live_seqs"],
            "prefill_tokens_saved": s["prefill_tokens_saved"],
            "prefix_hit_rate": round(s["prefix_hit_rate"], 4),
            "prefill_tokens": s["prefill_tokens"],
            "prefix_evictions_info": s["prefix_evictions"],
            "cow_copies_info": s["cow_copies"],
            "blocks_shared_info": s["blocks_shared"],
            "kv_blocks_cached_info": s["kv_blocks_cached"],
            "tokens_per_s_info": round(useful / elapsed, 1),
            "ttft_p50_ms_info": round(s["ttft_p50_ms"], 3),
            "ttft_p99_ms_info": round(s["ttft_p99_ms"], 3),
            "shed_rate_info": round(s["shed_rate"], 4),
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
        }
    on_row, off_row = rows["cache_on"], rows["cache_off"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "kv_pool_blocks": pool_blocks,
        "shared_prefix_len": prefix_len,
        "cache_on": on_row,
        "cache_off": off_row,
        "capacity_ratio": (round(on_row["capacity_seqs"]
                                 / off_row["capacity_seqs"], 2)
                           if off_row["capacity_seqs"] else float("inf")),
        "prefill_ratio_info": (
            round(on_row["prefill_tokens"]
                  / off_row["prefill_tokens"], 3)
            if off_row["prefill_tokens"] else 0.0),
        "ttft_p50_speedup_info": (
            round(off_row["ttft_p50_ms_info"]
                  / on_row["ttft_p50_ms_info"], 2)
            if on_row["ttft_p50_ms_info"] else float("inf")),
    }


def _quant_kv_ab(server, lm_model, quick: bool) -> dict:
    """Quantized-KV A/B: int8 per-block-scaled pools vs fp32 pools at
    EQUAL device KV bytes on the shared-prefix zipf trace.

    Both engines get the same byte budget; ``blocks_for_bytes`` turns
    it into a block count per encoding, so the int8 side's ~4x cheaper
    blocks (int8 payload + per-(layer, block) fp32 scales — the scales
    are IN the budget) buy ~4x the usable pool. At a budget sized for
    ~2 fp reservations the fp side throttles on pool pressure while the
    int8 side packs several times more CONCURRENT sequences —
    ``capacity_seqs`` (gated up, >= 2x) is the headline. Quantization
    is lossy, so the harness REPLAYS the identical trace through both
    engines and archives the per-request argmax agreement
    (``argmax_match_rate_info``, also pushed into the quant engine's
    stats via ``record_argmax_match``) next to the capacity win: the
    quality cost ships with the number that pays for it. tok/s rides as
    ``_info`` (scheduling noise); the one-trace invariant is gated on
    BOTH sides (quantized programs compile once, scales ride as traced
    data).
    """
    from multiverso_tpu.serving.block_pool import (blocks_for_bytes,
                                                   kv_bytes_per_block)

    block_size = 8
    prefix_len, tail_max, cap, min_new = 64, 8, 24, 12
    max_prompt = prefix_len + tail_max
    mcfg = lm_model.config
    # budget = ~2 uncached fp reservations (22 usable fp blocks)
    budget = 23 * kv_bytes_per_block(mcfg.n_layers, mcfg.d_model,
                                     block_size, mcfg.dtype)
    pool_blocks = {
        "fp32": blocks_for_bytes(budget, mcfg.n_layers, mcfg.d_model,
                                 block_size, mcfg.dtype),
        "int8": blocks_for_bytes(budget, mcfg.n_layers, mcfg.d_model,
                                 block_size, mcfg.dtype, quant="int8"),
    }
    n = 24 if quick else 48
    vocab = mcfg.vocab_size
    rng = np.random.default_rng(23)
    prefixes = [rng.integers(1, vocab, prefix_len).astype(np.int32)
                for _ in range(4)]
    trace, t = [], 0.0
    for _ in range(n):
        t += float(rng.exponential(0.002))
        head = prefixes[min(int(rng.zipf(1.8)) - 1, len(prefixes) - 1)]
        tail = rng.integers(1, vocab,
                            int(rng.integers(1, tail_max + 1))).astype(
            np.int32)
        n_new = int(min(cap, min_new + rng.zipf(1.6)))
        trace.append((t, np.concatenate([head, tail]), n_new))
    useful = sum(n_new for _, _, n_new in trace)

    rows, outputs, engines = {}, {}, {}
    for label, quant in (("fp32", "none"), ("int8", "int8")):
        engine = server.register_decoder(
            f"lm_qkv_{label}", lm_model, slots=24, max_prompt=max_prompt,
            max_new=cap, max_queue=max(64, n),
            prompt_buckets=(max_prompt,), kv_block_size=block_size,
            kv_pool_blocks=pool_blocks[label], prefill_token_budget=32,
            kv_quant=quant)
        engine.warmup()
        _play_decode_trace(server, f"lm_qkv_{label}",
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        results, elapsed = _play_decode_trace(server, f"lm_qkv_{label}",
                                              trace, True)
        outputs[label] = [np.asarray(r["result"]) for r in results]
        engines[label] = engine
        s = engine.stats()
        rows[label] = {
            "kv_pool_blocks": pool_blocks[label],
            "kv_bytes_per_device_info": s["kv_bytes_per_device"],
            "capacity_seqs": s["peak_live_seqs"],
            "prefill_tokens_saved_info": s["prefill_tokens_saved"],
            "tokens_per_s_info": round(useful / elapsed, 1),
            "ttft_p50_ms_info": round(s["ttft_p50_ms"], 3),
            "shed_rate_info": round(s["shed_rate"], 4),
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
            "decode_step_retraces": s["decode_step_retraces"],
        }
        if quant == "int8":
            rows[label]["quant_scale_blocks_info"] = \
                s["quant_scale_blocks"]
    # quality: per-request argmax agreement vs the fp32 engine on the
    # IDENTICAL trace, pushed into the quant engine's stats surface so
    # flight dumps and dashboards carry it too
    matches = []
    for a, b in zip(outputs["fp32"], outputs["int8"]):
        m = max(a.size, b.size)
        k = min(a.size, b.size)
        matches.append(float((a[:k] == b[:k]).sum()) / m if m else 1.0)
    rate = round(float(np.mean(matches)), 4)
    engines["int8"].record_argmax_match(rate)
    fp_row, q_row = rows["fp32"], rows["int8"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "kv_budget_bytes": budget,
        "shared_prefix_len": prefix_len,
        "fp32": fp_row,
        "int8": q_row,
        "capacity_ratio": (round(q_row["capacity_seqs"]
                                 / fp_row["capacity_seqs"], 2)
                           if fp_row["capacity_seqs"] else float("inf")),
        "blocks_ratio_info": round(q_row["kv_pool_blocks"]
                                   / fp_row["kv_pool_blocks"], 2),
        "argmax_match_rate_info": rate,
    }


def _spec_decode_ab(server, lm_model, quick: bool) -> dict:
    """Speculative-decoding A/B: n-gram prompt-lookup drafting
    (spec_k=4) vs the plain one-token engine (spec_k=0) — same model,
    same paged pool, same repetitive-tail arrival trace.

    The trace models the traffic speculation is FOR: prompts built by
    tiling a short motif (templated/boilerplate inputs whose greedy
    continuations re-enter their cycle within a few tokens), generating
    long-lived zipf outputs. The drafter proposes continuations from
    the sequence's own history, the fused verify step scores K + 1
    positions per dispatch, and greedy verification keeps outputs
    token-identical to the baseline — so the A/B prices pure
    amortization: per-iteration fixed costs (dispatch, host scheduling)
    divide over up to K + 1 emitted tokens. Gated columns:
    ``tokens_per_s`` both sides, ``speedup_spec``, and the spec side's
    ``accepted_per_step`` (mean extra tokens per verify dispatch,
    summed across slots); ``acceptance_rate`` archives as ``_info`` —
    it measures the trace's repetitiveness, not the code — alongside
    ITL percentiles per the 2-CPU noise rule. The spec_k=0 side runs
    literally today's path (no verify program is ever dispatched), so
    its numbers double as the no-regression reference for the plain
    engine.

    Geometry: TWO slots, long generations. Speculation's marginal win
    per iteration is ``accepted / n_live`` — the fused step already
    amortizes its dispatch across live slots, so the honest showcase
    is the low-concurrency latency-bound regime speculation serves in
    production (few sequences, deep decode), not a saturated batch.
    Measured on the CI container: 1.4-1.7x useful tok/s at ~0.8-0.9
    acceptance (the verify window costs ~1.7-2.5x a plain step for
    K+1 = 5 positions, so >= ~2 accepted drafts per live slot pay for
    it; the cycle-following drafter keeps windows full on the
    repetitive tail).
    """
    max_prompt, cap, min_new, K = 12, 64, 48, 4
    block_size = 8
    n = 12 if quick else 24
    vocab = lm_model.config.vocab_size
    rng = np.random.default_rng(37)
    trace, t = [], 0.0
    for _ in range(n):
        t += float(rng.exponential(0.002))
        motif = rng.integers(1, vocab,
                             int(rng.integers(2, 6))).astype(np.int32)
        plen = int(rng.integers(6, max_prompt + 1))
        prompt = np.tile(motif, -(-plen // len(motif)))[:plen]
        n_new = int(min(cap, min_new + rng.zipf(1.6)))
        trace.append((t, prompt.astype(np.int32), n_new))
    useful = sum(n_new for _, _, n_new in trace)

    rows = {}
    for label, k in (("spec", K), ("baseline", 0)):
        engine = server.register_decoder(
            f"lm_spec_{label}", lm_model, slots=2, max_prompt=max_prompt,
            max_new=cap, max_queue=max(64, n),
            prompt_buckets=(max_prompt,), kv_block_size=block_size,
            prefill_token_budget=max_prompt, spec_k=k)
        engine.warmup()
        _play_decode_trace(server, f"lm_spec_{label}",
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        _, elapsed = _play_decode_trace(server, f"lm_spec_{label}", trace,
                                        True)
        s = engine.stats()
        rows[label] = {
            "tokens_per_s": round(useful / elapsed, 1),
            "itl_p50_ms_info": round(s["itl_p50_ms"], 3),
            "itl_p99_ms_info": round(s["itl_p99_ms"], 3),
            "ttft_p50_ms_info": round(s["ttft_p50_ms"], 3),
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
            "decode_step_retraces": s["decode_step_retraces"],
        }
        if k:
            rows[label].update({
                "spec_k": s["spec_k"],
                "accepted_per_step": round(s["accepted_per_step"], 3),
                "acceptance_rate_info": round(s["acceptance_rate"], 4),
                "spec_steps_info": s["spec_steps"],
                "spec_proposed_info": s["spec_proposed"],
                "spec_accepted_info": s["spec_accepted"],
                "verify_traces": s["verify_traces"],
            })
    sp, base = rows["spec"], rows["baseline"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "spec_k": K,
        "spec": sp,
        "baseline": base,
        "speedup_spec": (round(sp["tokens_per_s"]
                               / base["tokens_per_s"], 2)
                         if base["tokens_per_s"] else float("inf")),
    }


def _sharded_decode_ab(server, quick: bool) -> dict:
    """Sharded-decode A/B: tp=2 vs tp=1 at EQUAL model + pool bytes.

    Same model, same paged pool, same arrival trace — the only
    difference is the decode mesh: the sharded side partitions heads/
    MLP/K-V pools over 2 devices (params resharded once per pin,
    programs compiled once against matched shardings), the replicated
    side is the classic single-device pin. The gated columns are
    ``kv_bytes_per_device`` (down — tensor parallelism exists to shrink
    what ONE device must hold; with the model row alongside, the line
    records when params + pool stop fitting a single device and tp>1 is
    the only way to serve) and ``decode_step_retraces`` (zero-baseline:
    any repartition/retrace of the fused step past warmup is the PR 2
    ~10x partitioner drag back in the hot loop). Wall-clock tok/s and
    step wall are ``_info`` per the 2-CPU noise rule — on a container
    whose virtual devices timeshare 2 cores, tp=2 pays real collective
    overhead for no real parallel compute, so the honest headline here
    is capacity, not speed.

    Needs >= 2 devices: run under ``--devices N`` (the scaling_bench
    pattern) or the multichip dryrun harness; the default 1-device
    bench archives a skip marker instead (no gated metrics emitted).
    """
    import jax

    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import kv_bytes_per_block

    if jax.device_count() < 2:
        return {"skipped": "needs >= 2 devices — run with --devices N "
                           "or under the multichip dryrun harness"}
    tp = 2
    max_prompt, cap, block_size = 16, 48, 8
    T = max_prompt + cap
    sd_cfg = TransformerConfig(vocab_size=256, d_model=256, n_heads=4,
                               n_layers=2, d_ff=512, max_seq=T)
    lm = TransformerLM(sd_cfg)
    pool_blocks = 8 * (T // block_size)
    kv_bytes = (pool_blocks + 1) * kv_bytes_per_block(
        sd_cfg.n_layers, sd_cfg.d_model, block_size)

    def _nbytes(a):
        return int(np.prod(a.shape)) * a.dtype.itemsize

    params_bytes = sum(_nbytes(a) for a in jax.tree.leaves(lm.params))
    # the decode layout replicates embed/pos/norms and shards the layer
    # stack (decode_param_shardings): what one device holds at tp
    rep_bytes = (_nbytes(lm.params["embed"]) + _nbytes(lm.params["pos"])
                 + _nbytes(lm.params["ln_f_g"])
                 + _nbytes(lm.params["layers"]["ln1_g"])
                 + _nbytes(lm.params["layers"]["ln2_g"]))
    n = 24 if quick else 48
    trace = _decode_trace(n, seed=31, max_prompt=max_prompt,
                          max_new_cap=cap, mean_gap_s=0.001,
                          vocab=sd_cfg.vocab_size, min_new=8)
    useful = sum(n_new for _, _, n_new in trace)

    rows, outs = {}, {}
    for label, tp_n in (("sharded", tp), ("replicated", 1)):
        engine = server.register_decoder(
            f"lm_sd_{label}", lm, slots=8, max_prompt=max_prompt,
            max_new=cap, max_queue=max(64, n),
            prompt_buckets=(max_prompt,), kv_block_size=block_size,
            kv_pool_blocks=pool_blocks, prefill_token_budget=16,
            decode_tp=tp_n)
        engine.warmup()
        _play_decode_trace(server, f"lm_sd_{label}",
                           [(0.0, np.ones(4, np.int32), 2)] * 4, True)
        engine.reset_stats()
        results, elapsed = _play_decode_trace(server, f"lm_sd_{label}",
                                              trace, True)
        outs[label] = [r["result"] for r in results]
        s = engine.stats()
        flight = engine.recorder.summary() if engine.recorder else {}
        rows[label] = {
            "decode_tp": s["decode_tp"],
            "mesh_devices": s["mesh_devices"],
            "kv_bytes_per_device": s["kv_bytes_per_device"],
            "decode_step_retraces": s["decode_step_retraces"],
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
            "pin_copies_info": s["pin_copies"],
            "tokens_per_s_info": round(useful / elapsed, 1),
            "ttft_p50_ms_info": round(s["ttft_p50_ms"], 3),
            "itl_p50_ms_info": round(s["itl_p50_ms"], 3),
            "mean_step_ms_info": round(flight.get("mean_step_ms", 0.0),
                                       3),
        }
    mismatches = sum(
        not np.array_equal(a, b)
        for a, b in zip(outs["sharded"], outs["replicated"]))
    sh = rows["sharded"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "decode_tp": tp,
        # the model-size story the mesh exists for: what ONE device must
        # hold. replicated = whole params + whole pool; sharded = the
        # replicated embed/pos/norm slice + 1/tp of the layer stack and
        # pool — when the replicated number exceeds a device's memory,
        # tp>1 is the only config that serves at all
        "model_params_bytes": params_bytes,
        "kv_pool_bytes": kv_bytes,
        "bytes_per_device_replicated": params_bytes + kv_bytes,
        "bytes_per_device_sharded": (
            rep_bytes + (params_bytes - rep_bytes) // tp
            + kv_bytes // tp),
        "output_mismatches_vs_tp1": mismatches,   # informational; tested
        "sharded": sh,
        "replicated": rows["replicated"],
    }


def _long_context_ab(server, quick: bool) -> dict:
    """Long-context A/B: sequence-parallel prefill on vs off at EQUAL
    pool bytes on the SAME tp=2 decode mesh.

    The trace is the long-context serving mix the feature exists for: a
    few "document" prompts (hundreds of tokens, ``max_new=1`` so the
    client-observed completion IS the TTFT) land in the middle of a
    steady stream of short interactive requests with zipf generations.
    Both legs run identical geometry — same model, same paged pool,
    same ``decode_tp=2`` mesh, same per-iteration token budget — the
    only difference is ``-prefill_sp``: the seqpar leg prefills
    ``budget x tp`` prompt tokens per engine iteration (one budget of
    rows per DEVICE, ring attention over the sequence axis), the off
    leg walks the same prompts one budget at a time on a single lane.

    Gated columns, both on the seqpar leg and lower-better:
    ``ttft_long_p50`` (median document TTFT — the headline: chunks are
    tp x fewer, so the document's first token lands in roughly half the
    iterations) and ``itl_short_p99`` (the tail inter-token latency of
    the short interactive requests decoding WHILE documents prefill —
    the number that says the bigger chunk did not buy TTFT by stalling
    everyone else; documents generate exactly one token so they
    contribute no ITL samples). The off leg's twins and the ratios ride
    as ``_info``. ``output_mismatches`` (seqpar vs single-lane token
    streams), ``decode_step_retraces`` and the one-trace counters ride
    the zero-baseline gates.

    Needs >= 2 devices (``--devices N`` / the dryrun harness); the
    default 1-device bench archives a skip marker like the
    sharded-decode A/B.
    """
    import jax

    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import OverloadedError

    if jax.device_count() < 2:
        return {"skipped": "needs >= 2 devices — run with --devices N "
                           "or under the multichip dryrun harness"}
    tp = 2
    block_size, budget, threshold = 16, 32, 64
    cap = 8
    # T divisible by block_size AND by tp (the ring backend's layout
    # constraint); documents span half to all of max_prompt
    max_prompt = 248 if quick else 376
    T = max_prompt + cap
    lc_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                               n_layers=2, d_ff=256, max_seq=T)
    lm = TransformerLM(lc_cfg)
    pool_blocks = 6 * (T // block_size)

    rng = np.random.default_rng(83)
    n_long = 3 if quick else 4
    n_short = 12 if quick else 24
    short_gap = 0.03
    trace = []
    t = 0.0
    for _ in range(n_short):
        t += float(rng.exponential(short_gap))
        plen = int(rng.integers(1, 13))
        trace.append((t, rng.integers(1, 256, plen).astype(np.int32),
                      int(min(cap, 4 + rng.zipf(1.6))), "short"))
    span = t
    for k in range(n_long):
        dlen = int(rng.integers(max_prompt // 2, max_prompt + 1))
        trace.append(((k + 1) * span / (n_long + 1),
                      rng.integers(1, 256, dlen).astype(np.int32),
                      1, "doc"))
    trace.sort(key=lambda r: r[0])

    def _play(model):
        done_t: dict = {}
        futs = []
        t0 = time.monotonic()
        for i, (at, prompt, n_new, tag) in enumerate(trace):
            delay = at - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            while True:
                try:
                    f = server.submit(model,
                                      {"prompt": prompt, "max_new": n_new})
                    break
                except OverloadedError as exc:
                    if not getattr(exc, "retriable", True):
                        raise
                    time.sleep(0.001)
            sub_t = time.monotonic()
            # completion stamped by the engine's done callback, not by
            # .result() below — waiting in submit order would charge
            # earlier stragglers' wait to later documents
            f.add_done_callback(
                lambda _f, ix=i: done_t.__setitem__(ix, time.monotonic()))
            futs.append((i, tag, sub_t, f))
        outs, doc_lat = [], []
        for i, tag, sub_t, f in futs:
            outs.append(f.result(timeout=600)["result"])
            if tag == "doc":
                doc_lat.append((done_t[i] - sub_t) * 1e3)
        return outs, doc_lat

    rows, outs = {}, {}
    for label, sp in (("seqpar", True), ("single_lane", False)):
        engine = server.register_decoder(
            f"lm_lc_{label}", lm, slots=6, max_prompt=max_prompt,
            max_new=cap, max_queue=max(64, n_short + n_long),
            prompt_buckets=(max_prompt,), kv_block_size=block_size,
            kv_pool_blocks=pool_blocks, prefill_token_budget=budget,
            decode_tp=tp, prefill_sp=sp, prefill_sp_backend="ring",
            prefill_sp_threshold=threshold)
        engine.warmup()
        engine.reset_stats()
        outs[label], doc_lat = _play(f"lm_lc_{label}")
        s = engine.stats()
        rows[label] = {
            "ttft_long_p50": round(float(np.median(doc_lat)), 3),
            "itl_short_p99": round(s["itl_p99_ms"], 3),
            "decode_step_retraces": s["decode_step_retraces"],
            "step_traces": s["step_traces"],
            "prefill_traces": s["prefill_traces"],
            "deadline_drops": s["deadline_drops"],
        }
        if sp:
            rows[label]["seqpar_traces"] = s["seqpar_traces"]
            rows[label]["seqpar_chunks_info"] = s["seqpar_chunks"]
        else:
            # off leg's latencies archive as _info: the seqpar leg owns
            # the gate, the ratios below tell the story across rounds
            rows[label] = {
                (k if k not in ("ttft_long_p50", "itl_short_p99")
                 else f"{k}_info"): v
                for k, v in rows[label].items()}
    mismatches = sum(
        not np.array_equal(a, b)
        for a, b in zip(outs["seqpar"], outs["single_lane"]))
    sp_row, off = rows["seqpar"], rows["single_lane"]
    return {
        "requests": n_short + n_long,
        "documents": n_long,
        "doc_prompt_max": max_prompt,
        "decode_tp": tp,
        "prefill_token_budget": budget,
        "sp_chunk_tokens": budget * tp,
        "output_mismatches": mismatches,
        "ttft_long_speedup_info": round(
            off["ttft_long_p50_info"]
            / max(sp_row["ttft_long_p50"], 1e-9), 3),
        "itl_short_p99_ratio_info": round(
            sp_row["itl_short_p99"]
            / max(off["itl_short_p99_info"], 1e-9), 3),
        "seqpar": sp_row,
        "single_lane": off,
    }


def _observability_ab(server, lm_model, quick: bool):
    """Prices the always-on black box: the SAME engine serves the same
    mixed-length trace twice — tracing fully disabled, then tail-sampled
    tracing on — with the flight recorder and watchdog running
    throughout (they are always on). Both tok/s columns are ``_info``:
    on a 2-CPU container the delta sits inside the scheduling-noise
    floor, and gating a noise-floor difference would flap — the number
    that IS gated is ``watchdog_trips`` (attached by ``run()``: any trip
    in a clean bench is a bug) and the one-trace invariant
    (``step_traces``) that proves flight recording adds no compiles.

    Returns ``(row, engine)`` — the engine so ``run()`` can export its
    ring (``--flight``) and merge its counter tracks into ``--trace``.
    """
    from multiverso_tpu import trace as trace_mod

    max_prompt, cap = 8, 64
    n = 24 if quick else 48
    tr = _decode_trace(n, seed=23, max_prompt=max_prompt, max_new_cap=cap,
                       mean_gap_s=0.0005, vocab=lm_model.config.vocab_size,
                       min_new=8)
    useful = sum(n_new for _, _, n_new in tr)
    # cost_ledger=True: the accounting A/B downstream rides this same
    # warm engine (detaching/re-attaching the ledger per leg) — and the
    # ledger running through THIS leg's passes is itself part of the
    # proof that accounting is pure host state (step_traces stays 1)
    engine = server.register_decoder(
        "lm_obs", lm_model, slots=8, max_prompt=max_prompt, max_new=cap,
        max_queue=max(64, n), prompt_buckets=(max_prompt,),
        cost_ledger=True)
    engine.warmup()
    _play_decode_trace(server, "lm_obs",
                       [(0.0, np.ones(4, np.int32), 2)] * 4, True)
    # two alternating passes per leg, best-of kept: single 0.2-1s passes
    # on the 2-CPU container swing with scheduler noise, and this column
    # exists to price TRACING, not whichever pass drew the noisy
    # neighbor. resume()/disable(), not enable(): re-enabling would wipe
    # the spans the earlier bench sections already recorded into the ring
    tps = {"untraced": 0.0, "traced": 0.0}
    for _ in range(2):
        for label, tracing_on in (("untraced", False), ("traced", True)):
            if tracing_on:
                trace_mod.resume()
            else:
                trace_mod.disable()
            engine.reset_stats()
            _, elapsed = _play_decode_trace(server, "lm_obs", tr, True)
            tps[label] = max(tps[label], round(useful / elapsed, 1))
    trace_mod.resume()
    stats = engine.stats()
    tail = trace_mod.collector().stats().get("tail", {})
    flight = engine.recorder.summary() if engine.recorder else {}
    row = {
        "requests": n,
        "useful_tokens": useful,
        "tokens_per_s_untraced_info": tps["untraced"],
        "tokens_per_s_traced_info": tps["traced"],
        "trace_overhead_frac_info": (
            round(1.0 - tps["traced"] / tps["untraced"], 4)
            if tps["untraced"] else 0.0),
        "tail_completed_info": tail.get("completed", 0),
        "tail_kept_info": tail.get("kept", 0),
        "tail_discarded_info": tail.get("discarded", 0),
        "flight_iterations_info": flight.get("iterations", 0),
        "flight_idle_frac_info": round(flight.get("idle_frac", 0.0), 4),
        "flight_mean_step_ms_info": round(flight.get("mean_step_ms", 0.0),
                                          3),
        "step_traces": stats["step_traces"],
    }
    return row, engine


def _lockwatch_ab(server, quick: bool):
    """Prices the runtime lock-order witness (``-lockwatch``): the SAME
    engine (``lm_obs``, registered by the observability A/B) serves the
    same mixed-length trace with the witness disabled vs enabled,
    best-of-3 alternating passes. Both tok/s columns are ``_info`` — on
    the 2-CPU container the witness's per-acquisition cost (a
    thread-local append; the graph lock only on never-before-seen edges,
    docs/ANALYSIS.md "cost posture") sits inside the scheduling-noise
    floor — while ``lock_order_violations`` is a zero-baseline gate: a
    cycle recorded during a clean bench is a latent deadlock, not noise.
    """
    from multiverso_tpu.analysis import lockwatch

    # quick keeps the full 48-request trace: each pass is still
    # sub-second, and a shorter one puts a single ~50 ms scheduler
    # hiccup at >15% of the window — the off/on delta becomes a coin
    # flip (observed up to 0.55 at n=24)
    max_prompt, cap = 8, 64
    n = 48
    tr = _decode_trace(n, seed=29, max_prompt=max_prompt, max_new_cap=cap,
                       mean_gap_s=0.0005, vocab=256, min_new=8)
    useful = sum(n_new for _, _, n_new in tr)
    before = lockwatch.violation_count()
    was_enabled = lockwatch.enabled()
    tps = {"off": 0.0, "on": 0.0}
    for _ in range(3):
        for label, on in (("off", False), ("on", True)):
            if on:
                lockwatch.enable()
            else:
                lockwatch.disable()
            _, elapsed = _play_decode_trace(server, "lm_obs", tr, True)
            tps[label] = max(tps[label], round(useful / elapsed, 1))
    (lockwatch.enable if was_enabled else lockwatch.disable)()
    return {
        "requests": n,
        "useful_tokens": useful,
        "tokens_per_s_lockwatch_off_info": tps["off"],
        "tokens_per_s_lockwatch_on_info": tps["on"],
        "lockwatch_overhead_frac_info": (
            round(1.0 - tps["on"] / tps["off"], 4) if tps["off"] else 0.0),
        "lock_order_violations": lockwatch.violation_count() - before,
    }


class _ObsBenchKV:
    """The three client calls the plane uses, backed by a local dict —
    lets the A/B run the REAL two-rank wire (sockets, acks, retained
    window) inside one bench process."""

    def __init__(self):
        import threading as _threading

        self._d = {}
        self._cv = _threading.Condition()

    def key_value_set(self, key, val, allow_overwrite=False):
        with self._cv:
            self._d[key] = val
            self._cv.notify_all()

    def blocking_key_value_get(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._d:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"NOT_FOUND: {key}")
                self._cv.wait(left)
            return self._d[key]

    def key_value_try_get(self, key):
        with self._cv:
            if key not in self._d:
                raise KeyError(f"NOT_FOUND: {key}")
            return self._d[key]


def _obs_plane_ab(server, quick: bool) -> dict:
    """Prices the fleet observability plane (``-obs_plane``): the SAME
    warm engine (``lm_obs``, registered by the observability A/B)
    serves the same mixed-length trace with no agents vs a REAL
    two-rank plane reporting every 100 ms — rank 1 builds full reports
    (snapshot diff, shared-helper deltas, bucket exports, engine
    stats/health/watchdog/flight summaries, span drain) and ships them
    over actual localhost p2p sockets; rank 0 runs the collector,
    draining/acking the stream and folding its own loopback reports.
    Best-of-2 alternating passes; both tok/s columns are ``_info`` —
    on the 2-CPU container the delta sits inside the scheduling-noise
    floor — while ``obs_dropped_reports`` (the WIRE publisher's drop
    counter) rides the zero-baseline gate: with a live, acking
    collector the bounded publish window must never fill, so any drop
    means the ack/release machinery broke — a bug, not noise.
    """
    from multiverso_tpu.serving.obs_plane import ObsAgent

    # quick keeps the full 48-request trace (the lockwatch A/B's
    # rationale: a shorter window puts one ~50 ms scheduler hiccup at
    # >15% of the measurement and the off/on delta becomes a coin flip)
    max_prompt, cap = 8, 64
    n = 48
    tr = _decode_trace(n, seed=43, max_prompt=max_prompt, max_new_cap=cap,
                       mean_gap_s=0.0005, vocab=256, min_new=8)
    useful = sum(n_new for _, _, n_new in tr)
    tps = {"off": 0.0, "on": 0.0}
    agent_stats = {}
    collector_nodes = 0
    for leg in range(2):
        for label, on in (("off", False), ("on", True)):
            agents = []
            if on:
                kv = _ObsBenchKV()
                # rank 0 = collector (+ its own loopback reports),
                # rank 1 = the wire publisher whose drop counter gates
                agents = [ObsAgent(rank=r, size=2, client=kv,
                                   report_ms=100,
                                   label=f"bench_obs{leg}")
                          for r in range(2)]
            try:
                _, elapsed = _play_decode_trace(server, "lm_obs", tr, True)
            finally:
                for a in reversed(agents):   # publisher flushes first
                    a.stop(final_report=True)
            tps[label] = max(tps[label], round(useful / elapsed, 1))
            if agents:
                agent_stats = agents[1].stats()
                collector_nodes = len(agents[0].collector.nodes())
    return {
        "requests": n,
        "useful_tokens": useful,
        "tokens_per_s_obs_off_info": tps["off"],
        "tokens_per_s_obs_on_info": tps["on"],
        "obs_overhead_frac_info": (
            round(1.0 - tps["on"] / tps["off"], 4) if tps["off"] else 0.0),
        "obs_reports_info": agent_stats.get("reports", 0),
        "obs_spans_shipped_info": agent_stats.get("spans_shipped", 0),
        "obs_collector_nodes_info": collector_nodes,
        "obs_dropped_reports": agent_stats.get("dropped_reports", 0),
    }


def _accounting_ab(server, engine, quick: bool) -> dict:
    """Prices and PROVES the per-tenant cost ledger (``-cost_ledger``):
    the SAME warm engine (``lm_obs``, registered with the ledger by the
    observability A/B) serves one mixed-length trace with the ledger
    detached vs attached, best-of-2 alternating passes — both tok/s
    columns are ``_info`` (per-token ledger work is a handful of host
    float adds; on the 2-CPU container it sits inside the
    scheduling-noise floor). The gated numbers are the CONSERVATION
    INVARIANTS, measured on a final 3-tenant round-robin tagged pass:
    ``accounting_drift`` (|sum-over-tenants - engine counter| over
    prefill/decode/transfer integer fields, serving/accounting.py) must
    be 0 — attribution that loses or invents tokens is corruption — and
    ``decode_step_retraces`` 0 / ``step_traces`` 1 prove the ledger is
    pure host state (no compile reachable from the loop). The tagged
    pass runs under a REAL two-rank obs plane (the obs-plane A/B's
    wire) so the per-tenant keyed instruments ship and the collector's
    ``tenant_rows()``/``tenants_table()`` — the ``opscenter --tenants``
    surface — render all 3 tenants; per-tenant cost units archive as
    ``_info`` (they measure the trace's tenant mix, not the code)."""
    from multiverso_tpu.serving.obs_plane import ObsAgent

    # full 48-request trace even under --quick (the lockwatch A/B's
    # rationale: a shorter window turns one scheduler hiccup into a
    # coin-flip overhead column)
    max_prompt, cap = 8, 64
    n = 48
    tr = _decode_trace(n, seed=53, max_prompt=max_prompt, max_new_cap=cap,
                       mean_gap_s=0.0005, vocab=256, min_new=8)
    useful = sum(n_new for _, _, n_new in tr)
    tenants = ("acme", "globex", "initech")
    ledger = engine.ledger
    tps = {"off": 0.0, "on": 0.0}
    try:
        for _ in range(2):
            for label, on in (("off", False), ("on", True)):
                # detach/re-attach between passes (no requests in
                # flight): the off leg runs the identical engine with
                # every ledger hook short-circuited at its None check
                engine.ledger = ledger if on else None
                _, elapsed = _play_decode_trace(
                    server, "lm_obs", tr, True,
                    tenants=tenants if on else None)
                tps[label] = max(tps[label], round(useful / elapsed, 1))
    finally:
        engine.ledger = ledger
    # the gated pass: fresh mirrors on both sides of the identity, a
    # 3-tenant tagged replay under a live 2-rank plane, then the
    # residual against the engine's own counters
    engine.reset_stats()
    kv = _ObsBenchKV()
    agents = [ObsAgent(rank=r, size=2, client=kv, report_ms=100,
                       label="bench_acct")
              for r in range(2)]
    try:
        _play_decode_trace(server, "lm_obs", tr, True, tenants=tenants)
    finally:
        for a in reversed(agents):       # publisher flushes first
            a.stop(final_report=True)
    stats = engine.stats()
    tenant_rows = agents[0].collector.tenant_rows()
    table = agents[0].collector.tenants_table()
    per_tenant = ledger.tenants()
    row = {
        "requests": n,
        "useful_tokens": useful,
        "tokens_per_s_ledger_off_info": tps["off"],
        "tokens_per_s_ledger_on_info": tps["on"],
        "ledger_overhead_frac_info": (
            round(1.0 - tps["on"] / tps["off"], 4) if tps["off"] else 0.0),
        "accounting_drift": stats["accounting_drift"],
        "decode_step_retraces": stats["decode_step_retraces"],
        "step_traces": stats["step_traces"],
        "tenants_live_info": stats["tenants_live"],
        "obs_tenant_rows_info": len(
            {r["tenant"] for r in tenant_rows}),
        "obs_tenant_table_lines_info": (len(table.splitlines())
                                        if table else 0),
    }
    for t in tenants:
        row[f"cost_{t}_info"] = round(
            (per_tenant.get(t) or {}).get("cost", 0.0), 3)
    return row


def _fleet_chaos_ab(quick: bool) -> dict:
    """The serving-fleet recovery A/B (``lm_fleet_chaos``): a 3-replica
    fleet behind the :class:`FleetRouter` serves one mixed-length trace
    twice over the real ``mvserve`` wire — fault-free, then with a
    seeded ``kill_at_request`` chaos plan that drops one replica
    mid-trace (abrupt in-process death: heartbeats stop, the wire
    breaks, its in-flight requests are drained into the retry queue and
    replayed on the survivors). The gated numbers are the recovery
    INVARIANTS, not the wall clock: ``requests_lost`` must be 0 (every
    accepted request resolves), ``fleet_redispatch_output_mismatches``
    must be 0 (deterministic greedy decode means a replay is
    bit-identical to the fault-free run — checked request by request),
    ``recovery_time_s`` (death flagged -> first replayed completion)
    regresses UP, and the fault-free aggregate ``fleet_tokens_per_s``
    regresses DOWN. Engines are built once and re-wrapped per leg; the
    chaos leg runs SECOND so the comparison outputs already exist."""
    from multiverso_tpu.dashboard import Dashboard
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import FaultPlan, FleetConfig, FleetRouter
    from multiverso_tpu.serving.decode_engine import (DecodeEngine,
                                                      DecodeEngineConfig)
    from multiverso_tpu.serving.replica import ReplicaServer

    n_replicas = 3
    max_prompt, cap = 8, 24
    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq=32)
    engines = []
    for r in range(1, n_replicas + 1):
        # SAME config (same param seed) on every replica: the fleet's
        # replay-determinism contract needs replicas to be replicas
        engine = DecodeEngine(f"fleet_r{r}", TransformerLM(cfg),
                              DecodeEngineConfig(
                                  slots=4, max_prompt=max_prompt,
                                  max_new=cap, max_queue=64,
                                  prompt_buckets=(max_prompt,),
                                  watchdog=False))
        engine.warmup()
        engines.append(engine)
    n = 24 if quick else 48
    trace = _decode_trace(n, seed=47, max_prompt=max_prompt,
                          max_new_cap=cap, mean_gap_s=0.002, vocab=256,
                          min_new=6)
    useful = sum(n_new for _, _, n_new in trace)
    kill_at = 3                   # the victim's 3rd dequeue: mid-trace
    legs: dict = {}
    try:
        for label, chaos in (("off", ""),
                             ("on", f"kill_at_request={kill_at}")):
            kv = _ObsBenchKV()
            router = FleetRouter(
                n_replicas + 1, kv, label=f"bench_fleet_{label}",
                fleet_config=FleetConfig(heartbeat_ms=100,
                                         deadline_s=120.0))
            replicas = []
            try:
                for i, engine in enumerate(engines):
                    rep = ReplicaServer(i + 1, n_replicas + 1, kv,
                                        engine,
                                        label=f"bench_fleet_{label}",
                                        heartbeat_ms=100)
                    if chaos and i == 0:
                        rep.chaos = FaultPlan(chaos, kill_fn=rep.die)
                    replicas.append(rep)
                t0 = time.monotonic()
                deadline = t0 + 60
                while router.stats()["up"] < n_replicas:
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"fleet never came up: "
                                           f"{router.replica_rows()}")
                    time.sleep(0.01)
                futs = []
                t0 = time.monotonic()
                for i, (at, prompt, n_new) in enumerate(trace):
                    delay = at - (time.monotonic() - t0)
                    if delay > 0:
                        time.sleep(delay)
                    futs.append(router.submit(prompt, n_new,
                                              session=f"s{i % 6}"))
                outs = [np.asarray(f.result(timeout=300)["result"],
                                   np.int32) for f in futs]
                elapsed = time.monotonic() - t0
                legs[label] = {"outs": outs, "elapsed": elapsed,
                               "stats": router.stats()}
            finally:
                # a failed leg must not leave router/replica threads
                # ticking (and holding sockets) under later workloads
                router.stop()
                for rep in replicas:
                    rep.stop(stop_engine=False)
    finally:
        for engine in engines:
            engine.stop()
    mismatches = sum(
        1 for a, b in zip(legs["off"]["outs"], legs["on"]["outs"])
        if a.shape != b.shape or not np.array_equal(a, b))
    chaos_stats = legs["on"]["stats"]
    return {
        "replicas": n_replicas,
        "requests": n,
        "useful_tokens": useful,
        "fleet_tokens_per_s": round(useful / legs["off"]["elapsed"], 1),
        "fleet_tokens_per_s_chaos_info": round(
            useful / legs["on"]["elapsed"], 1),
        "requests_lost": chaos_stats["requests_lost"],
        "fleet_redispatch_output_mismatches": mismatches
        + chaos_stats["output_mismatches"],
        "recovery_time_s": round(chaos_stats["recovery_time_s"] or 0.0, 4),
        "deaths_info": chaos_stats["deaths"],
        "redispatched_info": int(Dashboard.get_or_create_counter(
            "FLEET_REDISPATCH").get()),
        "chaos_completed_info": chaos_stats["completed"],
    }


def _disagg_ab(quick: bool) -> dict:
    """Disaggregated prefill/decode A/B (``lm_disagg``): the SAME two
    engines serve one mixed long-prompt / short-interactive trace twice
    over the real ``mvserve`` wire at equal hardware — as a classic
    2-replica unified fleet, then split into one ``prefill`` and one
    ``decode`` replica behind the router's two-stage dispatch (stage 1
    chunk-prefills into paged KV blocks and ships them as a
    ``kv_transfer`` payload, stage 2 splices them and admits through
    the prefix-cache full-hit path). Gated: ``output_mismatches`` 0
    (splice-at-arrival is bit-exact — every trace request AND the
    sequential repeat phase compared token by token across legs),
    ``itl_p99_ratio`` (unified decode-ITL p99 over disagg decode-ITL
    p99 — disaggregation exists to keep decode iterations clean of
    prefill bursts, so the ratio is higher-better), ``kv_bytes_moved``
    (raw K/V bytes over the wire; every long prompt in the trace is
    DISTINCT so the total is deterministic, lower-better),
    ``xfer_dedup_hit_rate`` (higher-better) and
    ``dedup_repeat_kv_bytes_moved`` (bytes moved when three
    already-shipped prompts are re-submitted sequentially: ~0 — a warm
    prefix never crosses the wire again). TTFT p99 and per-leg tok/s
    archive as ``_info``: the disagg leg's engine-side TTFT starts at
    stage-2 admission (the cross-stage wait lives in the
    ``kv.transfer`` span, not this histogram), and tok/s sits on the
    2-CPU noise floor."""
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import FleetConfig, FleetRouter
    from multiverso_tpu.serving.decode_engine import (DecodeEngine,
                                                      DecodeEngineConfig)
    from multiverso_tpu.serving.replica import ReplicaServer

    max_prompt, cap, block = 16, 12, 4
    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq=48)
    engines = []
    for r in (1, 2):
        # SAME config (same param seed) on both: replicas are replicas,
        # and the A/B's bit-exactness gate depends on it
        engine = DecodeEngine(f"disagg_r{r}", TransformerLM(cfg),
                              DecodeEngineConfig(
                                  slots=4, max_prompt=max_prompt,
                                  max_new=cap, max_queue=64,
                                  kv_block_size=block, kv_pool_blocks=64,
                                  prefill_token_budget=8,
                                  prefix_cache=True, watchdog=False))
        engine.warmup()
        engines.append(engine)
    n = 16 if quick else 32
    rng = np.random.default_rng(53)
    # Mixed trace: even slots are block-aligned LONG prompts (4 full
    # blocks each, all DISTINCT — so the disagg leg's shipped-bytes
    # total is exactly n/2 payloads of 4 blocks, deterministic run to
    # run), odd slots are 2-3 token interactive prompts (no full
    # block: nothing ships, stage 2 re-prefills them in one chunk).
    trace, longs, t = [], [], 0.0
    for i in range(n):
        t += float(rng.exponential(0.004))
        if i % 2 == 0:
            prompt = rng.integers(1, 256, max_prompt).astype(np.int32)
            longs.append(prompt)
            n_new = 4 + int(rng.integers(0, 5))
        else:
            prompt = rng.integers(
                1, 256, int(rng.integers(2, 4))).astype(np.int32)
            n_new = 6 + int(rng.integers(0, 5))
        trace.append((t, prompt, n_new))
    useful = sum(n_new for _, _, n_new in trace)
    legs: dict = {}
    try:
        for label, roles in (("unified", ("unified", "unified")),
                             ("disagg", ("prefill", "decode"))):
            for engine in engines:
                # cold caches + zeroed histograms per leg: the unified
                # leg's warm prefixes must not inflate the disagg leg's
                # dedup numbers, and per-leg ITL/TTFT must not mix
                engine._pool.flush_cache()
                engine.reset_stats()
            kv = _ObsBenchKV()
            router = FleetRouter(3, kv, label=f"bench_disagg_{label}",
                                 fleet_config=FleetConfig(
                                     heartbeat_ms=100, deadline_s=120.0))
            replicas = []
            try:
                for i, engine in enumerate(engines):
                    replicas.append(ReplicaServer(
                        i + 1, 3, kv, engine,
                        label=f"bench_disagg_{label}",
                        heartbeat_ms=100, role=roles[i]))
                deadline = time.monotonic() + 60
                while (router.stats()["up"] < 2
                       or [r["role"] for r in router.replica_rows()]
                       != list(roles)):
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"fleet never came up: "
                                           f"{router.replica_rows()}")
                    time.sleep(0.01)
                futs = []
                t0 = time.monotonic()
                for i, (at, prompt, n_new) in enumerate(trace):
                    delay = at - (time.monotonic() - t0)
                    if delay > 0:
                        time.sleep(delay)
                    futs.append(router.submit(prompt, n_new,
                                              session=f"s{i % 6}"))
                outs = [np.asarray(f.result(timeout=300)["result"],
                                   np.int32) for f in futs]
                elapsed = time.monotonic() - t0
                # sequential repeat phase: the first three long prompts
                # again, one at a time, after the trace drained — in
                # the disagg leg their chains sit in the router's
                # shipped book, so the prefill replica ships ZERO bytes
                # (dedup-at-source); outputs must still match the
                # unified leg's repeats bit-exactly
                b0 = router.stats()["kv_bytes_moved"]
                for j, p in enumerate(longs[:3]):
                    outs.append(np.asarray(
                        router.submit(p, 6, session=f"rep{j}")
                        .result(timeout=300)["result"], np.int32))
                st = router.stats()
                legs[label] = {
                    "outs": outs, "elapsed": elapsed, "stats": st,
                    "repeat_bytes": st["kv_bytes_moved"] - b0,
                    "engine_stats": [e.stats() for e in engines],
                }
            finally:
                # a failed leg must not leave router/replica threads
                # ticking (and holding sockets) under later workloads
                router.stop()
                for rep in replicas:
                    rep.stop(stop_engine=False)
    finally:
        for engine in engines:
            engine.stop()
    mismatches = sum(
        1 for a, b in zip(legs["unified"]["outs"], legs["disagg"]["outs"])
        if a.shape != b.shape or not np.array_equal(a, b))
    dstats = legs["disagg"]["stats"]
    uni_itl = max(e["itl_p99_ms"]
                  for e in legs["unified"]["engine_stats"])
    dec_itl = legs["disagg"]["engine_stats"][1]["itl_p99_ms"]
    uni_ttft = max(e["ttft_p99_ms"]
                   for e in legs["unified"]["engine_stats"])
    dec_ttft = legs["disagg"]["engine_stats"][1]["ttft_p99_ms"]
    return {
        "requests": n,
        "useful_tokens": useful,
        "output_mismatches": mismatches + dstats["output_mismatches"],
        "requests_lost": dstats["requests_lost"],
        "itl_p99_ratio": round(uni_itl / dec_itl, 3) if dec_itl else 0.0,
        "kv_bytes_moved": dstats["kv_bytes_moved"],
        "xfer_dedup_hit_rate": round(dstats["xfer_dedup_hit_rate"], 4),
        "dedup_repeat_kv_bytes_moved": legs["disagg"]["repeat_bytes"],
        "kv_xfers_info": dstats["kv_xfers"],
        "xfer_blocks_info": dstats["xfer_blocks"],
        "xfer_dedup_blocks_info": dstats["xfer_dedup_blocks"],
        "tokens_per_s_unified_info": round(
            useful / legs["unified"]["elapsed"], 1),
        "tokens_per_s_disagg_info": round(
            useful / legs["disagg"]["elapsed"], 1),
        "ttft_p99_ms_unified_info": round(uni_ttft, 3),
        "ttft_p99_ms_disagg_info": round(dec_ttft, 3),
        "itl_p99_ms_unified_info": round(uni_itl, 3),
        "itl_p99_ms_disagg_info": round(dec_itl, 3),
    }


def _trainer_chaos_ab(quick: bool) -> dict:
    """Durable online learning A/B (``lm_trainer_chaos``): one
    deterministic add-and-publish stream runs twice over the real
    ``mvparam`` wire into a subscriber replica — fault-free, then with
    a seeded ``kill_trainer_at_publish`` killing the trainer
    mid-stream, followed by the full recovery choreography: the
    subscriber flags STALE (``-params_stale_after_s``), a fresh
    incarnation restores checkpoint + replays the WAL to the exact
    pre-crash version, claims the next epoch, rebases the fleet with a
    STATE publish and finishes the schedule; finally one staged
    zombie (epoch-1) publish must be fenced. Gated: ``updates_lost``
    0 (every ACKNOWLEDGED add survives the kill),
    ``output_mismatches`` 0 (recovered trainer AND re-converged
    subscriber bit-identical to the fault-free leg),
    ``epoch_fence_rejections_unexpected`` 0 (exactly the staged
    zombie is rejected, nothing legitimate), and
    ``trainer_recovery_time_s`` (restart begin -> subscriber
    re-converged) regresses UP. The staleness peak and WAL replay
    volume archive as ``_info``."""
    import shutil
    import tempfile

    import multiverso_tpu as mv
    from multiverso_tpu.io import checkpoint
    from multiverso_tpu.io.wal import DeltaWAL
    from multiverso_tpu.runtime import Session
    from multiverso_tpu.serving import (FaultPlan, ParamPublisher,
                                        ParamSubscriber)

    n_adds = 16 if quick else 32
    kill_at = n_adds // 2 + 1       # mid-stream publish (1 = the rebase)
    rows, cols = 32, 16

    def make_delta(i):
        rng = np.random.default_rng(4200 + i)
        return rng.standard_normal((rows, cols)).astype(np.float32)

    class _Killed(Exception):
        pass

    def _die():
        raise _Killed()

    sess = Session.get()
    root = tempfile.mkdtemp(prefix="mv_trainer_chaos_")
    legs: dict = {}
    fence_stats: dict = {}
    try:
        for label in ("off", "on"):
            wal_dir = os.path.join(root, label, "wal")
            ck_root = os.path.join(root, label, "ckpt")
            src = mv.create_table("matrix", rows, cols,
                                  name=f"tchaos_src_{label}")
            dst = mv.create_table("matrix", rows, cols,
                                  name=f"tchaos_dst_{label}")
            kv = _ObsBenchKV()
            plane = f"bench_tchaos_{label}"
            chaos = (f"kill_trainer_at_publish={kill_at}"
                     if label == "on" else "")
            plan = FaultPlan(chaos, kill_fn=_die)
            sess.wal = DeltaWAL(wal_dir)
            plan.attach_wal(sess.wal)
            pub = ParamPublisher(kv, 2, label=plane, chaos=plan)
            sub = ParamSubscriber(kv, {src.table_id: dst}, rank=1,
                                  size=2, label=plane, poll_s=0.005,
                                  stale_after_s=0.2)
            saver = checkpoint.Autosaver(ck_root, every_steps=5, keep=2)
            acked = 0
            killed = False
            recovery_s = 0.0
            stale_peak = 0.0
            replayed = 0
            restored_step = -1
            try:
                try:
                    pub.publish_state(src)
                    for i in range(n_adds):
                        src.add(make_delta(i))       # acknowledged
                        acked += 1
                        saver.step(i + 1)
                        pub.publish_delta(src, make_delta(i))
                except _Killed:
                    killed = True
                if killed:
                    # crash: nothing more appends from this incarnation
                    sess.wal.close()
                    sess.wal = None
                    deadline = time.monotonic() + 15
                    while (not sub.params_stale()
                           and time.monotonic() < deadline):
                        time.sleep(0.005)
                    stale_peak = sub.params_age_s()
                    # restart: clobbered trainer recovers checkpoint +
                    # WAL to the exact acknowledged state, claims the
                    # next epoch, rebases the fleet, finishes the run
                    t_restart = time.monotonic()
                    src._install_state(
                        np.zeros((rows, cols), np.float32), 0)
                    restored_step = checkpoint.restore_latest(
                        ck_root, wal_dir=wal_dir, wal_rank=0) or 0
                    replayed = checkpoint.LAST_WAL_REPLAY["replayed"]
                    lost_at_recovery = acked - int(src.version)
                    sess.wal = DeltaWAL(wal_dir)
                    pub.stop()
                    pub = ParamPublisher(kv, 2, label=plane)  # epoch 2
                    pub.publish_state(src)
                    for i in range(acked, n_adds):
                        src.add(make_delta(i))
                        pub.publish_delta(src, make_delta(i))
                    deadline = time.monotonic() + 30
                    while (dst.version != src.version
                           and time.monotonic() < deadline):
                        time.sleep(0.005)
                    recovery_s = time.monotonic() - t_restart
                    # the staged zombie: one stale-epoch publish, never
                    # applied anywhere
                    pub.publish_record(
                        0, src.table_id,
                        [np.full((rows, cols), 99.0, np.float32)],
                        epoch=1, version=src.version + 1)
                # both legs: wait for the subscriber to fully converge
                deadline = time.monotonic() + 30
                want_rej = 1 if killed else 0
                while ((dst.version != src.version
                        or sub._fence.rejections < want_rej)
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                legs[label] = {
                    "src": np.asarray(src.get()),
                    "dst": np.asarray(dst.get()),
                    "version": int(src.version),
                    "acked_at_kill": acked if killed else n_adds,
                    "updates_lost_at_recovery": (lost_at_recovery
                                                 if killed else 0),
                    "killed": killed,
                    "recovery_s": recovery_s,
                    "stale_peak": stale_peak,
                    "replayed": replayed,
                    "restored_step": restored_step,
                    "pub_stats": pub.stats(),
                }
                fence_stats[label] = {
                    "rejections": sub._fence.rejections,
                    "staged": want_rej,
                }
            finally:
                sub.stop()
                pub.stop()
                if sess.wal is not None:
                    sess.wal.close()
                    sess.wal = None
    finally:
        if sess.wal is not None:
            sess.wal.close()
            sess.wal = None
        shutil.rmtree(root, ignore_errors=True)
    on, off = legs["on"], legs["off"]
    mismatches = int(not np.array_equal(on["src"], off["src"])) \
        + int(not np.array_equal(on["dst"], off["dst"])) \
        + int(not np.array_equal(on["dst"], on["src"]))
    unexpected = sum(st["rejections"] - st["staged"]
                     for st in fence_stats.values())
    # updates_lost: acknowledged adds the recovered state is missing —
    # the recovered version must equal the acknowledged count, and the
    # fault-free/chaos final states must agree bit for bit
    updates_lost = max(0, n_adds - on["version"]) \
        + max(0, on["updates_lost_at_recovery"])
    return {
        "adds": n_adds,
        "kill_at_publish": kill_at,
        "trainer_killed_info": int(on["killed"]),
        "acked_at_kill_info": on["acked_at_kill"],
        "updates_lost": updates_lost,
        "output_mismatches": mismatches,
        "epoch_fence_rejections_unexpected": unexpected,
        "trainer_recovery_time_s": round(on["recovery_s"], 4),
        "staleness_peak_s_info": round(on["stale_peak"], 4),
        "wal_replay_records_info": on["replayed"],
        "checkpoint_step_info": on["restored_step"],
        # the mvparam wire ledger (fault-free leg: the full stream went
        # through ONE publisher, so the byte count is deterministic):
        # bytes shipped post-codec regress UP; the compressed/raw ratio
        # is _info (dense random deltas don't compress — the ratio
        # documents the traffic, the SparseFilter tests gate the codec)
        "publish_bytes": off["pub_stats"]["publish_bytes"],
        "wire_compressed_ratio_info": round(
            off["pub_stats"]["wire_compressed_ratio"], 4),
    }


def _warm(workload, snap_mgr, buckets) -> None:
    """Compile every bucket outside the timed loop (and outside the
    latency histogram)."""
    snap = snap_mgr.current()
    for b in buckets:
        payloads = [workload._warm_payload() for _ in range(b)]
        workload.run(payloads, b, snap)


def run(duration_s: float = 2.0, clients: int = 32,
        quick: bool = False, trace_path: str = "",
        debug_dump_dir: str = "", flight_path: str = "") -> dict:
    import multiverso_tpu as mv
    from multiverso_tpu import trace
    from multiverso_tpu.dashboard import Dashboard

    # the black-box posture under test: tail-sampled tracing stays ON
    # for the whole bench (the observability A/B prices it), alongside
    # the always-on flight recorder and watchdog
    argv = ["serving_bench", "-log_level=error", "-trace=true",
            "-trace_tail=true"]
    if debug_dump_dir:
        argv.append(f"-debug_dump_dir={debug_dump_dir}")
    mv.init(argv)
    from multiverso_tpu.models.logreg import LogReg, LogRegConfig
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import (EmbeddingNeighbors, InferenceServer,
                                        LMGreedyDecode, LogRegPredict)

    if quick:
        duration_s = min(duration_s, 1.0)

    server = InferenceServer("bench")
    vocab, dim = 8192, 128
    w2v_table = mv.create_table("matrix", vocab, dim, init_value="random",
                                name="serve_w2v")
    w2v = EmbeddingNeighbors(w2v_table, k=8)
    w2v._warm_payload = lambda: 1
    lr_table = mv.create_table("matrix", 10, 129, updater="sgd",
                               name="serve_lr")
    logreg = LogRegPredict(LogReg(LogRegConfig(
        input_size=128, output_size=10, objective_type="softmax"), lr_table))
    logreg._warm_payload = lambda: np.zeros(128, np.float32)
    lm_cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                               n_layers=2, d_ff=128, max_seq=32)
    lm = LMGreedyDecode(TransformerLM(lm_cfg), max_prompt=8, max_new=4)
    lm._warm_payload = lambda: np.ones(4, np.int32)

    # lm compiles are the expensive ones: keep its bucket set minimal
    specs = {
        "w2v": (w2v, dict(max_batch=64, deadline_ms=2.0, max_queue=128,
                          buckets=(1, 8, 64)), clients,
                lambda rng: int(rng.integers(0, vocab))),
        "logreg": (logreg, dict(max_batch=64, deadline_ms=2.0, max_queue=128,
                                buckets=(1, 8, 64)), clients,
                   lambda rng: rng.random(128).astype(np.float32)),
        "lm": (lm, dict(max_batch=8, deadline_ms=4.0, max_queue=64,
                        buckets=(1, 8)), max(4, clients // 4),
               lambda rng: rng.integers(1, 256, 6).astype(np.int32)),
    }

    out: dict = {"bench": "serving", "clients": clients,
                 "duration_s": duration_s, "workloads": {}}
    # chunked-prefill A/B FIRST: its ITL percentiles are the most
    # scheduling-noise-sensitive numbers in this file, so they run
    # before the saturation workloads fill the box with client threads
    # and leftover batcher/engine loops (measured: the same A/B after
    # the closed-loop phase reads ~2x worse on both sides).
    # Long prompts (384) against a model big enough that a fused
    # admission wave costs ~10x one decode step: the regime chunking is
    # FOR (tiny models under-price the stall; the container's ~50 ms
    # scheduling-noise p99 floor would hide it)
    chunk_cfg = TransformerConfig(vocab_size=256, d_model=256, n_heads=4,
                                  n_layers=2, d_ff=768, max_seq=448)
    out["workloads"]["lm_chunked_prefill"] = _chunked_prefill_ab(
        server, TransformerLM(chunk_cfg), quick)
    # paged-KV capacity A/B second: throughput/capacity-led (robust to
    # scheduling noise) but still cleaner before the closed-loop phase
    # saturates the box; equal KV bytes, 4x slots on the paged side
    paged_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                                  n_layers=2, d_ff=256, max_seq=112)
    out["workloads"]["lm_paged_kv"] = _paged_kv_ab(
        server, TransformerLM(paged_cfg), quick)
    # overload A/B next: capacity-led (peak live sequences + count
    # invariants — robust to scheduler noise) and preemption-heavy, so
    # it runs while the box is quiet and its _info latency columns
    # still mean something
    ov_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                               n_layers=2, d_ff=256, max_seq=64)
    out["workloads"]["lm_overload"] = _overload_ab(
        server, TransformerLM(ov_cfg), quick)
    # prefix-cache A/B third: same capacity-led posture as the paged
    # A/B (its gated numbers are block counts and token totals, robust
    # to scheduler noise), run before the box saturates so the _info
    # TTFT columns stay meaningful
    pc_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                               n_layers=2, d_ff=256, max_seq=96)
    out["workloads"]["lm_prefix_cache"] = _prefix_cache_ab(
        server, TransformerLM(pc_cfg), quick)
    # quantized-KV A/B right after it: the same capacity-led posture
    # (gated numbers are peak live sequences and trace counts at an
    # equal byte budget), plus the replayed-trace argmax-match quality
    # number that must be measured while the box is still quiet
    qkv_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_seq=96)
    out["workloads"]["lm_quant_kv"] = _quant_kv_ab(
        server, TransformerLM(qkv_cfg), quick)
    # speculative-decoding A/B fourth: tok/s-led (its gated numbers are
    # a genuine schedule speedup on the repetitive trace, plus the
    # accepted_per_step amortization metric) — run before the box
    # saturates so the speedup measures drafting, not noisy neighbors
    spec_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                                 n_layers=2, d_ff=256, max_seq=80)
    out["workloads"]["lm_spec_decode"] = _spec_decode_ab(
        server, TransformerLM(spec_cfg), quick)
    # sharded-decode A/B fifth: capacity-led like the paged/prefix
    # A/Bs (gated columns are byte and retrace counts, wall clock is
    # _info); needs >= 2 devices (--devices / the dryrun harness), the
    # default 1-device bench archives a skip marker
    out["workloads"]["lm_sharded_decode"] = _sharded_decode_ab(
        server, quick)
    # long-context A/B right after it: same >= 2 device requirement and
    # the most latency-led gates in the file (document TTFT + witness
    # ITL tails), so it runs while the box is still quiet
    out["workloads"]["lm_long_context"] = _long_context_ab(server, quick)
    # observability A/B (tracing-off vs tail-sampled-on) before the
    # closed-loop phase saturates the box — it measures tok/s deltas
    # that must sit in the noise floor, not under 32 client threads
    obs_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_seq=80)
    out["workloads"]["observability"], obs_engine = _observability_ab(
        server, TransformerLM(obs_cfg), quick)
    # lockwatch A/B rides the warm lm_obs engine: witness-off vs -on
    # tok/s (both _info — the delta lives under the noise floor) plus
    # the zero-baseline lock_order_violations gate
    out["workloads"]["lockwatch"] = _lockwatch_ab(server, quick)
    # obs-plane A/B rides the same warm engine: no agents vs a real
    # two-rank wire plane (publisher sockets + collector drain/ack) at
    # 100 ms reports — tok/s _info, the publisher's 0 dropped reports
    # gated (zero-baseline, like watchdog_trips)
    out["workloads"]["obs_plane"] = _obs_plane_ab(server, quick)
    # per-tenant accounting A/B rides the same warm ledger'd engine:
    # ledger-detached vs -attached tok/s (_info), then a 3-tenant
    # tagged pass under a real 2-rank obs plane whose conservation
    # residual (accounting_drift) rides the zero-baseline gate
    out["workloads"]["accounting"] = _accounting_ab(server, obs_engine,
                                                    quick)
    # fleet-chaos A/B before the closed-loop phase: its gated numbers
    # are recovery invariants (counts), but recovery_time_s is a wall
    # clock that should not absorb 32 saturating client threads
    out["workloads"]["lm_fleet_chaos"] = _fleet_chaos_ab(quick)
    # disaggregated prefill/decode A/B rides the same wire plane: the
    # same two engines as a unified pair vs a prefill+decode split at
    # equal hardware — bit-exactness, the decode-ITL ratio and the
    # deterministic KV wire bytes gated, dedup proven by a zero-byte
    # sequential repeat phase
    out["workloads"]["lm_disagg"] = _disagg_ab(quick)
    # trainer-chaos A/B next to it: the TRAINING half's recovery
    # invariants (checkpoint+WAL exactness, epoch fencing, staleness
    # choreography) — count-led gates plus one restart wall clock that
    # should also stay ahead of the saturating closed-loop phase
    out["workloads"]["lm_trainer_chaos"] = _trainer_chaos_ab(quick)
    for name, (workload, knobs, n_clients, payload_fn) in specs.items():
        server.register(name, workload, **knobs)
        server.register(f"{name}_b1", workload, max_batch=1,
                        deadline_ms=knobs["deadline_ms"],
                        max_queue=knobs["max_queue"], buckets=(1,))
        entry = server._entry(name)
        _warm(workload, entry.manager, knobs["buckets"])
        row = _closed_loop(server, name, payload_fn, duration_s, n_clients)
        b1 = _closed_loop(server, f"{name}_b1", payload_fn,
                          min(duration_s, 1.5), n_clients)
        row["qps_batch1"] = b1["qps"]
        row["speedup_batched"] = (round(row["qps"] / b1["qps"], 2)
                                  if b1["qps"] else float("inf"))
        row["jit_traces"] = workload.jit_cache_size()
        out["workloads"][name] = row
    out["max_speedup_batched"] = max(
        r["speedup_batched"] for r in out["workloads"].values()
        if "speedup_batched" in r)
    # continuous-batching decode A/B rides the same JSON line; its own
    # model is sized so per-step compute (which the static path spends
    # cap/mean-fold on dead tokens) outweighs per-iteration dispatch
    ab_cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                               n_layers=2, d_ff=256, max_seq=112)
    out["workloads"]["lm_decode"] = _decode_ab(
        server, TransformerLM(ab_cfg), quick)
    # the FULL instrument state rides the same line: bench archives keep
    # every histogram/gauge/counter/SLO, not just the hand-picked fields
    dash = Dashboard.snapshot()
    out["dashboard"] = dash
    # the standing health gate: a clean bench trips NO watchdog — any
    # trip here is a bug (bench_compare gates watchdog_trips hard)
    out["workloads"]["observability"]["watchdog_trips"] = sum(
        int(row.get("value", 0)) for name, row in dash.items()
        if name.startswith("WATCHDOG_TRIPS[")
        and row.get("type") == "counter")
    if flight_path and obs_engine.recorder is not None:
        obs_engine.recorder.export_jsonl(flight_path)
        out["flight"] = {"file": flight_path,
                         **obs_engine.recorder.summary()}
    if trace_path:
        # retained spans + the flight recorder's counter tracks in ONE
        # Perfetto-loadable document (same epoch-µs timebase)
        doc = trace.export_chrome()
        if obs_engine.recorder is not None:
            doc = obs_engine.recorder.merge_chrome(doc)
        with open(trace_path, "w") as f:
            json.dump(doc, f)
        out["trace"] = {"file": trace_path, **trace.collector().stats()}
    mv.shutdown()
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-duration", type=float, default=2.0,
                    help="seconds of closed-loop load per workload")
    ap.add_argument("-clients", type=int, default=32)
    ap.add_argument("-quick", action="store_true",
                    help="cap duration at 1 s (CI smoke)")
    ap.add_argument("-trace", "--trace", default="",
                    help="write the retained (tail-sampled) request spans "
                         "+ flight-recorder counter tracks as "
                         "Chrome/Perfetto trace JSON here")
    ap.add_argument("--flight", default="",
                    help="dump the observability engine's flight-recorder "
                         "ring (JSONL) here for tools/engine_timeline.py")
    ap.add_argument("--debug_dump_dir", default="",
                    help="watchdog trip bundles land here (passed through "
                         "as -debug_dump_dir)")
    ap.add_argument("--devices", type=int, default=0,
                    help="pin a virtual CPU mesh of N devices before jax "
                         "initializes (the tools/scaling_bench.py pattern) "
                         "so the lm_sharded_decode A/B can run tp>1; "
                         "0 = leave the platform alone (the A/B then "
                         "skips on a 1-device host)")
    args, _ = ap.parse_known_args()
    if args.devices > 0:
        # CLI runs own the process: pin the virtual mesh BEFORE the jax
        # import inside run() fixes the backend (scaling_bench.py:48 —
        # XLA_FLAGS must be set before JAX import, never after)
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.devices}").strip()
    result = run(args.duration, args.clients, args.quick, args.trace,
                 args.debug_dump_dir, args.flight)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
