"""Closed-loop serving load generator -> one JSON line.

Drives the three serving workloads (word2vec neighbor lookup, logreg
predict, LM greedy decode) through ``serving.InferenceServer`` with N
closed-loop clients each (issue -> wait -> issue; sheds back off briefly),
and emits ONE JSON line with qps / p50 / p99 / shed_rate per workload —
the serving counterpart of bench.py's training line, so BENCH rounds can
track both sides of the train/serve stack.

Each workload is also measured with the scheduler degraded to batch=1
(same jitted workload, bucket set {1}) to price micro-batching itself:
``speedup_batched`` is saturated batched qps over batch=1 qps.

Usage::

    JAX_PLATFORMS=cpu python tools/serving_bench.py [-duration 2.0]
        [-clients 32] [-quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _closed_loop(server, model: str, payload_fn, duration_s: float,
                 clients: int) -> dict:
    """N clients issuing blocking predicts for ``duration_s``; returns
    qps/latency/shed stats measured OVER THE LOOP (warmup excluded)."""
    from multiverso_tpu.serving import OverloadedError

    stop = time.monotonic() + duration_s
    counts = [0] * clients
    sheds = [0] * clients

    def client(ix: int) -> None:
        rng = np.random.default_rng(ix)
        while time.monotonic() < stop:
            try:
                server.predict(model, payload_fn(rng), timeout_s=60.0)
                counts[ix] += 1
            except OverloadedError:
                sheds[ix] += 1
                time.sleep(0.0005)          # shed: back off, retry

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120)
    elapsed = time.monotonic() - t0
    done, shed = sum(counts), sum(sheds)
    stats = server.stats(model)
    return {
        "qps": round(done / elapsed, 1),
        "p50_ms": round(stats["p50_ms"], 3),
        "p99_ms": round(stats["p99_ms"], 3),
        "shed_rate": round(shed / (done + shed), 4) if done + shed else 0.0,
        "completed": done,
    }


def _warm(workload, snap_mgr, buckets) -> None:
    """Compile every bucket outside the timed loop (and outside the
    latency histogram)."""
    snap = snap_mgr.current()
    for b in buckets:
        payloads = [workload._warm_payload() for _ in range(b)]
        workload.run(payloads, b, snap)


def run(duration_s: float = 2.0, clients: int = 32,
        quick: bool = False) -> dict:
    import multiverso_tpu as mv

    mv.init(["serving_bench", "-log_level=error"])
    from multiverso_tpu.models.logreg import LogReg, LogRegConfig
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import (EmbeddingNeighbors, InferenceServer,
                                        LMGreedyDecode, LogRegPredict)

    if quick:
        duration_s = min(duration_s, 1.0)

    server = InferenceServer("bench")
    vocab, dim = 8192, 128
    w2v_table = mv.create_table("matrix", vocab, dim, init_value="random",
                                name="serve_w2v")
    w2v = EmbeddingNeighbors(w2v_table, k=8)
    w2v._warm_payload = lambda: 1
    lr_table = mv.create_table("matrix", 10, 129, updater="sgd",
                               name="serve_lr")
    logreg = LogRegPredict(LogReg(LogRegConfig(
        input_size=128, output_size=10, objective_type="softmax"), lr_table))
    logreg._warm_payload = lambda: np.zeros(128, np.float32)
    lm_cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                               n_layers=2, d_ff=128, max_seq=32)
    lm = LMGreedyDecode(TransformerLM(lm_cfg), max_prompt=8, max_new=4)
    lm._warm_payload = lambda: np.ones(4, np.int32)

    # lm compiles are the expensive ones: keep its bucket set minimal
    specs = {
        "w2v": (w2v, dict(max_batch=64, deadline_ms=2.0, max_queue=128,
                          buckets=(1, 8, 64)), clients,
                lambda rng: int(rng.integers(0, vocab))),
        "logreg": (logreg, dict(max_batch=64, deadline_ms=2.0, max_queue=128,
                                buckets=(1, 8, 64)), clients,
                   lambda rng: rng.random(128).astype(np.float32)),
        "lm": (lm, dict(max_batch=8, deadline_ms=4.0, max_queue=64,
                        buckets=(1, 8)), max(4, clients // 4),
               lambda rng: rng.integers(1, 256, 6).astype(np.int32)),
    }

    out: dict = {"bench": "serving", "clients": clients,
                 "duration_s": duration_s, "workloads": {}}
    for name, (workload, knobs, n_clients, payload_fn) in specs.items():
        server.register(name, workload, **knobs)
        server.register(f"{name}_b1", workload, max_batch=1,
                        deadline_ms=knobs["deadline_ms"],
                        max_queue=knobs["max_queue"], buckets=(1,))
        entry = server._entry(name)
        _warm(workload, entry.manager, knobs["buckets"])
        row = _closed_loop(server, name, payload_fn, duration_s, n_clients)
        b1 = _closed_loop(server, f"{name}_b1", payload_fn,
                          min(duration_s, 1.5), n_clients)
        row["qps_batch1"] = b1["qps"]
        row["speedup_batched"] = (round(row["qps"] / b1["qps"], 2)
                                  if b1["qps"] else float("inf"))
        row["jit_traces"] = workload.jit_cache_size()
        out["workloads"][name] = row
    out["max_speedup_batched"] = max(
        r["speedup_batched"] for r in out["workloads"].values())
    mv.shutdown()
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-duration", type=float, default=2.0,
                    help="seconds of closed-loop load per workload")
    ap.add_argument("-clients", type=int, default=32)
    ap.add_argument("-quick", action="store_true",
                    help="cap duration at 1 s (CI smoke)")
    args, _ = ap.parse_known_args()
    result = run(args.duration, args.clients, args.quick)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
