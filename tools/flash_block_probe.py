"""On-chip block-size probe for the flash kernel at the flagship LM
attention shape (r5).

At seq 1024 the default 1024x1024 blocks make the causal kernel compute
the FULL score matrix (one k-block -> nothing to skip), so ~2x the
needed work; finer blocks let the `run` predicate skip above-diagonal
blocks at the cost of more grid steps. This probe measures the real
trade on hardware: vmapped (B=8) fwd+bwd at [B, seq, 12 heads, 64 dim]
— exactly the tools/lm_mfu.py in-model attention call — for a sweep of
(block_q, block_k). One subprocess trace per point (wall clocks lie
through the tunnel; repeated start/stop in-process hangs).

Usage: python tools/flash_block_probe.py [--seq 1024]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _one(seq: int, bq: int, bk: int) -> None:
    import jax
    import jax.numpy as jnp

    from multiverso_tpu.ops.flash_attention import flash_attention
    from tools.xprof_util import trace_device_ms

    B, h, d = 8, 12, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, seq, h, d)), jnp.bfloat16)

    def loss(q, k, v):
        out = jax.vmap(lambda a, b, c: flash_attention(
            a, b, c, causal=True, block_q=bq, block_k=bk))(q, k, v)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    jax.block_until_ready(fn(q, q, q))
    ms = trace_device_ms(lambda: fn(q, q, q))
    print(f"DEVICE_MS {ms:.6f}")


def main(argv=None) -> int:
    if argv is None and len(sys.argv) >= 2 and sys.argv[1] == "--_one":
        _one(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
        return 0
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=1024)
    args = ap.parse_args(argv)

    for bq, bk in ((1024, 1024), (512, 1024), (512, 512), (256, 512),
                   (256, 256), (128, 256)):
        if bq > args.seq or bk > args.seq:
            continue
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_one",
             str(args.seq), str(bq), str(bk)],
            capture_output=True, text=True, timeout=600)
        ms = None
        for line in out.stdout.splitlines():
            if line.startswith("DEVICE_MS "):
                ms = float(line.split()[1])
        if ms is None:
            print(f"bq={bq} bk={bk}: FAILED\n{out.stdout[-800:]}"
                  f"{out.stderr[-800:]}")
            continue
        print(f"seq={args.seq} bq={bq} bk={bk}: {ms:.3f} ms "
              f"(B=8, h=12, d=64, fwd+bwd, device)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
