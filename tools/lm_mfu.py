"""LM training MFU on the real chip (VERDICT r2 item 5).

Measures the TransformerLM train step's DEVICE time via xprof (wall
clocks lie under the tunneled device — see tools/tpu_validate.py) and
divides the step's matmul FLOPs by v5e bf16 peak to report MFU at
seq 1024/2048 with reference vs flash attention.

FLOP accounting (causal-aware, so MFU is not inflated by counting work
the kernels skip):

* matmul params N = L*(4*d^2 + 2*d*d_ff) + d*vocab (the logits head;
  the embedding lookup is a gather, not a matmul);
* forward = 2*N FLOPs/token + attention 2*2*(T/2)*d per layer
  (QK^T and PV over an average causal span of T/2);
* training = 3x forward (bwd does ~2x fwd's matmul work).

Usage: python tools/lm_mfu.py [--out docs/LM_MFU.md] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# v5e: 197 TFLOP/s bf16 per chip (public spec)
PEAK_FLOPS = 197e12
_VOCAB = 256


def train_flops_per_step(d_model: int, n_layers: int, d_ff: int,
                         vocab: int, batch: int, seq: int) -> float:
    n_matmul = n_layers * (4 * d_model * d_model + 2 * d_model * d_ff) \
        + d_model * vocab
    per_token = 6 * n_matmul + 3 * 4 * (seq / 2) * d_model * n_layers
    return per_token * batch * seq


def _measure_one(argv) -> None:
    """Subprocess entry: ONE xprof trace of the jitted train step."""
    import jax.numpy as jnp

    import multiverso_tpu as mv
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from tools.xprof_util import trace_device_ms

    mv.init(["lm_mfu", "-log_level=error"])
    d_model, n_layers, n_heads, d_ff, batch, seq, attn, dtype = argv
    cfg = TransformerConfig(
        vocab_size=_VOCAB, d_model=int(d_model), n_heads=int(n_heads),
        n_layers=int(n_layers), d_ff=int(d_ff), max_seq=int(seq),
        attention=attn,
        dtype=jnp.bfloat16 if dtype == "bf16" else jnp.float32)
    lm = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, _VOCAB, (int(batch), int(seq))).astype(np.int32)
    float(lm.train_batch(toks))                   # compile + land
    ms = trace_device_ms(lambda: lm.train_batch(toks))
    print(f"DEVICE_MS {ms:.6f}")


def measure(d_model, n_layers, n_heads, d_ff, batch, seq, attn, dtype
            ) -> float:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_one",
         str(d_model), str(n_layers), str(n_heads), str(d_ff),
         str(batch), str(seq), attn, dtype],
        capture_output=True, text=True, timeout=900)
    for line in out.stdout.splitlines():
        if line.startswith("DEVICE_MS "):
            return float(line.split()[1])
    raise RuntimeError(f"measure failed:\n{out.stdout[-2000:]}\n"
                       f"{out.stderr[-2000:]}")


def main(argv=None) -> int:
    if argv is None and len(sys.argv) >= 2 and sys.argv[1] == "--_one":
        _measure_one(sys.argv[2:])
        return 0
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)

    # flagship-ish size: 85M matmul params — big enough that the MXU, not
    # dispatch, is the limiter on one chip
    d_model, n_layers, n_heads = 768, 12, 12
    d_ff = 4 * d_model
    rows = []
    seqs = (1024,) if args.quick else (1024, 2048)
    for seq in seqs:
        batch = max(1, (8 * 1024) // seq)         # ~8k tokens/step
        for attn in ("reference", "flash"):
            for dtype in ("bf16",):
                ms = measure(d_model, n_layers, n_heads, d_ff, batch, seq,
                             attn, dtype)
                flops = train_flops_per_step(d_model, n_layers, d_ff,
                                             _VOCAB, batch, seq)
                mfu = flops / (ms / 1e3) / PEAK_FLOPS
                tok_s = batch * seq / (ms / 1e3)
                rows.append({"seq": seq, "batch": batch, "attention": attn,
                             "dtype": dtype, "step_ms": ms,
                             "tok_per_s": tok_s, "mfu": mfu})
                print(f"seq={seq} batch={batch} attn={attn} {dtype}: "
                      f"{ms:.2f} ms/step, {tok_s:,.0f} tok/s, "
                      f"MFU {mfu * 100:.1f}%", flush=True)

    if args.out:
        n_params = n_layers * (4 * d_model ** 2 + 2 * d_model * d_ff) \
            + d_model * _VOCAB
        lines = [
            "# LM training MFU (one v5e chip, device-time via xprof)",
            "",
            f"`tools/lm_mfu.py` — byte-level TransformerLM, d_model "
            f"{d_model}, {n_layers} layers, {n_heads} heads, d_ff {d_ff} "
            f"({n_params / 1e6:.0f}M matmul params), bf16 params, ~8k "
            "tokens/step. MFU = causal-aware matmul FLOPs / device time "
            f"/ {PEAK_FLOPS / 1e12:.0f} TFLOP/s (v5e bf16 peak); the "
            "attention column is TransformerConfig.attention.",
            "",
            "| seq | batch | attention | step ms | tok/s | MFU |",
            "|---|---|---|---|---|---|",
        ]
        for r in rows:
            lines.append(
                f"| {r['seq']} | {r['batch']} | {r['attention']} "
                f"| {r['step_ms']:.2f} | {r['tok_per_s']:,.0f} "
                f"| {r['mfu'] * 100:.1f}% |")
        lines += [
            "",
            "The flash rows are exactly what `attention=\"flash\"` users "
            "get: `best_attention` with the batched crossover (seq 512 "
            "when B > 1 — measured in-model, where flash ties XLA at 512 "
            "and wins above; the standalone single-sequence crossover "
            "stays 1536, docs/TPU_VALIDATE.json). Layers are unrolled by "
            "default (`scan_layers=False`): the layer-stack `lax.scan` "
            "measured +27% device time at this shape (58.7 vs 46.2 "
            "ms/step, r5 re-probe) in scan-carry copies and grad-stack "
            "dynamic-update-slices. No remat: per-layer `jax.checkpoint` "
            "re-probed at +30% (60.1 ms/step) — activations fit HBM at "
            "this scale, so recompute buys nothing.",
            "",
            "r5 step anatomy (xprof per-op at seq 1024): param matmuls "
            "~26.5 ms (~80% of bf16 peak), attention is the rest. Four "
            "measured changes took the flash step 54.1 -> 46.2 ms/step "
            "(45.1% -> 51% MFU): full-length-forward loss (kills the "
            "seq-1023 pad/slice around every kernel, -1.4 ms), "
            "kernel-native bf16 output (-1 ms), a fused one-pass "
            "backward kernel for the one-k-block case (5 dots vs the "
            "two-pass 7, -3.6 ms), and a plain-softmax one-k-block "
            "forward kernel (no online-softmax carries, -1.1 ms). "
            "Measured rejections, same shape: finer block sizes "
            "(512/256 — causal-skip savings lose to grid overhead, "
            "tools/flash_block_probe.py), fused QKV concat gemm "
            "(-0.18 ms only), and the r4 `_pad_dim` question — "
            "lane-padded vs unpadded d=64 is a 0.27% wash in-model "
            "(53.94 vs 54.08 ms pre-fusion), so the r4 snapshot's '30% "
            "of the train step' padding attribution was wrong; the "
            "unpadded form stays for its halved VMEM footprint.",
            "",
        ]
        with open(args.out, "w") as f:
            f.write("\n".join(lines))
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
