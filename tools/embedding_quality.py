"""Embedding-quality probe: batched update semantics vs the reference's.

VERDICT r1 item 5: the batched scatter path deviates from the reference's
sequential per-pair updates (``Applications/WordEmbedding/src/
wordembedding.cpp:120-168``) in two tunable ways — summed colliding grads
(row_mean off) or capped row-mean (row_mean on, ``row_update_cap``). This
tool quantifies what those semantics do to embedding QUALITY, not just loss:

* corpus: synthetic clustered language — K topic clusters; each sentence
  samples words from one cluster (plus shared stop-words), so ground truth
  is known: words of a cluster should embed near each other.
* probe: nearest-neighbor purity (fraction of content words whose cosine
  nearest neighbor is in their own cluster) and the within-minus-across
  cluster mean-cosine gap.

Runs a small sweep (reference-semantics small batch; summed and row-mean
variants at large batch; cap sweep) and writes a markdown table. The
numbers behind ``docs/EMBEDDING_QUALITY.md`` and the CLI's auto default.

Usage: python tools/embedding_quality.py [--quick] [--out docs/EMBEDDING_QUALITY.md]
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def make_clustered_corpus(path: str, n_clusters: int = 8,
                          words_per_cluster: int = 40, n_stop: int = 12,
                          n_sentences: int 	= 30000, sent_len: int = 12,
                          stop_rate: float = 0.25, seed: int = 7):
    """Write the corpus; returns {word: cluster_id} (stop words -> -1)."""
    rng = random.Random(seed)
    clusters = [[f"c{k}w{i}" for i in range(words_per_cluster)]
                for k in range(n_clusters)]
    stops = [f"the{i}" for i in range(n_stop)]
    labels = {w: k for k, ws in enumerate(clusters) for w in ws}
    labels.update({w: -1 for w in stops})
    with open(path, "w") as f:
        for _ in range(n_sentences):
            k = rng.randrange(n_clusters)
            words = [rng.choice(stops) if rng.random() < stop_rate
                     else rng.choice(clusters[k]) for _ in range(sent_len)]
            f.write(" ".join(words) + "\n")
    return labels


def load_vectors(path: str):
    words, vecs = [], []
    with open(path) as f:
        f.readline()
        for line in f:
            parts = line.rstrip("\n").split(" ")
            words.append(parts[0])
            vecs.append([float(x) for x in parts[1:]])
    return words, np.asarray(vecs, np.float32)


def probe(words, vecs, labels):
    """(nn_purity, cosine_gap) over content words."""
    norms = np.linalg.norm(vecs, axis=1, keepdims=True)
    unit = vecs / np.maximum(norms, 1e-9)
    lab = np.array([labels.get(w, -1) for w in words])
    content = lab >= 0
    sim = unit @ unit.T
    np.fill_diagonal(sim, -np.inf)
    sim[:, ~content] = -np.inf          # neighbors restricted to content
    nn = sim.argmax(axis=1)
    purity = float(np.mean(lab[content] == lab[nn[content]]))
    c = np.flatnonzero(content)
    s = unit[c] @ unit[c].T
    same = lab[c][:, None] == lab[c][None, :]
    off = ~np.eye(len(c), dtype=bool)
    gap = float(s[same & off].mean() - s[~same].mean())
    return purity, gap


def run_config(corpus, labels, tag, batch_size, row_mean, cap,
               epochs=3, size=64, static=False, shared=0):
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Word2VecConfig, train
    from multiverso_tpu.runtime import Session

    Session._instance = None
    mv.init([tag])
    try:
        cfg = Word2VecConfig(embedding_size=size, window=5, negative=5,
                             batch_size=batch_size, init_lr=0.05,
                             row_mean_updates=row_mean, row_update_cap=cap,
                             row_mean_static=static, seed=3,
                             shared_negatives=shared)
        out = tempfile.NamedTemporaryFile(suffix=".vec", delete=False).name
        res = train(corpus, out, cfg, epochs=epochs, min_count=1,
                    sample=1e-3, log_every=0)
        words, vecs = load_vectors(out)
        os.unlink(out)
        purity, gap = probe(words, vecs, labels)
        return {"tag": tag, "batch": batch_size,
                "row_mean": row_mean, "cap": cap,
                "loss": res.final_loss, "pairs_per_sec": res.pairs_per_sec,
                "nn_purity": purity, "cos_gap": gap}
    finally:
        mv.shutdown()
        Session._instance = None


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="smaller corpus / fewer epochs")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    corpus = os.path.join(tempfile.gettempdir(), "eq_corpus.txt")
    n_sent = 8000 if args.quick else 30000
    epochs = 2 if args.quick else 3
    labels = make_clustered_corpus(corpus, n_sentences=n_sent)

    # vocab = 8*40 + 12 = 332 content+stop words. cap*vocab ~ 2.6k: the
    # 16k batch is ~50 expected hits per row -> deep in divergence regime.
    configs = [
        ("reference-semantics small batch", 1024, False, 8.0, False, 0),
        ("summed large batch", 16384, False, 8.0, False, 0),
        ("row-mean cap=1 large batch", 16384, True, 1.0, False, 0),
        ("row-mean cap=8 large batch", 16384, True, 8.0, False, 0),
        ("row-mean cap=32 large batch", 16384, True, 32.0, False, 0),
        ("row-mean cap=64 large batch", 16384, True, 64.0, False, 0),
        ("STATIC row-mean cap=8 large batch", 16384, True, 8.0, True, 0),
        # group-shared negatives (VERDICT r2 item 1): each group of G
        # consecutive pairs shares one K-negative draw — the 2.8x
        # throughput mode. Swept at the cap=8 large-batch baseline.
        ("shared negatives G=2, cap=8", 16384, True, 8.0, False, 2),
        ("shared negatives G=4, cap=8", 16384, True, 8.0, False, 4),
        ("shared negatives G=8, cap=8", 16384, True, 8.0, False, 8),
        ("shared negatives G=16, cap=8", 16384, True, 8.0, False, 16),
    ]
    rows = []
    for name, batch, rm, cap, static, shared in configs:
        r = run_config(corpus, labels, name, batch, rm, cap, epochs=epochs,
                       static=static, shared=shared)
        r["name"] = name
        r["shared"] = shared
        print(f"{name:36s} loss {r['loss']:.4f} "
              f"nn_purity {r['nn_purity']:.3f} gap {r['cos_gap']:.3f}",
              flush=True)
        rows.append(r)

    lines = [
        "# Embedding quality: batched semantics vs reference sequential",
        "",
        "Produced by `tools/embedding_quality.py` (synthetic 8-cluster corpus,",
        f"{n_sent} sentences, {epochs} epochs, dim 64, window 5, 5 negatives;",
        "higher nn-purity / cosine-gap = better cluster recovery; chance",
        "purity = 1/8 = 0.125).",
        "",
        "| config | batch | row_mean | cap | G | final loss | NN purity | cos gap |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['name']} | {r['batch']} | {r['row_mean']} | {r['cap']:g} "
            f"| {r.get('shared', 0)} "
            f"| {r['loss']:.4f} | {r['nn_purity']:.3f} | {r['cos_gap']:.3f} |")
    ref = rows[0]
    cap8 = next((r for r in rows if r["row_mean"] and r["cap"] == 8.0
                 and not r.get("shared")), None)
    lines += [
        "",
        f"Reference-semantics baseline purity: **{ref['nn_purity']:.3f}**.",
    ]
    if cap8 is not None:
        lines += [
            f"The default cap=8 at 16k batch reaches purity "
            f"{cap8['nn_purity']:.3f} / gap {cap8['cos_gap']:.3f} — parity "
            f"with the reference-semantics baseline, while the uncapped sum "
            f"diverges (NaN) and very large caps re-diverge; this is the "
            f"evidence behind the `row_update_cap = 8` default.",
        ]
    shared_rows = [r for r in rows if r.get("shared")]
    if shared_rows and cap8 is not None:
        ok = [r for r in shared_rows
              if r["nn_purity"] >= ref["nn_purity"] - 0.02
              and r["cos_gap"] >= 0.9 * ref["cos_gap"]]
        best = max((r["shared"] for r in ok), default=0)
        lines += [
            "",
            "Group-shared negatives (`-shared_negatives=G`) share one",
            "K-negative draw across each group of G consecutive pairs,",
            "cutting the dominant negative gather/scatter traffic by G",
            "(same objective in expectation — every pair still sees K",
            "negatives from the unigram^0.75 law, they are just correlated",
            "within a group).",
            (f"Parity bar: purity within 0.02 and cos-gap within 10% of the "
             f"reference-semantics baseline. Largest G at parity: **{best}**."
             if best else
             "No swept G met the parity bar (purity within 0.02, cos-gap "
             "within 10% of baseline)."),
            "",
            "Note the probe is deliberately harsh on G: its ~332-word",
            "vocab makes within-group negative correlation ~200x denser",
            "than text8's 71k vocab (each word re-drawn ~G*K*B/(G*vocab)",
            "times per step), so a G that passes here has headroom at",
            "real vocab sizes. Throughput context (bench.py, text8 shape,",
            "one v5e chip): exact draws ~3.1M pairs/s, G=4 ~6.9M, G=8",
            "~8.7M — the bench default is the largest G at parity.",
        ]
    lines += [
        "",
        "The capped row-mean path is the large-batch divergence guard: the",
        "auto default in `apps/wordembedding.py` estimates the hottest",
        "row's expected colliding grads per step from the sampling laws",
        "and enables the cap past ~512 expected hits (stable at ~150,",
        "divergent by ~2300 — zipf corpora concentrate collisions on the",
        "head words). See",
        "`models/word2vec.py` `row_mean_updates`/`row_update_cap` docs for",
        "the mechanism; reference sequential loop:",
        "`Applications/WordEmbedding/src/wordembedding.cpp:120-168`.",
        "",
    ]
    text = "\n".join(lines)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
