"""Embedding-quality probe: batched update semantics vs the reference's.

VERDICT r1 item 5: the batched scatter path deviates from the reference's
sequential per-pair updates (``Applications/WordEmbedding/src/
wordembedding.cpp:120-168``) in two tunable ways — summed colliding grads
(row_mean off) or capped row-mean (row_mean on, ``row_update_cap``). This
tool quantifies what those semantics do to embedding QUALITY, not just loss:

* corpus: synthetic clustered language — K topic clusters; each sentence
  samples words from one cluster (plus shared stop-words), so ground truth
  is known: words of a cluster should embed near each other.
* probe: nearest-neighbor purity (fraction of content words whose cosine
  nearest neighbor is in their own cluster) and the within-minus-across
  cluster mean-cosine gap.

Runs a small sweep (reference-semantics small batch; summed and row-mean
variants at large batch; cap sweep) and writes a markdown table. The
numbers behind ``docs/EMBEDDING_QUALITY.md`` and the CLI's auto default.

Usage: python tools/embedding_quality.py [--quick] [--out docs/EMBEDDING_QUALITY.md]
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def make_clustered_corpus(path: str, n_clusters: int = 8,
                          words_per_cluster: int = 40, n_stop: int = 12,
                          n_sentences: int 	= 30000, sent_len: int = 12,
                          stop_rate: float = 0.25, seed: int = 7):
    """Write the corpus; returns {word: cluster_id} (stop words -> -1)."""
    rng = random.Random(seed)
    clusters = [[f"c{k}w{i}" for i in range(words_per_cluster)]
                for k in range(n_clusters)]
    stops = [f"the{i}" for i in range(n_stop)]
    labels = {w: k for k, ws in enumerate(clusters) for w in ws}
    labels.update({w: -1 for w in stops})
    with open(path, "w") as f:
        for _ in range(n_sentences):
            k = rng.randrange(n_clusters)
            words = [rng.choice(stops) if rng.random() < stop_rate
                     else rng.choice(clusters[k]) for _ in range(sent_len)]
            f.write(" ".join(words) + "\n")
    return labels


def make_realscale_corpus(path: str, vocab: int = 71291,
                          n_clusters: int = 1000, cluster_size: int = 8,
                          n_tokens: int = 8_000_000, sent_len: int = 16,
                          topical_rate: float = 0.5, p_in: float = 0.6,
                          rank_lo: int = 100, rank_hi: int = 20000,
                          seed: int = 13):
    """text8-SCALE probe corpus (VERDICT r3 item 7): the full 71k zipf
    vocabulary of the bench corpus, with planted semantic clusters.

    The r3 probe's 332-word vocab makes within-group negative correlation
    ~200x denser than text8's — too harsh a G bar. This corpus keeps the
    REAL collision structure (71k vocab, zipf(1) unigram law, the frozen
    bench batch shape) while planting recoverable ground truth:

    * clusters are ``cluster_size`` words of CONSECUTIVE zipf rank in
      [rank_lo, rank_hi) — homogeneous within-cluster frequency, clusters
      spanning the head-to-mid spectrum (ultra-head words act as
      stop-words and stay unplanted; deep-tail words occur too rarely to
      learn in a bounded run);
    * a sentence is topical with prob ``topical_rate`` (topic uniform
      over clusters); topical sentences draw each word from the cluster
      with prob ``p_in``, else from the global zipf law — so cluster
      words strongly co-occur on top of a realistic background.

    Returns {word: cluster_id} for the planted words.
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab + 1)
    probs = 1.0 / ranks
    probs /= probs.sum()

    # consecutive-rank clusters, evenly spaced over [rank_lo, rank_hi)
    span = rank_hi - rank_lo
    stride = max(span // n_clusters, cluster_size)
    cluster_words = np.stack([
        np.arange(rank_lo + k * stride, rank_lo + k * stride + cluster_size)
        for k in range(n_clusters)])              # [C, size] word ids
    labels = {f"w{w}": k for k, ws in enumerate(cluster_words) for w in ws}

    n_sent = n_tokens // sent_len
    topical = rng.random(n_sent) < topical_rate
    topic = rng.integers(0, n_clusters, n_sent)
    words = rng.choice(vocab, size=(n_sent, sent_len), p=probs)
    in_cluster = (rng.random((n_sent, sent_len)) < p_in) & topical[:, None]
    member = rng.integers(0, cluster_size, (n_sent, sent_len))
    planted = cluster_words[topic[:, None], member]
    words = np.where(in_cluster, planted, words)
    # guarantee full-vocab dictionary coverage (as bench.py's corpus does):
    # a shuffled enumeration padded to a whole number of sentences
    perm = rng.permutation(vocab)
    pad = (-len(perm)) % sent_len
    cover = np.concatenate([perm, perm[:pad]]).reshape(-1, sent_len)
    words[:cover.shape[0], :] = cover
    with open(path, "w") as f:
        for row in words:
            f.write(" ".join(f"w{w}" for w in row) + "\n")
    return labels


def probe_subset(words, vecs, labels, bands=None):
    """(nn_purity, cosine_gap[, per-band rows]) over ONLY the planted
    cluster words — at 71k vocab the full sim matrix is 20 GB; the
    planted subset (C x size words) is what ground truth exists for
    anyway.

    ``bands``: optional list of (name, lo_rank, hi_rank) — word ids ARE
    zipf ranks in the synthetic corpora, so banding by id splits the
    planted clusters into frequency strata. The per-band rows answer the
    TAIL-sensitivity question the aggregate can hide: an approximation
    (e.g. G-shared negatives) could hold the head and quietly damage
    rare words.
    """
    idx = [i for i, w in enumerate(words) if w in labels]
    lab = np.array([labels[words[i]] for i in idx])
    rank = np.array([int(words[i][1:]) for i in idx])   # "w123" -> 123
    sub = vecs[idx]
    unit = sub / np.maximum(np.linalg.norm(sub, axis=1, keepdims=True), 1e-9)
    sim = unit @ unit.T
    np.fill_diagonal(sim, -np.inf)
    nn = sim.argmax(axis=1)
    hit = lab == lab[nn]
    same = lab[:, None] == lab[None, :]
    off = ~np.eye(len(idx), dtype=bool)

    def _gap(mask_rows):
        s = sim[mask_rows]
        sm = same[mask_rows]
        offm = off[mask_rows]
        return float(s[sm & offm].mean()
                     - s[~sm & offm][:: max(len(idx) // 64, 1)].mean())

    purity = float(hit.mean())
    gap = _gap(np.ones(len(idx), bool))
    if bands is None:
        return purity, gap
    rows = []
    for name, lo, hi in bands:
        m = (rank >= lo) & (rank < hi)
        if m.sum() == 0:
            continue
        rows.append({"band": name, "n": int(m.sum()),
                     "purity": float(hit[m].mean()), "gap": _gap(m)})
    return purity, gap, rows


def load_vectors(path: str):
    words, vecs = [], []
    with open(path) as f:
        f.readline()
        for line in f:
            parts = line.rstrip("\n").split(" ")
            words.append(parts[0])
            vecs.append([float(x) for x in parts[1:]])
    return words, np.asarray(vecs, np.float32)


def probe(words, vecs, labels):
    """(nn_purity, cosine_gap) over content words."""
    norms = np.linalg.norm(vecs, axis=1, keepdims=True)
    unit = vecs / np.maximum(norms, 1e-9)
    lab = np.array([labels.get(w, -1) for w in words])
    content = lab >= 0
    sim = unit @ unit.T
    np.fill_diagonal(sim, -np.inf)
    sim[:, ~content] = -np.inf          # neighbors restricted to content
    nn = sim.argmax(axis=1)
    purity = float(np.mean(lab[content] == lab[nn[content]]))
    c = np.flatnonzero(content)
    s = unit[c] @ unit[c].T
    same = lab[c][:, None] == lab[c][None, :]
    off = ~np.eye(len(c), dtype=bool)
    gap = float(s[same & off].mean() - s[~same].mean())
    return purity, gap


def run_config(corpus, labels, tag, batch_size, row_mean, cap,
               epochs=3, size=64, static=False, shared=0):
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Word2VecConfig, train
    from multiverso_tpu.runtime import Session

    Session._instance = None
    mv.init([tag])
    try:
        cfg = Word2VecConfig(embedding_size=size, window=5, negative=5,
                             batch_size=batch_size, init_lr=0.05,
                             row_mean_updates=row_mean, row_update_cap=cap,
                             row_mean_static=static, seed=3,
                             shared_negatives=shared)
        out = tempfile.NamedTemporaryFile(suffix=".vec", delete=False).name
        res = train(corpus, out, cfg, epochs=epochs, min_count=1,
                    sample=1e-3, log_every=0)
        words, vecs = load_vectors(out)
        os.unlink(out)
        purity, gap = probe(words, vecs, labels)
        return {"tag": tag, "batch": batch_size,
                "row_mean": row_mean, "cap": cap,
                "loss": res.final_loss, "pairs_per_sec": res.pairs_per_sec,
                "nn_purity": purity, "cos_gap": gap}
    finally:
        mv.shutdown()
        Session._instance = None


def run_realscale_config(corpus, labels, tag, shared, epochs=3,
                         heldout_corpus=None, heldout_counts=None):
    """One G configuration at the FROZEN bench shape (BASELINE.md):
    71k vocab, dim 200, 64k batch, oversample 2.5, negative pool,
    static capped row-mean — the exact config whose throughput the
    bench records, so the quality verdict transfers 1:1.

    With ``heldout_corpus`` set, also evaluates the trained model's
    held-out skip-gram NS likelihood (:func:`heldout_nll`) — the
    generalization guard the in-sample loss and the saturating
    planted-cluster bar cannot provide (VERDICT r4 item 4)."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Word2VecConfig, train
    from multiverso_tpu.runtime import Session

    Session._instance = None
    mv.init([tag, "-log_level=error"])
    try:
        cfg = Word2VecConfig(embedding_size=200, window=5, negative=5,
                             batch_size=65536, init_lr=0.025,
                             oversample=2.5, neg_pool_size=1 << 22,
                             row_mean_updates=True, row_mean_static=True,
                             shared_negatives=shared, seed=3)
        out = tempfile.NamedTemporaryFile(suffix=".vec", delete=False).name
        out_ctx = (tempfile.NamedTemporaryFile(
            suffix=".vec", delete=False).name if heldout_corpus else None)
        res = train(corpus, out, cfg, epochs=epochs, min_count=1,
                    sample=1e-3, log_every=0, output_path_ctx=out_ctx)
        words, vecs = load_vectors(out)
        row = {"tag": tag, "shared": shared, "loss": res.final_loss,
               "pairs_per_sec": res.pairs_per_sec}
        if heldout_corpus:
            row["heldout_nll"] = heldout_nll(
                words, vecs, load_vectors(out_ctx)[1], heldout_corpus,
                heldout_counts)
            os.unlink(out_ctx)
        os.unlink(out)
        purity, gap, bands = probe_subset(
            words, vecs, labels,
            bands=[("head [100,1k)", 100, 1000),
                   ("mid [1k,5k)", 1000, 5000),
                   ("tail [5k,20k)", 5000, 20000)])
        row.update({"nn_purity": purity, "cos_gap": gap, "bands": bands})
        return row
    finally:
        mv.shutdown()
        Session._instance = None


def split_heldout(corpus: str, train_path: str, heldout_path: str,
                  every: int = 8, skip_first: int = 0):
    """Interleaved sentence split: every ``every``-th line past the first
    ``skip_first`` (the full-vocab coverage block, which must stay in
    TRAIN so the dictionary reaches every word) goes to the held-out
    file, the rest to the train file. Interleaving keeps both splits on
    the same distribution (the corpus has no document structure)."""
    with open(corpus) as f, open(train_path, "w") as tr, \
            open(heldout_path, "w") as ho:
        for i, line in enumerate(f):
            if i >= skip_first and (i - skip_first) % every == 0:
                ho.write(line)
            else:
                tr.write(line)


def heldout_nll(words, w_in, w_ctx, heldout_corpus, counts,
                window: int = 5, negative: int = 5,
                max_pairs: int = 2_000_000, seed: int = 17) -> float:
    """Mean held-out skip-gram negative-sampling NLL.

    For each held-out (center c, context o) pair within the full
    window: ``-log sig(u_o . v_c) - sum_k log sig(-u_nk . v_c)`` with
    ``negative`` FRESH exact unigram^0.75 draws (fixed seed) — the
    reference training objective (``WE/src/wordembedding.cpp:120-168``)
    evaluated on unseen text, so it measures what any training-time
    negative-sharing relaxation (G) does to generalization, on the
    exact-draw objective regardless of how the model was trained.
    Deterministic: full window (no shrink), no subsampling, seeded
    negatives and pair subsample.
    """
    idx = {w: i for i, w in enumerate(words)}
    sents = []
    with open(heldout_corpus) as f:
        for line in f:
            toks = line.split()
            ids = [idx[t] for t in toks if t in idx]
            if len(ids) > 1:
                sents.append(np.asarray(ids, np.int32))
    # window pairs, vectorized per offset (sentences are fixed-length
    # lines here, but ragged input works too)
    lens = np.asarray([len(s) for s in sents])
    centers, contexts = [], []
    for d in range(1, window + 1):
        keep = lens > d
        c = np.concatenate([sents[i][:-d] for i in np.flatnonzero(keep)])
        o = np.concatenate([sents[i][d:] for i in np.flatnonzero(keep)])
        centers += [c, o]          # both directions
        contexts += [o, c]
    centers = np.concatenate(centers)
    contexts = np.concatenate(contexts)
    rng = np.random.default_rng(seed)
    if centers.size > max_pairs:
        sel = rng.choice(centers.size, size=max_pairs, replace=False)
        centers, contexts = centers[sel], contexts[sel]
    # counts is TOKEN-ID-indexed ("w{id}"), but embedding rows follow the
    # dictionary's first-occurrence order (the corpus opens with a
    # SHUFFLED coverage block, so rows are a random permutation of ids);
    # realign the negative law to ROW order so draws index real words
    tok_ids = np.asarray([int(w[1:]) for w in words])
    p = counts[tok_ids].astype(np.float64) ** 0.75
    p /= p.sum()
    w_in = np.asarray(w_in, np.float32)
    w_ctx = np.asarray(w_ctx, np.float32)
    total, n = 0.0, 0
    chunk = 1 << 18
    for i in range(0, centers.size, chunk):
        c = centers[i:i + chunk]
        o = contexts[i:i + chunk]
        v = w_in[c]                                   # [m, D]
        pos = np.einsum("md,md->m", w_ctx[o], v)
        negs = rng.choice(len(p), size=(c.size, negative), p=p)
        neg = np.einsum("mkd,md->mk", w_ctx[negs], v)
        # -log sig(x) = logaddexp(0, -x), stable
        total += np.logaddexp(0, -pos).sum()
        total += np.logaddexp(0, neg).sum()
        n += c.size
    return float(total / n)


_RS_BEGIN = "<!-- realscale:begin -->"
_RS_END = "<!-- realscale:end -->"


def realscale_sweep(out_path: str = "", quick: bool = False,
                    gs=(0, 16, 32, 64)):
    """VERDICT r3 item 7: re-probe the G cap at the real text8 shape."""
    gs = tuple(gs)
    if not gs or gs[0] != 0:
        # rows[0] is used as the exact-draw reference below; a --gs list
        # not starting with 0 would silently rebase every Δ column on a
        # shared-draw run (ADVICE r4)
        gs = (0,) + tuple(g for g in gs if g != 0)
    corpus = os.path.join(tempfile.gettempdir(), "eq_real_corpus.txt")
    n_tokens = 2_000_000 if quick else 8_000_000
    n_clusters = 250 if quick else 1000
    epochs = 2 if quick else 3
    labels = make_realscale_corpus(corpus, n_tokens=n_tokens,
                                   n_clusters=n_clusters)
    rows = []
    for g in gs:
        r = run_realscale_config(corpus, labels, f"rs_g{g}", g,
                                 epochs=epochs)
        print(f"realscale G={g}: loss {r['loss']:.4f} purity "
              f"{r['nn_purity']:.3f} gap {r['cos_gap']:.3f} "
              f"({r['pairs_per_sec'] / 1e6:.2f}M pairs/s)", flush=True)
        rows.append(r)
    ref = rows[0]

    def band_parity(r):
        """Tail-sensitivity bar: EVERY frequency band must hold parity
        (purity within 0.02, gap within 10% of the same band's exact-draw
        baseline) — the aggregate can hide rare-word damage."""
        ref_bands = {b["band"]: b for b in ref["bands"]}
        return all(b["purity"] >= ref_bands[b["band"]]["purity"] - 0.02
                   and b["gap"] >= 0.9 * ref_bands[b["band"]]["gap"]
                   for b in r["bands"] if b["band"] in ref_bands)

    ok = [r for r in rows[1:]
          if r["nn_purity"] >= ref["nn_purity"] - 0.02
          and r["cos_gap"] >= 0.9 * ref["cos_gap"]
          and band_parity(r)]
    best = max((r["shared"] for r in ok), default=0)
    # Loss guard (round 4): the planted-cluster bar is ONE-SIDED (it
    # rejects degradation; improvement passes) and saturates at real
    # scale — gaps improve monotonically with G — so it stops
    # discriminating. Final training loss on the actual objective is
    # the guard the bar cannot provide: cap the recommendation at <1%
    # drift off the exact-draw baseline.
    guarded = [r for r in ok if r["loss"] <= 1.01 * ref["loss"]]
    best_guarded = max((r["shared"] for r in guarded), default=0)
    lines = [
        _RS_BEGIN,
        "## Real-scale G probe (71k-vocab, frozen bench config)",
        "",
        "Produced by `tools/embedding_quality.py --realscale`: the full",
        f"text8 vocabulary (71,291 words, zipf unigram law), {n_clusters}",
        "planted 8-word clusters of consecutive rank in [100, 20k),",
        f"{n_tokens / 1e6:.0f}M tokens, {epochs} epochs, at the EXACT frozen",
        "bench config (dim 200, 64k batch, oversample 2.5, static capped",
        "row-mean — BASELINE.md). The r3 probe above is ~200x denser in",
        "within-group negative correlation than text8; this one has the",
        "real collision structure, so its G verdict transfers to the",
        "bench corpus 1:1. (pairs/s below is THIS probe run's own rate,",
        "not the idle-chip bench — see BASELINE.md for bench rates.)",
        "",
        "| G | final loss | Δloss | NN purity | cos gap | pairs/s |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        dl = ("—" if r is ref else
              f"{(r['loss'] / ref['loss'] - 1) * 100:+.1f}%")
        lines.append(f"| {r['shared']} | {r['loss']:.4f} | {dl} "
                     f"| {r['nn_purity']:.3f} | {r['cos_gap']:.3f} "
                     f"| {r['pairs_per_sec'] / 1e6:.2f}M |")
    lines += [
        "",
        "Per-frequency-band breakdown (word ids are zipf ranks; the",
        "aggregate could hide rare-word damage — G-shared draws touch",
        "head rows most, so the TAIL bands are the sensitivity check):",
        "",
        "| G | " + " | ".join(
            f"{b['band']} purity / gap" for b in rows[0]["bands"]) + " |",
        "|---|" + "---|" * len(rows[0]["bands"]),
    ]
    for r in rows:
        cells = " | ".join(f"{b['purity']:.3f} / {b['gap']:.3f}"
                           for b in r["bands"])
        lines.append(f"| {r['shared']} | {cells} |")
    lines += [
        "",
        (f"Parity bar (ONE-SIDED degradation bar: purity within 0.02 "
         f"below and cos-gap no more than 10% below the exact-draw G=0 "
         f"baseline — improvement passes — in aggregate AND in every "
         f"frequency band): largest G at parity = **{best}**. "
         f"Loss guard (final training loss within 1% of exact-draw — "
         f"the check the saturating cluster bar cannot make): largest "
         f"G = **{best_guarded}**. The bench default is the loss-guarded "
         f"value, additionally capped by measured on-chip throughput "
         f"saturation (BASELINE.md)."),
        _RS_END,
    ]
    text = "\n".join(lines)
    if out_path:
        from tools.docsplice import splice

        splice(out_path, text, _RS_BEGIN, _RS_END)
        print(f"wrote {out_path}")
    else:
        print(text)
    return rows, best


_HO_BEGIN = "<!-- heldout:begin -->"
_HO_END = "<!-- heldout:end -->"


def heldout_sweep(out_path: str = "", quick: bool = False,
                  gs=(0, 16, 64, 128)):
    """VERDICT r4 item 4: a HELD-OUT likelihood guard for the G default.

    The realscale sweep's loss guard is in-sample (final training loss);
    this sweep splits the realscale corpus, trains each G on the train
    split at the frozen bench config, and scores held-out skip-gram NS
    NLL under the EXACT-draw objective (:func:`heldout_nll`). The G cap
    criterion becomes out-of-sample: largest G whose held-out NLL stays
    within 1% of the exact-draw baseline's.
    """
    gs = tuple(gs)
    if not gs or gs[0] != 0:
        gs = (0,) + tuple(g for g in gs if g != 0)
    tmp = tempfile.gettempdir()
    corpus = os.path.join(tmp, "eq_ho_full.txt")
    train_c = os.path.join(tmp, "eq_ho_train.txt")
    held_c = os.path.join(tmp, "eq_ho_held.txt")
    n_tokens = 2_000_000 if quick else 8_000_000
    n_clusters = 250 if quick else 1000
    epochs = 2 if quick else 3
    sent_len = 16
    labels = make_realscale_corpus(corpus, n_tokens=n_tokens,
                                   n_clusters=n_clusters,
                                   sent_len=sent_len)
    # the full-vocab coverage block must stay in TRAIN (dictionary
    # coverage); hold out every 8th sentence after it
    vocab = 71291
    skip = -(-vocab // sent_len)
    split_heldout(corpus, train_c, held_c, every=8, skip_first=skip)
    # negative-draw law for the evaluation = TRAIN-corpus unigram counts
    # (what training's sampler used)
    counts = np.zeros(vocab, np.int64)
    with open(train_c) as f:
        for line in f:
            ids = [int(t[1:]) for t in line.split()]
            np.add.at(counts, ids, 1)
    rows = []
    for g in gs:
        r = run_realscale_config(train_c, labels, f"ho_g{g}", g,
                                 epochs=epochs, heldout_corpus=held_c,
                                 heldout_counts=counts)
        print(f"heldout G={g}: train-loss {r['loss']:.4f} "
              f"heldout-NLL {r['heldout_nll']:.4f} "
              f"purity {r['nn_purity']:.3f}", flush=True)
        rows.append(r)
    ref = rows[0]
    guarded = [r for r in rows
               if r["heldout_nll"] <= 1.01 * ref["heldout_nll"]]
    best = max((r["shared"] for r in guarded), default=0)
    lines = [
        _HO_BEGIN,
        "## Held-out likelihood guard for the G default",
        "",
        "Produced by `tools/embedding_quality.py --heldout`: the",
        "realscale corpus split 7:1 (interleaved sentences; the",
        "full-vocab coverage block stays in train), each G trained on",
        "the train split at the frozen bench config, then scored on the",
        "held-out split as mean skip-gram negative-sampling NLL under",
        "the EXACT-draw objective (5 fresh unigram^0.75 negatives per",
        "pair, fixed seed, full window, no subsampling) — out-of-sample",
        "generalization on the reference objective, independent of the",
        "training-time draw-sharing relaxation being probed.",
        "",
        "| G | train loss | held-out NLL | ΔNLL vs exact |",
        "|---|---|---|---|",
    ]
    for r in rows:
        d = ("—" if r is ref else
             f"{(r['heldout_nll'] / ref['heldout_nll'] - 1) * 100:+.2f}%")
        lines.append(f"| {r['shared']} | {r['loss']:.4f} "
                     f"| {r['heldout_nll']:.4f} | {d} |")
    lines += [
        "",
        f"Held-out guard (NLL within 1% of the exact-draw baseline): "
        f"largest G = **{best}**. This — not the in-sample training "
        f"loss — is the cap criterion the bench default cites "
        f"(BASELINE.md); the in-sample loss guard and the saturating "
        f"planted-cluster bar remain as secondary checks "
        f"(sections above).",
        _HO_END,
    ]
    text = "\n".join(lines)
    if out_path:
        from tools.docsplice import splice

        splice(out_path, text, _HO_BEGIN, _HO_END)
        print(f"wrote {out_path}")
    else:
        print(text)
    return rows, best


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="smaller corpus / fewer epochs")
    ap.add_argument("--realscale", action="store_true",
                    help="71k-vocab G probe at the frozen bench config "
                         "(appends its own section to --out)")
    ap.add_argument("--gs", default="0,16,32,64",
                    help="comma-separated G values for --realscale")
    ap.add_argument("--heldout", action="store_true",
                    help="held-out NS-NLL G guard at the frozen bench "
                         "config (appends its own section to --out)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (e.g. accelerator tunnel "
                         "down); quality verdicts are backend-independent")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    if args.cpu:
        import jax

        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    if args.heldout:
        gs = tuple(int(g) for g in args.gs.split(","))
        if args.gs == ap.get_default("gs"):
            gs = (0, 16, 64, 128)   # the VERDICT r4 item-4 sweep
        heldout_sweep(args.out, quick=args.quick, gs=gs)
        return 0
    if args.realscale:
        realscale_sweep(args.out, quick=args.quick,
                        gs=tuple(int(g) for g in args.gs.split(",")))
        return 0

    corpus = os.path.join(tempfile.gettempdir(), "eq_corpus.txt")
    n_sent = 8000 if args.quick else 30000
    epochs = 2 if args.quick else 3
    labels = make_clustered_corpus(corpus, n_sentences=n_sent)

    # vocab = 8*40 + 12 = 332 content+stop words. cap*vocab ~ 2.6k: the
    # 16k batch is ~50 expected hits per row -> deep in divergence regime.
    configs = [
        ("reference-semantics small batch", 1024, False, 8.0, False, 0),
        ("summed large batch", 16384, False, 8.0, False, 0),
        ("row-mean cap=1 large batch", 16384, True, 1.0, False, 0),
        ("row-mean cap=8 large batch", 16384, True, 8.0, False, 0),
        ("row-mean cap=32 large batch", 16384, True, 32.0, False, 0),
        ("row-mean cap=64 large batch", 16384, True, 64.0, False, 0),
        ("STATIC row-mean cap=8 large batch", 16384, True, 8.0, True, 0),
        # group-shared negatives (VERDICT r2 item 1): each group of G
        # consecutive pairs shares one K-negative draw — the 2.8x
        # throughput mode. Swept at the cap=8 large-batch baseline.
        ("shared negatives G=2, cap=8", 16384, True, 8.0, False, 2),
        ("shared negatives G=4, cap=8", 16384, True, 8.0, False, 4),
        ("shared negatives G=8, cap=8", 16384, True, 8.0, False, 8),
        ("shared negatives G=16, cap=8", 16384, True, 8.0, False, 16),
    ]
    rows = []
    for name, batch, rm, cap, static, shared in configs:
        r = run_config(corpus, labels, name, batch, rm, cap, epochs=epochs,
                       static=static, shared=shared)
        r["name"] = name
        r["shared"] = shared
        print(f"{name:36s} loss {r['loss']:.4f} "
              f"nn_purity {r['nn_purity']:.3f} gap {r['cos_gap']:.3f}",
              flush=True)
        rows.append(r)

    lines = [
        "# Embedding quality: batched semantics vs reference sequential",
        "",
        "Produced by `tools/embedding_quality.py` (synthetic 8-cluster corpus,",
        f"{n_sent} sentences, {epochs} epochs, dim 64, window 5, 5 negatives;",
        "higher nn-purity / cosine-gap = better cluster recovery; chance",
        "purity = 1/8 = 0.125).",
        "",
        "| config | batch | row_mean | cap | G | final loss | NN purity | cos gap |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['name']} | {r['batch']} | {r['row_mean']} | {r['cap']:g} "
            f"| {r.get('shared', 0)} "
            f"| {r['loss']:.4f} | {r['nn_purity']:.3f} | {r['cos_gap']:.3f} |")
    ref = rows[0]
    cap8 = next((r for r in rows if r["row_mean"] and r["cap"] == 8.0
                 and not r.get("shared")), None)
    lines += [
        "",
        f"Reference-semantics baseline purity: **{ref['nn_purity']:.3f}**.",
    ]
    if cap8 is not None:
        lines += [
            f"The default cap=8 at 16k batch reaches purity "
            f"{cap8['nn_purity']:.3f} / gap {cap8['cos_gap']:.3f} — parity "
            f"with the reference-semantics baseline, while the uncapped sum "
            f"diverges (NaN) and very large caps re-diverge; this is the "
            f"evidence behind the `row_update_cap = 8` default.",
        ]
    shared_rows = [r for r in rows if r.get("shared")]
    if shared_rows and cap8 is not None:
        ok = [r for r in shared_rows
              if r["nn_purity"] >= ref["nn_purity"] - 0.02
              and r["cos_gap"] >= 0.9 * ref["cos_gap"]]
        best = max((r["shared"] for r in ok), default=0)
        lines += [
            "",
            "Group-shared negatives (`-shared_negatives=G`) share one",
            "K-negative draw across each group of G consecutive pairs,",
            "cutting the dominant negative gather/scatter traffic by G",
            "(same objective in expectation — every pair still sees K",
            "negatives from the unigram^0.75 law, they are just correlated",
            "within a group).",
            (f"Parity bar: purity within 0.02 and cos-gap within 10% of the "
             f"reference-semantics baseline (one-sided — improvement "
             f"passes). Largest G at parity: **{best}** — on THIS harsh "
             f"probe; the real-scale probe below supersedes it for the "
             f"bench default (loss-guarded, see its section)."
             if best else
             "No swept G met the parity bar (purity within 0.02, cos-gap "
             "within 10% of baseline)."),
            "",
            "Note the probe is deliberately harsh on G: its ~332-word",
            "vocab makes within-group negative correlation ~200x denser",
            "than text8's 71k vocab (each word re-drawn ~G*K*B/(G*vocab)",
            "times per step), so a G that passes here has headroom at",
            "real vocab sizes — which is why the real-scale probe, not",
            "this one, sets the bench default.",
        ]
    lines += [
        "",
        "The capped row-mean path is the large-batch divergence guard: the",
        "auto default in `apps/wordembedding.py` estimates the hottest",
        "row's expected colliding grads per step from the sampling laws",
        "and enables the cap past ~512 expected hits (stable at ~150,",
        "divergent by ~2300 — zipf corpora concentrate collisions on the",
        "head words). See",
        "`models/word2vec.py` `row_mean_updates`/`row_update_cap` docs for",
        "the mechanism; reference sequential loop:",
        "`Applications/WordEmbedding/src/wordembedding.cpp:120-168`.",
        "",
    ]
    text = "\n".join(lines)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
