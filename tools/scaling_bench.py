"""Virtual-mesh dp scaling harness (VERDICT r2 item 2).

Rehearses the BASELINE.json scaling methodology (docs/DISTRIBUTED.md
"Scaling methodology") with MEASURED numbers instead of prose: runs the
jitted word2vec train step and raw `psum`/`all_gather` collectives at
dp = 1/2/4/8 on the virtual CPU mesh and reports weak-scaling efficiency
and collective time/byte.

Honesty note baked into the output: this host exposes N virtual devices
over `os.cpu_count()` real cores. When cores < devices the devices
TIMESHARE the cores, so raw weak-scaling efficiency is bounded by
cores/dp regardless of framework overhead. The number that transfers to
real hardware (one core/chip per device) is the *normalized* efficiency

    eff_norm(dp) = dp * T(1) / (min(dp, cores) * T(dp))

which charges the unavoidable compute timesharing to the machine and
leaves sharding/collective overhead — the thing the framework controls —
in the measurement. On a real pod (cores >= dp) eff_norm == raw
efficiency, i.e. the reference's 3.40x/4-worker-style number
(`binding/python/docs/BENCHMARK.md:54-57`).

Usage:
  python tools/scaling_bench.py [--devices 8] [--json] [--quick]
  python tools/scaling_bench.py --out docs/DISTRIBUTED.md   # rewrite table

The same sweep (tiny shapes) runs inside ``__graft_entry__.dryrun_multichip``
so every round's MULTICHIP_r*.json records the numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __name__ == "__main__":
    # CLI runs own the process: pin the virtual CPU mesh BEFORE the jax
    # import below fixes the backend. Library importers (the dryrun, the
    # tests) already configured their platform — mutating it for them
    # mid-process would silently retarget all their jax work.
    _i = sys.argv.index("--devices") if "--devices" in sys.argv else -1
    _n = sys.argv[_i + 1] if 0 <= _i < len(sys.argv) - 1 else "8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_n}").strip()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _best_of(fn, repeats: int) -> float:
    """Best-of-N wall time of ``fn()`` (each call must block)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def collective_sweep(dps, payload_mb: float = 4.0, repeats: int = 5,
                     inner: int = 4):
    """Time `psum` and `all_gather` on a fixed PER-DEVICE payload at each dp.

    Returns rows with per-op wall time and algorithmic bandwidth
    (payload / time — the BASELINE methodology's `mv.aggregate` probe,
    step 2). ``inner`` chained ops per dispatch amortise dispatch cost.
    """
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n_elem = int(payload_mb * (1 << 20) / 4)
    rows = []
    for dp in dps:
        devs = np.array(jax.devices()[:dp])
        mesh = Mesh(devs, ("dp",))
        x = jax.device_put(
            np.ones((dp, n_elem), np.float32),
            jax.sharding.NamedSharding(mesh, P("dp")))

        @jax.jit
        def psum_n(x):
            def one(v):
                # re-introduce per-shard variance (0*idx) so the scan carry
                # stays device-varying after the collective reduces it
                idx = jax.lax.axis_index("dp").astype(v.dtype)

                def body(c, _):
                    return jax.lax.psum(c, "dp") / dp + 0.0 * idx, None
                return jax.lax.scan(body, v, None, length=inner)[0]
            f = shard_map(one, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
            return f(x)

        @jax.jit
        def gather_n(x):
            def one(v):
                idx = jax.lax.axis_index("dp").astype(v.dtype)

                # fold the gathered axis back down so the carry shape is
                # stable under scan (sum stands in for "consume the copy")
                def body(c, _):
                    g = jax.lax.all_gather(c, "dp")      # [dp, n]
                    return g.sum(axis=0) / dp + 0.0 * idx, None
                return jax.lax.scan(body, v, None, length=inner)[0]
            f = shard_map(one, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
            return f(x)

        for name, fn in (("psum", psum_n), ("all_gather", gather_n)):
            fn(x).block_until_ready()       # compile
            t = _best_of(lambda: fn(x).block_until_ready(), repeats) / inner
            rows.append({
                "op": name, "dp": dp, "payload_mb": payload_mb,
                "time_ms": t * 1e3,
                # algorithmic bandwidth: bytes reduced/gathered per second
                "algbw_gbps": (payload_mb / 1024) / t,
            })
    return rows


def w2v_weak_scaling(dps, per_dev_batch: int = 2048, vocab: int = 20000,
                     dim: int = 128, steps: int = 25, repeats: int = 5,
                     dp_sync: str = "dispatch"):
    """Weak-scaling sweep of the REAL jitted word2vec train step.

    Fixed per-device batch; the batch axis is sharded over the mesh
    ``worker`` axis — the exact program a dp pod runs (BASELINE
    methodology step 1). ``steps`` is the dispatch cadence: the default
    25 matches real training (bench.py / the app driver fuse 25 batches
    per dispatch), which is what amortises the per-dispatch delta
    exchange of ``dp_sync="dispatch"``; pass 1 to measure the unamortised
    per-batch cost, or ``dp_sync="batch"`` for the per-batch GSPMD BSP
    program (a table-sized allreduce every scan iteration).
    """
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig
    from multiverso_tpu.runtime import Session

    rows = []
    for dp in dps:
        Session._instance = None
        mv.set_flag("mesh_shape", f"{dp},1")
        mv.init([f"scale{dp}", "-log_level=error"])
        try:
            batch = per_dev_batch * dp
            cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim,
                                 negative=5, batch_size=batch,
                                 steps_per_call=steps, seed=3,
                                 dp_sync=dp_sync)
            w_in = mv.create_table("matrix", vocab, dim, init_value="random")
            w_out = mv.create_table("matrix", vocab, dim)
            model = Word2Vec(cfg, w_in, w_out,
                             counts=np.ones(vocab, np.float64))
            rng = np.random.default_rng(dp)
            centers = rng.integers(0, vocab, (steps, batch)).astype(np.int32)
            contexts = rng.integers(0, vocab, (steps, batch)).astype(np.int32)
            mask = np.ones((steps, batch), np.float32)

            def run():
                float(model.train_batches(centers, contexts, mask))

            run()                            # compile
            t = _best_of(run, repeats)
            rows.append({
                "dp": dp, "batch": batch, "steps": steps,
                "time_ms": t * 1e3,
                "pairs_per_sec": steps * batch / t,
            })
        finally:
            mv.shutdown()
            mv.set_flag("mesh_shape", "")
            Session._instance = None
    return rows


def efficiencies(rows, cores: int):
    """Raw + timeshare-normalized weak-scaling efficiency vs the dp=1 row.

    Ideal weak-scaling wall time with C cores timesharing dp devices is
    ``T(1) * dp / min(dp, C)`` (total compute scales with dp; at most
    min(dp, C) cores execute it). eff = ideal / actual.
    """
    t1 = next(r["time_ms"] for r in rows if r["dp"] == 1)
    out = []
    for r in rows:
        dp = r["dp"]
        raw = t1 / r["time_ms"]
        norm = dp * t1 / (min(dp, cores) * r["time_ms"])
        # UNclamped: > 1 means the timeshare model under-charges the
        # machine at this shape (sublinear tiny-shape timing) — annotate
        # so readers discount it rather than mistaking it for headroom
        out.append({**r, "eff_raw": raw, "eff_norm": norm,
                    "saturated": bool(norm > 1.0 + 1e-9),
                    "overhead_frac": max(0.0, 1.0 - norm)})
    return out


def quick_sweep(dps):
    """The ONE quick-shape rehearsal parameterization — shared by the
    test floor (`tests/test_scaling.py`) and `run_sweep(quick=True)`, so
    both measure the same program (real dispatch cadence, tiny shapes)."""
    return efficiencies(
        w2v_weak_scaling(dps, per_dev_batch=512, vocab=4096, dim=64,
                         steps=25, repeats=3),
        os.cpu_count() or 1)


def dryrun_sweep(dps):
    """The REAL-shape sweep the dryrun embeds in MULTICHIP_r*.json —
    same shape + cadence as the docs/DISTRIBUTED.md table (batch 2048/dev,
    vocab 20k, dim 128, 25-batch dispatches), reduced repeats so the
    dryrun stays bounded. This is the honest number: the quick shapes
    saturate the timeshare normalisation (eff_norm > 1 artifacts) and say
    nothing about the exchange cost at real table sizes."""
    return efficiencies(
        w2v_weak_scaling(dps, per_dev_batch=2048, vocab=20000, dim=128,
                         steps=25, repeats=2),
        os.cpu_count() or 1)


def run_sweep(n_devices: int = 8, quick: bool = False):
    dps = [d for d in (1, 2, 4, 8, 16, 32) if d <= n_devices]
    cores = os.cpu_count() or 1
    if quick:
        w2v = quick_sweep(dps)
        cadence = []
    else:
        w2v = efficiencies(
            w2v_weak_scaling(dps, per_dev_batch=2048, vocab=20000,
                             dim=128, repeats=5),
            cores)
        # dispatch-cadence amortisation at the widest dp: the per-dispatch
        # delta exchange is a fixed cost, so efficiency is a function of
        # steps_per_call (real training runs 25)
        top = max(dps)
        cadence = []
        for steps in (1, 4, 25):
            rows = w2v_weak_scaling([1, top], per_dev_batch=2048,
                                    vocab=20000, dim=128, steps=steps,
                                    repeats=3)
            cadence.append(efficiencies(rows, cores)[-1])
    coll = collective_sweep(dps, payload_mb=1.0 if quick else 4.0,
                            repeats=3 if quick else 5)
    return {"cores": cores, "devices": n_devices, "w2v": w2v,
            "cadence": cadence, "collectives": coll}


_BEGIN = "<!-- scaling_bench:begin -->"
_END = "<!-- scaling_bench:end -->"


def render_markdown(res) -> str:
    cores = res["cores"]
    lines = [
        _BEGIN,
        "### Measured: virtual-mesh dp weak scaling (this host)",
        "",
        f"`tools/scaling_bench.py` on {res['devices']} virtual CPU devices "
        f"over **{cores} real core(s)**. With cores < dp the devices",
        "timeshare the cores, so raw efficiency is bounded by cores/dp",
        "by construction; `eff_norm = dp*T(1)/(min(dp, cores)*T(dp))`",
        "charges that to the machine and isolates the framework's",
        "sharding + collective overhead — the quantity the ≥90%",
        "8→64-chip target is about (each real chip has its own compute).",
        "Values > 1 are reported unclamped and flagged `(sat)`: they mean",
        "the timeshare model under-charges the machine at that shape, not",
        "that the framework beat ideal.",
        "",
        "word2vec jitted train step, `dp_sync=\"dispatch\"` (workers train",
        "locally, ONE summed-delta psum per dispatch), fixed per-device",
        "batch, real dispatch cadence (weak scaling):",
        "",
        "| dp | global batch | steps/dispatch | dispatch ms | pairs/s "
        "| eff_raw | eff_norm | sync overhead |",
        "|---|---|---|---|---|---|---|---|",
    ]

    def _eff(r, k):
        return f"{r[k]:.2f}" + (" (sat)" if r.get("saturated") else "")

    for r in res["w2v"]:
        lines.append(
            f"| {r['dp']} | {r['batch']} | {r.get('steps', '?')} "
            f"| {r['time_ms']:.1f} "
            f"| {r['pairs_per_sec']:.3g} | {r['eff_raw']:.2f} "
            f"| {_eff(r, 'eff_norm')} | {r['overhead_frac'] * 100:.0f}% |")
    if res.get("cadence"):
        top = res["cadence"][0]["dp"]
        lines += [
            "",
            f"Dispatch-cadence amortisation at dp={top}: the delta "
            "exchange is a fixed per-dispatch cost, so efficiency is a "
            "function of `steps_per_call` (real training fuses 25 "
            "batches/dispatch — bench.py and the app driver):",
            "",
            "| steps/dispatch | dispatch ms | eff_norm | sync overhead |",
            "|---|---|---|---|",
        ]
        for r in res["cadence"]:
            lines.append(
                f"| {r['steps']} | {r['time_ms']:.1f} | {_eff(r, 'eff_norm')} "
                f"| {r['overhead_frac'] * 100:.0f}% |")
    lines += [
        "",
        "Raw collectives, fixed per-device payload "
        f"({res['collectives'][0]['payload_mb']:g} MB f32):",
        "",
        "| op | dp | time/op ms | algbw GB/s |",
        "|---|---|---|---|",
    ]
    for r in res["collectives"]:
        lines.append(f"| {r['op']} | {r['dp']} | {r['time_ms']:.2f} "
                     f"| {r['algbw_gbps']:.2f} |")
    lines += [
        "",
        "#### Bytes on the wire (per device, per 25-batch dispatch, "
        "real shape: V=20k, D=128, f32, ring-collective cost "
        "`2(dp-1)/dp · bytes`)",
        "",
        "| dp data plane | what moves | bytes @ dp=8 |",
        "|---|---|---|",
        "| per-batch BSP (`dp_sync=\"batch\"`, r3) | 2-3 table-sized "
        "allreduces EVERY scan iteration: `S × ~2.5 × V·D·4 × 2(dp-1)/dp` "
        "| ~1.1 GB |",
        "| delta exchange (`dp_sync=\"dispatch\"`, r4 default) | ONE fused "
        "allreduce of 2 table deltas per dispatch: `2 × V·D·4 × 2(dp-1)/dp` "
        "| ~36 MB |",
        "| keyed rows (async bus, cross-process) | touched rows only: "
        "`S × N·(D+1)·4` per publisher | ~29 MB |",
        "",
        "The r4 step compiles to exactly one `all-reduce` op "
        "(f32[V,D] × 2 + loss — verified in the dp=8 HLO); the reference "
        "never ships a dense table either (sparse-filtered row-bucket "
        "Adds, `src/table/sparse_matrix_table.cpp:145-153`). What remains "
        "in `sync overhead` above is the exchange's table-shaped "
        "arithmetic (delta subtract/add + the psum memcpy) serialised "
        "through this host's single core — on a real pod that arithmetic "
        "is parallel per chip and the wire cost is ~36 MB over ICI "
        "(sub-ms at v5e bandwidths). On real v5e the same sweep runs "
        "unchanged per chip count (methodology steps 1-2 above).",
        _END,
    ]
    return "\n".join(lines)


def splice_into(path: str, block: str) -> None:
    from tools.docsplice import splice

    # first insertion lands before the next section after the
    # "Scaling methodology" numbered list
    splice(path, block, _BEGIN, _END, anchor="## Failure recovery")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="print the sweep as one JSON object")
    ap.add_argument("--out", default="",
                    help="markdown file to splice the results table into")
    args = ap.parse_args(argv)

    res = run_sweep(args.devices, quick=args.quick)
    if args.json:
        print(json.dumps(res))
    else:
        for r in res["w2v"]:
            print(f"w2v dp={r['dp']}: {r['time_ms']:.1f} ms "
                  f"eff_raw {r['eff_raw']:.2f} eff_norm {r['eff_norm']:.2f}",
                  flush=True)
        for r in res["collectives"]:
            print(f"{r['op']} dp={r['dp']}: {r['time_ms']:.2f} ms "
                  f"({r['algbw_gbps']:.2f} GB/s)", flush=True)
    if args.out:
        splice_into(args.out, render_markdown(res))
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
