"""Two-process x multi-device dp scaling over the REAL gRPC control plane
(VERDICT r3 item 8).

The single-process virtual-mesh sweep (`tools/scaling_bench.py`) cannot
see cross-process costs: the coordination-service handshake, the
cross-process collective transport, the bus. This harness launches TWO
OS processes x 4 virtual CPU devices each (8 devices total, the same
device count as the single-process sweep) joined through a real
`jax.distributed` coordinator over localhost, and measures the SAME
jitted word2vec program both ways:

* **sync** — one global mesh {worker: 2, server: 4}: the worker axis
  spans the processes, so `dp_sync="dispatch"`'s per-dispatch delta psum
  rides the cross-process CPU collective transport (the DCN stand-in);
  each process feeds its batch shard via
  `make_array_from_process_local_data`.
* **async** — per-process local meshes; cross-process sync rides the
  p2p delta bus instead of in-jit collectives (the reference's default
  mode). Throughput = aggregate pairs/s of both ranks between two
  drain barriers.

Reference analogue: the 4-process benchmark table
`binding/python/docs/BENCHMARK.md:54-57` in the Multiverso reference.

Usage:
  python tools/dcn_bench.py            # driver: spawns workers, prints table
  python tools/dcn_bench.py --json     # one JSON object
  python tools/dcn_bench.py --out docs/DISTRIBUTED.md   # splice the table
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# shared shape: the scaling_bench real-shape methodology at 8 devices
VOCAB, DIM, PER_DEV_BATCH, STEPS = 20000, 128, 2048, 25

# keyed-vs-dense comparison at the REAL text8 shape (the frozen bench
# config: 71,291-word vocab, 200 dims, zipf corpus, G=64 shared
# negatives) with per-batch dispatches — the cross-HOST sync cadence a
# DCN deployment would run (dense at this shape is ~57 MB/table per
# dispatch; ICI affords that, DCN does not)
R_VOCAB, R_DIM, R_BATCH, R_CAP = 71291, 200, 16384, 12288

_WORKER = textwrap.dedent("""
    import json, os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    sys.path.insert(0, %(repo)r)
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    mode = os.environ["MV_DCN_MODE"]
    rank = int(os.environ["MV_PROCESS_ID"])
    nproc = int(os.environ["MV_NUM_PROCESSES"])
    VOCAB, DIM, PB, S = %(vocab)d, %(dim)d, %(pb)d, %(steps)d
    n_local_dev = 4

    if mode in ("densepb", "keyed"):
        # real text8 shape, per-batch dispatch, zipf ids (the wire size
        # of the keyed exchange depends on the touched-row union, so the
        # id distribution must be the bench corpus's, not uniform)
        VOCAB, DIM, B = %(r_vocab)d, %(r_dim)d, %(r_batch)d
        mv.init(["w", "-sync=true", "-mesh_shape=%%d,4" %% nproc,
                 "-log_level=error"])
        ranks_ = np.arange(1, VOCAB + 1)
        probs = 1.0 / ranks_; probs /= probs.sum()
        cfg = Word2VecConfig(vocab_size=VOCAB, embedding_size=DIM,
                             negative=5, shared_negatives=64,
                             batch_size=B, steps_per_call=1, seed=3,
                             dp_sync="dispatch",
                             dp_exchange=("keyed" if mode == "keyed"
                                          else "dense"),
                             dp_keyed_cap=%(r_cap)d)
        w_in = mv.create_table("matrix", VOCAB, DIM, init_value="random")
        w_out = mv.create_table("matrix", VOCAB, DIM)
        model = Word2Vec(cfg, w_in, w_out,
                         counts=probs * 4e6)
        # per-rank stream, but the SAME ids across the two modes so the
        # dense-vs-keyed dispatch times compare on identical work
        rng = np.random.default_rng(7 + rank)
        Bl = B // nproc
        def draw():
            c = rng.choice(VOCAB, size=(1, Bl), p=probs).astype(np.int32)
            t = rng.choice(VOCAB, size=(1, Bl), p=probs).astype(np.int32)
            return c, t, np.ones((1, Bl), np.float32)
        c, t, m = draw()
        float(model.train_batches(c, t, m))          # compile
        mv.barrier()
        union = {}
        if rank == 0:
            before_in = np.asarray(w_in.get())
            before_out = np.asarray(w_out.get())
        c, t, m = draw()
        float(model.train_batches(c, t, m))
        if rank == 0:
            union = {
                "union_in": int(np.any(
                    np.asarray(w_in.get()) != before_in, 1).sum()),
                "union_out": int(np.any(
                    np.asarray(w_out.get()) != before_out, 1).sum()),
            }
        mv.barrier()
        best = 1e9
        for _ in range(3):
            c, t, m = draw()
            t0 = time.perf_counter()
            float(model.train_batches(c, t, m))
            best = min(best, time.perf_counter() - t0)
        mv.barrier()
        print(json.dumps({"mode": mode, "rank": rank,
                          "dispatch_ms": best * 1e3,
                          "global_pairs_per_dispatch": B, **union}),
              flush=True)
        mv.shutdown()
        sys.exit(0)

    if mode == "sync":
        mv.init(["w", "-sync=true", "-mesh_shape=%%d,4" %% nproc,
                 "-log_level=error"])
        B = PB * n_local_dev * nproc          # global batch (weak scaling)
    else:
        mv.init(["w", "-sync=false", "-log_level=error"])
        B = PB * n_local_dev                  # per-process batch
    cfg = Word2VecConfig(vocab_size=VOCAB, embedding_size=DIM, negative=5,
                         batch_size=B, steps_per_call=S, seed=3)
    w_in = mv.create_table("matrix", VOCAB, DIM, init_value="random")
    w_out = mv.create_table("matrix", VOCAB, DIM)
    model = Word2Vec(cfg, w_in, w_out, counts=np.ones(VOCAB, np.float64))
    rng = np.random.default_rng(rank)
    # sync mode: each process passes its LOCAL batch shard (worker axis
    # spans processes); async: the whole per-process batch
    Bl = B // nproc if mode == "sync" else B
    c = rng.integers(0, VOCAB, (S, Bl)).astype(np.int32)
    t = rng.integers(0, VOCAB, (S, Bl)).astype(np.int32)
    m = np.ones((S, Bl), np.float32)

    def run():
        float(model.train_batches(c, t, m))

    run()                                     # compile
    mv.barrier()
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); run(); best = min(best,
                                                    time.perf_counter() - t0)
    mv.barrier()
    pairs = S * (B * nproc if mode != "sync" else B)
    print(json.dumps({"mode": mode, "rank": rank,
                      "dispatch_ms": best * 1e3,
                      "global_pairs_per_dispatch": pairs}), flush=True)
    mv.shutdown()
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_mode(mode: str, tmpdir: str, nproc: int = 2):
    port = _free_port()
    script = os.path.join(tmpdir, f"dcn_{mode}.py")
    with open(script, "w") as f:
        f.write(_WORKER % {"repo": _REPO, "vocab": VOCAB, "dim": DIM,
                           "pb": PER_DEV_BATCH, "steps": STEPS,
                           "r_vocab": R_VOCAB, "r_dim": R_DIM,
                           "r_batch": R_BATCH, "r_cap": R_CAP})
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "MV_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "MV_NUM_PROCESSES": str(nproc),
            "MV_PROCESS_ID": str(rank),
            "MV_DCN_MODE": mode,
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        })
        procs.append(subprocess.Popen(
            [sys.executable, script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    rows = []
    try:
        for rank, proc in enumerate(procs):
            try:
                out, _ = proc.communicate(timeout=900)
            except subprocess.TimeoutExpired:
                raise RuntimeError(f"{mode} rank {rank} timed out")
            if proc.returncode != 0:
                raise RuntimeError(
                    f"{mode} rank {rank} failed:\n{out[-4000:]}")
            for line in out.splitlines():
                if line.startswith("{"):
                    rows.append(json.loads(line))
    finally:
        # never leave a wedged worker pinning the CPU/coordinator (the
        # round-3 zombie lesson: orphans poison every later measurement)
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rows


def single_process_reference():
    """dp=8 single-process dispatch time at the same shape (the number the
    cross-process runs are compared against)."""
    from tools.scaling_bench import w2v_weak_scaling

    rows = w2v_weak_scaling([1, 8], per_dev_batch=PER_DEV_BATCH,
                            vocab=VOCAB, dim=DIM, steps=STEPS, repeats=2)
    return {r["dp"]: r for r in rows}


_BEGIN = "<!-- dcn_bench:begin -->"
_END = "<!-- dcn_bench:end -->"


def render(res) -> str:
    sp = res["single"]
    lines = [
        _BEGIN,
        "### Measured: 2-process x 4-device dp over the real control plane",
        "",
        "`tools/dcn_bench.py` — same 8 total devices and shape as the",
        "single-process sweep, but split across two OS processes joined by",
        "a real `jax.distributed` coordinator (localhost gRPC). The delta",
        "vs the single-process dp=8 row isolates the cross-process cost",
        "the virtual mesh cannot see (control plane + cross-process",
        "collective transport for sync; the p2p bus for async).",
        "",
        "| config | global batch | dispatch ms | pairs/s | vs 1-proc dp=8 |",
        "|---|---|---|---|---|",
    ]
    one = sp[8]["time_ms"]
    base_pps = sp[8]["pairs_per_sec"]
    lines.append(f"| 1 proc x 8 dev (reference) | {sp[8]['batch']} "
                 f"| {one:.0f} | {base_pps:.3g} | 1.00 |")
    for mode in ("sync", "async"):
        rows = res[mode]
        ms = max(r["dispatch_ms"] for r in rows)
        pairs = rows[0]["global_pairs_per_dispatch"]
        pps = pairs / (ms / 1e3)
        lines.append(f"| 2 proc x 4 dev, {mode} | {pairs // STEPS} "
                     f"| {ms:.0f} | {pps:.3g} | {pps / base_pps:.2f} |")
    if "keyed" in res and res["keyed"]:
        dense_b = R_VOCAB * R_DIM * 4
        keyed_b = R_VOCAB * 4 + R_CAP * R_DIM * 4
        u = next((r for r in res["keyed"] if "union_in" in r), {})
        dms = max(r["dispatch_ms"] for r in res["densepb"])
        kms = max(r["dispatch_ms"] for r in res["keyed"])
        lines += [
            "",
            "#### Keyed vs dense dispatch at the REAL shape "
            f"(V={R_VOCAB:,}, D={R_DIM}, per-batch dispatch, B={R_BATCH:,} "
            "zipf ids, G=64)",
            "",
            "| exchange | bytes/table/dispatch | dispatch ms (2-proc) | "
            "measured dirty union (in / out) |",
            "|---|---|---|---|",
            f"| dense (`dp_exchange=\"dense\"`) | {dense_b / 1e6:.1f} MB "
            f"| {dms:.0f} | — |",
            f"| keyed (`dp_exchange=\"keyed\"`, cap {R_CAP:,}) "
            f"| {keyed_b / 1e6:.1f} MB (**{dense_b / keyed_b:.1f}x "
            f"smaller**) | {kms:.0f} "
            + "| {} / {} rows |".format(
                *(f"{u[k]:,}" if k in u else "?"
                  for k in ("union_in", "union_out"))),
            "",
            "Keyed wire = V*4 (psum'd row-moved mask) + cap*D*4 (psum'd "
            "union rows); exact — an over-cap union falls back to the "
            "dense psum inside the dispatch (replicated-predicate cond), "
            "so the cap tunes wire size, never correctness "
            "(`tests/test_word2vec.py` keyed-vs-dense oracle). The "
            "dispatch-ms column reads opposite to the bytes column ON "
            "THIS HOST because the localhost 'wire' is shared memory "
            "(dense psum ~free) while the keyed form's extra table "
            "sweeps (row-moved mask over [V,D], gather/scatter) run "
            "serialized across 8 virtual devices x 2 processes on one "
            "core — microseconds of VPU work per real chip. On a real "
            "multi-host pod the economics invert: DCN moves 5.6x fewer "
            "bytes per dispatch, which is the binding resource the "
            "reference's sparse-filtered Adds also optimise for "
            "(`src/table/sparse_matrix_table.cpp:145-153`).",
        ]
    lines += [
        "",
        "(async trains 2 independent per-process replicas — its row counts "
        "aggregate pairs across both ranks; staleness is the bus poll "
        "interval. sync is one global-mesh SPMD program whose per-dispatch "
        "delta psum crosses the process boundary.)",
        "",
        "Reading the absolute ratios on THIS host: the two worker",
        "processes are two full XLA CPU runtimes timesharing ONE core —",
        "cross-process collectives spin-wait while the peer computes, so",
        "the core is double-booked in a way real multi-host deployment",
        "(own cores per host) never is. The transferable findings are",
        "(a) both cross-process paths run the full program end-to-end",
        "through the real coordinator, and (b) sync's in-jit",
        "cross-process delta psum costs about the same as the async bus",
        "path at this shape — the control plane itself is not the",
        "bottleneck.",
        _END,
    ]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        res = {
            "single": single_process_reference(),
            "sync": run_mode("sync", td),
            "async": run_mode("async", td),
            "densepb": run_mode("densepb", td),
            "keyed": run_mode("keyed", td),
        }
    if args.json:
        print(json.dumps(res, default=str))
    else:
        print(render(res))
    if args.out:
        from tools.docsplice import splice

        splice(args.out, render(res), _BEGIN, _END,
               anchor="## Failure recovery")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    # pin the 8-virtual-device CPU platform BEFORE jax initialises (the
    # single-process reference sweep runs in THIS process)
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, _REPO)
    sys.exit(main())
