"""Diff two serving_bench JSON lines -> regression verdict (exit code).

The standing perf gate for serving PRs: run ``tools/serving_bench.py``
on the base and on the candidate, feed both JSON lines here, and the
exit code says whether any tracked metric regressed past its threshold
— no eyeballing twenty numbers per round.

Direction is metric-aware: throughput-like metrics (``qps``,
``tokens_per_s``, ``speedup_*``) regress DOWN, latency/overload-like
metrics (``*_ms``, ``shed_rate``) regress UP. Everything else
(``completed``, ``jit_traces``, trace counts, and anything suffixed
``_info`` — the bench-side escape hatch for measured-but-noisy
columns) is informational and never gates. Thresholds are relative: a metric regresses when it
is more than ``--tolerance`` (default 25%, sized for CI-container
noise) worse than the baseline; ``--metric NAME=TOL`` overrides the
tolerance for one metric name (applies wherever that name appears),
and tiny latencies below ``--min-ms`` are ignored (sub-millisecond
percentiles are scheduler noise, not signal).

Usage::

    python tools/serving_bench.py > base.json     # on main
    python tools/serving_bench.py > new.json      # on the candidate
    python tools/bench_compare.py base.json new.json [--tolerance 0.25]
        [--metric itl_p99_ms=0.5] [--min-ms 1.0]

Exit status: 0 = no regression, 1 = at least one metric regressed,
2 = inputs malformed/incomparable.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

# metric-name suffix/prefix rules deciding gating direction.
# capacity_seqs / kv_bytes_per_seq are the paged-KV capacity metrics
# (serving_bench's lm_paged_kv A/B): concurrent sequences held at a
# fixed KV-bytes budget regress DOWN, bytes paid per held sequence
# regress UP — the standing gate covers capacity, not just latency.
# watchdog_trips is a HARD gate in practice: a clean bench baseline has
# zero trips, and the zero-baseline rule below makes ANY trip on the
# candidate side regress (worseness = the trip count itself) — a
# watchdog firing during a healthy bench is a bug, not noise.
# lock_order_violations rides the same rule: the runtime witness
# recording a cycle during a clean bench is a latent deadlock.
# prefill_tokens_saved / prefix_hit_rate are the prefix-cache capacity
# metrics (serving_bench's lm_prefix_cache A/B): prompt tokens the
# content-addressed block cache kept off the prefill path, and the
# fraction of looked-up blocks it served — both regress DOWN (a
# candidate that stops hitting the cache re-prefills shared prefixes).
# kv_bytes_per_device is the sharded-decode capacity metric
# (lm_sharded_decode A/B): KV bytes each decode-mesh device must hold —
# tensor parallelism exists to push it DOWN, so it regresses UP.
# decode_step_retraces rides the zero-baseline rule like
# watchdog_trips: the fused step compiles ONCE per engine config, and
# any retrace on the candidate side is the PR 2 ~10x partitioner drag
# sneaking back into the hot loop — a bug, not noise.
# accepted_per_step is the speculative-decoding amortization metric
# (lm_spec_decode A/B): mean EXTRA tokens each fused verify step
# bought — a candidate whose drafter stops matching (or whose verify
# window shrinks) regresses DOWN. acceptance_rate itself archives as
# _info: it depends on the trace's repetitiveness, not on the code.
# dropped_reports (the obs_plane A/B's obs_dropped_reports) rides the
# zero-baseline rule like watchdog_trips: the fleet plane's reports
# are bounded BY DESIGN — a report dropped on an idle loopback
# collector means the bound machinery broke, a bug, not noise.
# requests_lost / output_mismatches are the serving-fleet recovery
# invariants (lm_fleet_chaos A/B): every request accepted by the
# router must resolve, and a replayed request's output must be
# bit-identical to the first completion (deterministic greedy decode)
# — both have a zero baseline by construction, so ANY loss or
# mismatch on the candidate side gates hard. recovery_time_s (death
# flagged -> first re-dispatched completion) regresses UP like a
# latency; fleet_tokens_per_s rides the tokens_per_s rule.
# updates_lost / epoch_fence_rejections_unexpected are the durable
# online-learning invariants (lm_trainer_chaos A/B): every add the
# trainer ACKNOWLEDGED must survive a kill via checkpoint + WAL
# replay, and the epoch fence must reject exactly the staged zombie
# publishes — both zero-baseline hard gates. trainer_recovery_time_s
# (kill -> fleet re-converged on the restarted incarnation) rides the
# recovery_time_s suffix rule; wal_replay_records archives as _info
# (it measures the checkpoint cadence, not the code).
# preempt_output_mismatches / starved_requests are the overload-
# graceful invariants (lm_overload A/B): a preempted-and-resumed
# generation must be bit-identical to its un-preempted oracle, and
# every accepted request must resolve under sustained pressure — both
# zero-baseline hard gates. deadline_drops regresses UP: the A/B's
# deadlines are sized so the priority+preemption leg meets them all
# (zero baseline), so any drop on the candidate side is scheduling
# gone wrong, not traffic. output_mismatches already covers the
# fleet's twin; capacity_seqs covers the optimistic-admission packing
# headline via the existing higher-better rule.
# kv_bytes_moved / xfer_dedup_hit_rate are the disaggregated-serving
# transfer-plane pair (lm_disagg A/B): raw K/V bytes crossing the
# prefill->decode wire regress UP (dedup-on-arrival and chain
# advertisement exist to shrink them), and the fraction of blocks that
# dedup'd instead of shipping regresses DOWN. The saturated tok/s of
# each leg archives as _info — it measures the trace mix, not the code.
# publish_bytes is the mvparam wire's cousin of kv_bytes_moved: bytes a
# publisher shipped per delta stream (post SparseFilter/int8 codec) —
# regressing UP means the wire compression stopped paying. Its ratio
# sibling wire_compressed_ratio archives as *_info (ratio would hit the
# higher-better rule backwards: smaller is better there).
# ttft_long_p50 / itl_short_p99 are the long-context serving pair
# (lm_long_context A/B): the median time-to-first-token of the few
# "document" prompts sequence-parallel prefill exists to speed up, and
# the p99 inter-token latency of the short interactive requests
# decoding while those documents prefill — both regress UP (the gate
# holds the seqpar leg to both: faster documents AND an unstalled
# interactive tail; the off leg's twins archive as *_info).
# accounting_drift is the cost ledger's conservation residual
# (|sum-over-tenants - engine counter| over the integer usage fields,
# serving/accounting.py): the bench archives 0 and the zero-baseline
# rule makes ANY nonzero candidate value gate — attribution that loses
# or invents tokens is corruption, not noise (same contract as
# requests_lost/updates_lost). Per-tenant cost columns archive as
# *_info: they measure the trace's tenant mix, not the code.
_HIGHER_BETTER = ("qps", "tokens_per_s", "speedup", "ratio",
                  "capacity_seqs", "prefill_tokens_saved",
                  "prefix_hit_rate", "accepted_per_step",
                  "xfer_dedup_hit_rate")
_LOWER_BETTER = ("_ms", "shed_rate", "kv_bytes_per_seq",
                 "kv_bytes_per_device", "decode_step_retraces",
                 "watchdog_trips", "lock_order_violations",
                 "dropped_reports", "requests_lost",
                 "output_mismatches", "recovery_time_s",
                 "updates_lost", "epoch_fence_rejections_unexpected",
                 "preempt_output_mismatches", "starved_requests",
                 "deadline_drops", "kv_bytes_moved", "publish_bytes",
                 "accounting_drift", "ttft_long_p50", "itl_short_p99")


def metric_direction(name: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 informational.

    An ``_info`` suffix ALWAYS means informational, overriding the
    pattern rules: benches use it for measured-but-noisy columns (e.g.
    the paged-KV A/B's saturated tok/s and noise-floor latencies) that
    must ride the archive without flapping the standing gate."""
    if name.endswith("_info"):
        return 0
    for pat in _HIGHER_BETTER:
        if name == pat or name.startswith(pat) or name.endswith(pat):
            return 1
    for pat in _LOWER_BETTER:
        if name.endswith(pat) or name == pat:
            return -1
    return 0


def _flatten(prefix: str, node, out: Dict[str, float]) -> None:
    if isinstance(node, dict):
        for k, v in node.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(node, (int, float)) and not isinstance(node, bool):
        out[prefix] = float(node)


def flatten_workloads(line: dict) -> Dict[str, float]:
    """Dotted metric paths under ``workloads`` (the gated surface; the
    archived ``dashboard`` snapshot is diagnostic, not a gate)."""
    out: Dict[str, float] = {}
    _flatten("", line.get("workloads", {}), out)
    return out


def dropped_gated_metrics(base: dict, new: dict) -> List[str]:
    """Gated-direction metric paths present in ``base`` but ABSENT from
    ``new`` — lost coverage the intersection-only compare would
    otherwise hide (e.g. the ``lm_sharded_decode`` A/B archiving its
    skip marker on a 1-device candidate run while the baseline ran
    under ``--devices``: its zero-baseline ``decode_step_retraces``
    gate would silently vanish). Surfaced as a loud warning, not an
    exit-code flip: metrics legitimately evolve between rounds, but a
    gate disappearing must never be invisible."""
    b, n = flatten_workloads(base), flatten_workloads(new)
    return sorted(path for path in set(b) - set(n)
                  if metric_direction(path.rsplit(".", 1)[-1]) != 0)


def compare(base: dict, new: dict, tolerance: float = 0.25,
            overrides: Dict[str, float] = {}, min_ms: float = 1.0
            ) -> Tuple[List[dict], List[dict]]:
    """Return ``(regressions, rows)``: every compared metric as a row,
    the over-threshold subset as regressions (worst first)."""
    b, n = flatten_workloads(base), flatten_workloads(new)
    rows: List[dict] = []
    regressions: List[dict] = []
    for path in sorted(set(b) & set(n)):
        leaf = path.rsplit(".", 1)[-1]
        sign = metric_direction(leaf)
        if sign == 0:
            continue
        bv, nv = b[path], n[path]
        if sign == -1 and max(bv, nv) < min_ms and leaf.endswith("_ms"):
            continue                      # sub-threshold latency noise
        if bv == 0.0 and sign == 1:
            continue                      # broken baseline: nothing to gate
        # worseness > 0 means NEW is worse, as a fraction of base. A
        # ZERO baseline on a lower-is-better metric (shed_rate 0.0 on a
        # healthy run) must still gate — skipping it would wave through
        # a candidate that starts shedding — so the new value itself
        # stands in as the worseness (0.4 shed_rate > 0.25 tol -> gate;
        # a zero-base *_ms metric past the min-ms floor gates likewise)
        if bv == 0.0:
            worse = nv
        else:
            worse = (bv - nv) / bv if sign == 1 else (nv - bv) / bv
        # most-specific override wins: full dotted path before leaf name
        tol = overrides.get(path, overrides.get(leaf, tolerance))
        row = {"metric": path, "base": bv, "new": nv,
               "worse_frac": round(worse, 4), "tolerance": tol,
               "direction": "up" if sign == 1 else "down",
               "regressed": worse > tol}
        rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    regressions.sort(key=lambda r: r["worse_frac"], reverse=True)
    return regressions, rows


def _load_line(path: str) -> dict:
    """First JSON object found in the file (serving_bench prints ONE
    line, but logs may precede it when stderr was merged)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    raise ValueError(f"{path}: no JSON object line found")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two serving_bench JSON lines; exit 1 on regression")
    ap.add_argument("base", help="baseline serving_bench JSON line file")
    ap.add_argument("new", help="candidate serving_bench JSON line file")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="relative worseness gate (default 0.25)")
    ap.add_argument("--metric", action="append", default=[],
                    metavar="NAME=TOL",
                    help="per-metric tolerance override (leaf name or "
                         "full dotted path; repeatable)")
    ap.add_argument("--min-ms", type=float, default=1.0,
                    help="ignore latency metrics where both sides are "
                         "below this (default 1.0 ms)")
    args = ap.parse_args(argv)
    overrides: Dict[str, float] = {}
    for spec in args.metric:
        name, _, tol = spec.partition("=")
        if not tol:
            ap.error(f"--metric needs NAME=TOL, got {spec!r}")
        overrides[name] = float(tol)
    try:
        base, new = _load_line(args.base), _load_line(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"bench_compare: {exc}", file=sys.stderr)
        return 2
    regressions, rows = compare(base, new, args.tolerance, overrides,
                                args.min_ms)
    if not rows:
        print("bench_compare: no comparable metrics", file=sys.stderr)
        return 2
    dropped = dropped_gated_metrics(base, new)
    if dropped:
        print(f"WARNING: {len(dropped)} gated metric(s) in the baseline "
              f"are ABSENT from the candidate (coverage lost, not "
              f"compared): {', '.join(dropped)}", file=sys.stderr)
    print(f"{len(rows)} metrics compared, {len(regressions)} regressed "
          f"(tolerance {args.tolerance:.0%})")
    print(f"{'metric':<52} {'base':>10} {'new':>10} {'worse':>8}")
    for r in rows:
        flag = " <-- REGRESSED" if r["regressed"] else ""
        print(f"{r['metric']:<52} {r['base']:>10.3f} {r['new']:>10.3f} "
              f"{r['worse_frac']:>+7.1%}{flag}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
