"""Word2vec hot-path profiling on the real chip (VERDICT r1 item 4).

Measures the device-resident training pipeline at the text8-shaped config
(71k vocab, 200-dim) and ablates its stages so the throughput ceiling is a
measured fact, not a guess:

* full fused step (sample + train) — the bench.py number;
* train-only on a fixed batch (no sampler) — isolates the gather/scatter
  + MXU objective work; the printed "sampler overhead" is the
  full-minus-train residual (sampling + the dispatch/fusion differences
  between the two programs);
* bytes-per-pair roofline vs the chip's HBM bandwidth.

Optionally dumps an xprof trace (``--trace DIR``) via
``dashboard.profile_trace`` for op-level inspection.

Usage: python tools/w2v_profile.py [--dim 200] [--vocab 71291] [--trace DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def timed(fn, iters=10):
    import jax

    jax.block_until_ready(fn())       # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=71291)   # text8 vocab
    ap.add_argument("--dim", type=int, default=200)       # text8 config dim
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--negative", type=int, default=5)
    ap.add_argument("--bf16", type=int, default=1)
    ap.add_argument("--oversample", type=float, default=2.5)
    ap.add_argument("--row_mean", type=int, default=1)
    ap.add_argument("--static", type=int, default=0,
                    help="row_mean_static (the shipped bench stabiliser)")
    ap.add_argument("--impl", default="scatter",
                    choices=["scatter", "segsum", "split8"])
    ap.add_argument("--compact", default="scatter",
                    choices=["scatter", "gather"],
                    help="candidate-compaction impl (Word2VecConfig."
                         "compact_impl; gather is the measured-rejected "
                         "alternative)")
    ap.add_argument("--shared", type=int, default=0,
                    help="shared_negatives group size G (bench default 64)")
    ap.add_argument("--trace", default="")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    mv.init(["profile", "-log_level=error"])
    vocab, D, B, S, K = (args.vocab, args.dim, args.batch, args.steps,
                         args.negative)
    rng = np.random.default_rng(0)
    # zipf-ish counts like a real corpus
    counts = (1.0 / np.arange(1, vocab + 1)) ** 1.0
    counts = np.maximum(counts / counts.min(), 5).astype(np.float64)

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    cfg = Word2VecConfig(vocab_size=vocab, embedding_size=D, window=5,
                         negative=K, batch_size=B, oversample=args.oversample,
                         neg_pool_size=1 << 22,
                         row_mean_updates=bool(args.row_mean),
                         row_mean_static=bool(args.static),
                         update_impl=args.impl,
                         compact_impl=args.compact,
                         shared_negatives=args.shared)
    w_in = mv.create_table("matrix", vocab, D, init_value="random",
                           dtype=dtype, name="w_in")
    w_out = mv.create_table("matrix", vocab, D, dtype=dtype, name="w_out")
    model = Word2Vec(cfg, w_in, w_out, counts=counts)
    model.total_words = 10 ** 9

    # synthetic corpus in HBM: zipf draws, sentence breaks every ~1k
    n_tok = 2_000_000
    probs = counts / counts.sum()
    ids = rng.choice(vocab, size=n_tok, p=probs).astype(np.int32)
    sent = (np.arange(n_tok) // 1000).astype(np.int32)
    model.load_corpus_chunk(ids, sent, np.zeros(vocab, np.float32))

    # ---- full fused pipeline -------------------------------------------
    def full():
        loss, count = model.train_device_steps(S)
        return loss

    t_full = timed(full)
    pairs = S * B
    full_rate = pairs / t_full
    print(f"full fused: {t_full*1e3:8.2f} ms / {S} steps  "
          f"-> {full_rate/1e6:7.2f}M pairs/s", flush=True)

    # ---- train-only: fixed batches through the multi-step scan ---------
    centers = jnp.asarray(rng.choice(vocab, (S, B), p=probs), jnp.int32)
    contexts = jnp.asarray(rng.choice(vocab, (S, B), p=probs), jnp.int32)
    mask = jnp.ones((S, B), jnp.float32)

    def train_only():
        return model.train_batches(centers, contexts, mask)

    t_train = timed(train_only)
    print(f"train-only: {t_train*1e3:8.2f} ms / {S} steps  "
          f"-> {pairs/t_train/1e6:7.2f}M pairs/s", flush=True)
    print(f"sampler overhead: {(t_full-t_train)/t_full*100:5.1f}% of full",
          flush=True)

    # ---- roofline -------------------------------------------------------
    itemsize = np.dtype(np.float32).itemsize // 2 if args.bf16 else 4
    # per pair: in-row gather + scatter-add (read+write), (1+K/G) out rows
    # gather + scatter-add (G pairs share one K-negative draw);
    # scatter-add = read + write of the row
    G = max(args.shared, 1)
    rows_moved = (1 + 2) + (1 + K / G) * (1 + 2)
    bytes_per_pair = rows_moved * D * itemsize
    HBM = 819e9   # v5e ~819 GB/s
    bound = HBM / bytes_per_pair
    print(f"roofline: {bytes_per_pair/1e3:.2f} KB/pair -> HBM bound "
          f"{bound/1e6:.1f}M pairs/s; full = {full_rate/bound*100:.1f}% "
          f"of bound", flush=True)

    if args.trace:
        from multiverso_tpu.dashboard import profile_trace

        with profile_trace(args.trace):
            for _ in range(3):
                model.train_device_steps(S)
            jax.block_until_ready(model.input_table._data)
        print(f"trace -> {args.trace}", flush=True)

    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
