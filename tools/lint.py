#!/usr/bin/env python
"""Repo-native static analysis driver: trace hazards + lock discipline.

Runs the two AST passes in ``multiverso_tpu/analysis`` over the package
(and ``tools/``), subtracts the justified-suppression baseline, and
reports what's left:

    python tools/lint.py                  # report all findings
    python tools/lint.py --check          # CI gate: exit 1 on anything
                                          # unsuppressed OR a stale/
                                          # unjustified baseline entry
    python tools/lint.py --graph          # dump the inter-lock graph
    python tools/lint.py serving/foo.py   # lint specific files/dirs

Baseline format (``tools/lint_baseline.txt``), one suppression per line:

    LK203 path.py::Qual.name::slug -- why this is by-design

The ``-- justification`` part is REQUIRED — an entry without one makes
the run fail, because the whole point is that every silenced finding
carries its defense in-tree. Stale entries (nothing matches anymore)
also fail ``--check``: a fixed finding must leave the baseline with it.
See docs/ANALYSIS.md for the rule catalog.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from multiverso_tpu.analysis import locklint, retrace_lint  # noqa: E402
from multiverso_tpu.analysis.common import (  # noqa: E402
    BaselineError, iter_py_files, load_baseline, parse_module,
    split_findings)

DEFAULT_PATHS = ("multiverso_tpu", "tools")
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "lint_baseline.txt")


def run(paths, baseline_path, check=False, graph=False, verbose=False,
        out=sys.stdout):
    files = []
    for p in paths:
        resolved = (os.path.join(REPO_ROOT, p) if not os.path.isabs(p)
                    and not os.path.exists(p) else p)
        got = iter_py_files([resolved])
        if not got:
            # a typo'd path silently linting NOTHING (and exiting 0)
            # reads as "clean" — fail loudly instead
            print(f"ERROR: {p!r} matched no Python files (resolved to "
                  f"{resolved!r})", file=out)
            return 2
        files.extend(got)
    files = sorted(set(files))
    modules = [m for m in (parse_module(f, root=REPO_ROOT) for f in files)
               if m is not None]
    lock_findings, linter = locklint.lint_modules(modules)
    findings = lock_findings + retrace_lint.lint_modules(modules)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    try:
        baseline = load_baseline(baseline_path) if baseline_path else {}
    except BaselineError as exc:
        print(f"BASELINE ERROR: {exc}", file=out)
        return 1
    fresh, silenced, stale = split_findings(findings, baseline)
    if graph:
        print(linter.graph_report(), file=out)
    for f in fresh:
        print(f.render(), file=out)
    if verbose:
        for f in silenced:
            print(f"suppressed: {f.render()}", file=out)
            print(f"  -- {baseline[f.identity]}", file=out)
    for ident in stale:
        print(f"STALE baseline entry (fix landed? delete the line): "
              f"{ident}", file=out)
    print(f"{len(modules)} modules: {len(fresh)} finding(s), "
          f"{len(silenced)} suppressed, {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}", file=out)
    if check and (fresh or stale):
        return 1
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/directories to lint (default: "
                         "multiverso_tpu tools)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="justified-suppression file ('' = none)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any unsuppressed finding or stale "
                         "baseline entry (the CI gate)")
    ap.add_argument("--graph", action="store_true",
                    help="also print the inter-lock acquisition graph")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print suppressed findings + justifications")
    args = ap.parse_args(argv)
    return run(args.paths or list(DEFAULT_PATHS), args.baseline,
               check=args.check, graph=args.graph, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
