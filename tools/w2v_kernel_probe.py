"""Measured probe of the w2v fused-kernel refutation (docs/W2V_KERNEL.md).

VERDICT r3 item 3 resolved as a written-up refutation whose load-bearing
claim — a Pallas per-row DMA kernel cannot beat the ~18 ns/row the XLA
scatter already sustains — was argued from hardware constants because
the accelerator tunnel died mid-round. This tool turns the argument
into on-chip numbers, and the first finding is stronger than the
argument: **the per-row DMA kernel class does not even compile.**
Mosaic rejects any HBM slice smaller than the hardware tile — dim-0
slices must be 8-aligned f32 (16 bf16), and a flat 1-D view must slice
in 1024-element units — so the minimum addressable DMA from a f32
table is the enclosing (8, D) tile. A "per-row" kernel is therefore
really a per-TILE kernel: 8x read amplification on the gather side and
8x+8x read+write on the RMW side, before any issue-rate argument.

What this probe measures on the real chip (same shape, same zipf index
distribution as the bench step):

  xla_scatter   table.at[idx].add(grads)     — the incumbent update op
  xla_gather    jnp.take(table, idx, 0)      — the incumbent gather
  pallas_gather per-row gather via enclosing-tile DMA, DEPTH=8
                ring-pipelined — the best per-row rate the kernel class
                reaches on its gather side alone (8 KB moved per row)
  pallas_rmw    per-row read-modify-write via enclosing-tile DMA,
                serial — what zipf duplicate rows allow (any pipelined
                RMW races whenever two in-flight rows share a tile,
                and the hottest zipf rows collide thousands of times
                per batch; 16 KB moved per row + 2 DMA waits)

Shape: D=256 f32 rows (1 KB; the bench's 200-dim rows are 800 B f32 /
400 B bf16 — the tile-granularity penalty this probe isolates only
grows as rows shrink relative to the fixed (8,128) tile), N = 204800
scattered rows into a 71296-row table, indices drawn zipf(1.0) like
the corpus. Timing is hardware ``device_duration_ps`` via
tools/xprof_util.py, one measurement per subprocess (tunnel wall
clocks lie; repeated traces in one process hang).

Correctness is asserted before timing: the Pallas gather must equal
jnp.take exactly, and the serial RMW must equal scatter-add INCLUDING
duplicate rows.

Usage: python tools/w2v_kernel_probe.py [--json]
Reference metric under test: words/sec
(/root/reference/Applications/WordEmbedding/src/trainer.cpp:45-48).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

VOCAB = 71296
DIM = 256
N_ROWS = 204800
CHUNK = 2048          # rows per grid step (idx block = 8 KB SMEM)
DEPTH = 8             # in-flight DMA ring for the pipelined gather
TILE = 8              # f32 dim-0 tiling: the minimum HBM slice height


def _make_inputs():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    # zipf-law draws like the corpus: duplicates are the NORM — the
    # hottest rows collect thousands of colliding updates
    ranks = np.arange(1, VOCAB + 1)
    p = 1.0 / ranks
    p /= p.sum()
    idx = rng.choice(VOCAB, size=N_ROWS, p=p)
    table = rng.standard_normal((VOCAB, DIM)).astype(np.float32)
    grads = (rng.standard_normal((N_ROWS, DIM)) * 1e-3).astype(np.float32)
    return (jnp.asarray(table), jnp.asarray(idx.astype(np.int32)),
            jnp.asarray(grads))


# ---------------------------------------------------------------- kernels


def _tile_slice(pl, idx):
    """The enclosing TILE-row slice of ``idx`` — the smallest HBM window
    Mosaic will DMA (sub-tile slices fail to compile; measured, see
    module docstring)."""
    return pl.ds(pl.multiple_of((idx // TILE) * TILE, TILE), TILE)


def _gather_kernel(idx_ref, table_ref, out_ref, scratch, sems):
    """Per-row gather via enclosing-tile DMA, DEPTH-deep ring."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def dma(i, slot):
        return pltpu.make_async_copy(
            table_ref.at[_tile_slice(pl, idx_ref[i]), :],
            scratch.at[pl.ds(slot * TILE, TILE), :],
            sems.at[slot])

    def retire(j, slot):
        dma(j, slot).wait()
        out_ref[pl.ds(j, 1), :] = scratch[
            pl.ds(slot * TILE + idx_ref[j] % TILE, 1), :]

    def body(i, _):
        slot = jax.lax.rem(i, DEPTH)

        @pl.when(i >= DEPTH)
        def _():
            retire(i - DEPTH, slot)

        dma(i, slot).start()
        return 0

    jax.lax.fori_loop(0, CHUNK, body, 0)

    def drain(k, _):
        j = CHUNK - DEPTH + k
        retire(j, jax.lax.rem(j, DEPTH))
        return 0

    jax.lax.fori_loop(0, DEPTH, drain, 0)


def pallas_gather(table, idx, interpret: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = idx.shape[0]
    return pl.pallas_call(
        _gather_kernel,
        grid=n // CHUNK,
        in_specs=[
            pl.BlockSpec((CHUNK,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((CHUNK, DIM), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, DIM), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((DEPTH * TILE, DIM), jnp.float32),
            pltpu.SemaphoreType.DMA((DEPTH,)),
        ],
        interpret=interpret,
    )(idx, table)


def _rmw_kernel(idx_ref, grad_ref, table_in_ref, table_out_ref,
                scratch, sem_in, sem_out):
    """Serial per-row read-modify-write via enclosing-tile DMA. Serial
    because zipf duplicates make any pipelined RMW racy: row i's tile
    write-back must land before a colliding row j>i reads the same
    tile — and collisions are the workload, not a corner case.

    Reads AND writes go through ``table_out_ref``: on TPU the aliased
    input is the same buffer, but interpret mode gives the input ref a
    stale snapshot — reading it would lose earlier duplicate-row
    updates (caught by tests/test_kernel_probe.py)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del table_in_ref     # aliased to table_out_ref; RMW uses one view

    def body(i, _):
        idx = idx_ref[i]
        tile = _tile_slice(pl, idx)
        pltpu.make_async_copy(table_out_ref.at[tile, :], scratch,
                              sem_in).start()
        pltpu.make_async_copy(table_out_ref.at[tile, :], scratch,
                              sem_in).wait()
        row = pl.ds(idx % TILE, 1)
        scratch[row, :] = scratch[row, :] + grad_ref[pl.ds(i, 1), :]
        pltpu.make_async_copy(scratch, table_out_ref.at[tile, :],
                              sem_out).start()
        pltpu.make_async_copy(scratch, table_out_ref.at[tile, :],
                              sem_out).wait()
        return 0

    jax.lax.fori_loop(0, CHUNK, body, 0)


def pallas_rmw(table, idx, grads, interpret: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = idx.shape[0]
    return pl.pallas_call(
        _rmw_kernel,
        grid=n // CHUNK,
        in_specs=[
            pl.BlockSpec((CHUNK,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CHUNK, DIM), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((table.shape[0], DIM), jnp.float32),
        input_output_aliases={2: 0},
        scratch_shapes=[
            pltpu.VMEM((TILE, DIM), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(idx, grads, table)


def subtile_rejected() -> str:
    """Self-verifying form of the probe's strongest finding: attempt the
    ACTUAL per-row kernel — a (1, DIM) HBM row slice DMA — and return
    the compiler's rejection. If a future Mosaic release starts
    accepting sub-tile slices, this raises and the 8x-amplification
    argument in docs/W2V_KERNEL.md must be re-measured."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(idx_ref, table_ref, out_ref, scratch, sem):
        def body(i, _):
            row = pl.ds(idx_ref[i], 1)           # sub-tile: 1 of 8 rows
            pltpu.make_async_copy(table_ref.at[row, :], scratch,
                                  sem).start()
            pltpu.make_async_copy(table_ref.at[row, :], scratch,
                                  sem).wait()
            out_ref[pl.ds(i, 1), :] = scratch[:, :]
            return 0

        jax.lax.fori_loop(0, 8, body, 0)

    call = pl.pallas_call(
        kern, grid=1,
        in_specs=[pl.BlockSpec((8,), lambda i: (0,),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((8, DIM), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, DIM), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, DIM), jnp.float32),
                        pltpu.SemaphoreType.DMA(())],
    )
    try:
        np.asarray(call(jnp.zeros(8, jnp.int32),
                        jnp.zeros((64, DIM), jnp.float32)))
    except Exception as exc:                     # expected: Mosaic reject
        # a rejection with ANY wording keeps the measured verdict valid;
        # only genuine ACCEPTANCE (the fall-through below) triggers the
        # re-measure alarm. Matching one literal compiler string here
        # made a harmless wording change look like a probe failure
        # (ADVICE r4).
        msg = str(exc)
        if "aligned to tiling" in msg:
            return "rejected: slice must be aligned to tiling (8)"
        return ("rejected (unrecognized wording — still a reject): "
                + (msg.splitlines() or ["<no message>"])[-1][-200:])
    raise AssertionError(
        "Mosaic now ACCEPTS sub-tile HBM DMA slices — the per-row kernel "
        "class exists after all; re-measure docs/W2V_KERNEL.md's verdict")


# ------------------------------------------------------------ measurement


def _measure_one(which: str) -> None:
    import jax
    import jax.numpy as jnp

    from tools.xprof_util import trace_device_ms

    if which == "subtile":
        print(f"SUBTILE {subtile_rejected()}")
        return

    table, idx, grads = _make_inputs()

    # The in-place ops DONATE the table (like the real training step):
    # without donation XLA prepends a ~73 MB defensive table copy inside
    # the traced jit_ span, inflating the in-place ops' ns/row. Donated
    # calls chain the result back in as the next call's operand.
    holder = [table]

    if which == "xla_scatter":
        fn = jax.jit(lambda t, i, g: t.at[i].add(g), donate_argnums=0)

        def run():
            holder[0] = fn(holder[0], idx, grads)
            return holder[0]
    elif which == "xla_gather":
        fn = jax.jit(lambda t, i: jnp.take(t, i, axis=0))

        def run():
            return fn(table, idx)
    elif which == "pallas_gather":
        fn = jax.jit(pallas_gather)
        ref = jnp.take(table, idx, axis=0)
        err = float(jnp.max(jnp.abs(fn(table, idx) - ref)))
        assert err == 0.0, f"pallas gather wrong: max err {err}"

        def run():
            return fn(table, idx)
    elif which == "pallas_rmw":
        check = jax.jit(pallas_rmw)
        ref = table.at[idx].add(grads)
        # duplicate rows accumulate in a different order → f32 rounding
        err = float(jnp.max(jnp.abs(check(table, idx, grads) - ref)))
        assert err < 1e-4, f"pallas rmw wrong: max err {err}"
        fn = jax.jit(pallas_rmw, donate_argnums=0)

        def run():
            holder[0] = fn(holder[0], idx, grads)
            return holder[0]
    else:
        raise SystemExit(f"unknown probe {which}")

    jax.block_until_ready(run())         # compile outside the trace
    ms = trace_device_ms(run, iters=5)
    print(f"DEVICE_MS {ms:.6f}")


def _measure(which: str) -> float:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_one", which],
        capture_output=True, text=True, timeout=500)
    for line in out.stdout.splitlines():
        if line.startswith("DEVICE_MS "):
            return float(line.split()[1])
    raise RuntimeError(f"probe {which} failed:\n{out.stdout[-2000:]}\n"
                       f"{out.stderr[-2000:]}")


def main(argv=None):
    if argv is None and len(sys.argv) >= 3 and sys.argv[1] == "--_one":
        _measure_one(sys.argv[2])
        return 0
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    sub = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_one", "subtile"],
        capture_output=True, text=True, timeout=500)
    subtile = next((ln.partition(" ")[2] for ln in sub.stdout.splitlines()
                    if ln.startswith("SUBTILE ")), None)
    if subtile is None:
        raise RuntimeError(f"subtile probe failed:\n{sub.stdout[-2000:]}\n"
                           f"{sub.stderr[-2000:]}")
    print(f"sub-tile row DMA: {subtile}", flush=True)

    rows = {}
    for which in ("xla_scatter", "xla_gather", "pallas_gather",
                  "pallas_rmw"):
        ms = _measure(which)
        rows[which] = {"device_ms": round(ms, 3),
                       "ns_per_row": round(ms * 1e6 / N_ROWS, 1)}
        print(f"{which:14s} {ms:8.3f} ms   "
              f"{rows[which]['ns_per_row']:7.1f} ns/row", flush=True)

    if args.json:
        print(json.dumps({"vocab": VOCAB, "dim": DIM, "n_rows": N_ROWS,
                          "chunk": CHUNK, "depth": DEPTH, "tile": TILE,
                          "subtile_dma": subtile, "rows": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
