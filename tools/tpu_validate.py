"""Real-TPU validation of the Pallas hot-op kernels (VERDICT r1 weak 6).

Compiles (no interpret mode) and numerically checks on the actual chip:

* the flash-attention Pallas kernel vs the reference jnp attention, over a
  shape sweep incl. causal + ragged lengths;
* a micro-benchmark of kernel vs XLA-fused reference attention, so the
  kernel's existence is justified by numbers, not vibes.

Writes a JSON artifact (default ``docs/TPU_VALIDATE.json``) with platform,
max errors and timings — the evidence that the "TPU-native kernel" has run
on a TPU.

Usage: python tools/tpu_validate.py [--out docs/TPU_VALIDATE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _device_ms_one(impl: str, seq: int, mode: str = "fwd",
                   h: int = 8, d: int = 128) -> None:
    """Subprocess entry: trace ONE implementation at ONE shape and print
    the hardware-measured device ms/call. Wall clocks are unreliable on a
    tunneled device (dispatch acks return early), and repeated
    start_trace/stop_trace in one process hangs — hence one measurement
    per process, device_duration_ps from the trace.

    ``mode="fwd"`` times the forward; ``mode="fwdbwd"`` times a full
    value+grad step (the training-step attention cost)."""
    import jax
    import jax.numpy as jnp

    from multiverso_tpu.ops import flash_attention, reference_attention
    from tools.xprof_util import trace_device_ms

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
    base = flash_attention if impl == "flash" else reference_attention
    if mode == "fwdbwd":
        def step(q, k, v):
            return jax.grad(
                lambda q, k, v: jnp.sum(base(q, k, v, causal=True) ** 2),
                argnums=(0, 1, 2))(q, k, v)
        fn = jax.jit(step)
    else:
        fn = jax.jit(lambda q, k, v: base(q, k, v, causal=True))
    jax.block_until_ready(fn(q, q, q))   # compile outside the trace
    ms = trace_device_ms(lambda: fn(q, q, q))
    print(f"DEVICE_MS {ms:.6f}")


def _device_ms(impl: str, seq: int, mode: str = "fwd",
               h: int = 8, d: int = 128) -> float:
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_one", impl,
         str(seq), mode, str(h), str(d)],
        capture_output=True, text=True, timeout=400)
    for line in out.stdout.splitlines():
        if line.startswith("DEVICE_MS "):
            return float(line.split()[1])
    raise RuntimeError(f"device timing failed ({impl}, {seq}, {mode}):\n"
                       f"{out.stdout[-1500:]}\n{out.stderr[-1500:]}")


def main(argv=None):
    if argv is None and len(sys.argv) >= 4 and sys.argv[1] == "--_one":
        _device_ms_one(sys.argv[2], int(sys.argv[3]),
                       sys.argv[4] if len(sys.argv) > 4 else "fwd",
                       int(sys.argv[5]) if len(sys.argv) > 5 else 8,
                       int(sys.argv[6]) if len(sys.argv) > 6 else 128)
        return 0
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/TPU_VALIDATE.json")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from multiverso_tpu.ops import flash_attention, reference_attention

    platform = jax.devices()[0].platform
    result = {"platform": platform,
              "device": str(jax.devices()[0]),
              "interpret": platform not in ("tpu", "axon"),
              "cases": [], "bench": []}

    rng = np.random.default_rng(0)
    # (seq, heads, head_dim, causal)
    cases = [(256, 4, 64, False), (256, 4, 64, True),
             (512, 8, 128, True), (1024, 2, 128, True),
             (384, 4, 64, True)]            # non-power-of-two seq
    for seq, h, d, causal in cases:
        q = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        out = flash_attention(q, k, v, causal=causal,
                              interpret=result["interpret"])
        ref = reference_attention(q, k, v, causal=causal)
        err = float(jnp.max(jnp.abs(out - ref)))
        # backward: both Pallas kernels (dq and dk/dv) vs XLA autodiff
        gf = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=causal, interpret=result["interpret"]) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(reference_attention(
            *a, causal=causal) ** 2), argnums=(0, 1, 2))(q, k, v)
        # RELATIVE to the grad scale: the sum-of-squares probe loss makes
        # grad magnitudes grow with seq, so an absolute bar would conflate
        # bf16 MXU rounding with real error (CPU f32 interpret matches to
        # 1e-4; on-chip default-precision passes land ~1e-3 relative)
        gerr = max(
            float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
            for a, b in zip(gf, gr))
        case = {"seq": seq, "heads": h, "head_dim": d, "causal": causal,
                "max_abs_err": err, "max_grad_rel_err": gerr}
        result["cases"].append(case)
        status = "ok" if err < 2e-2 and gerr < 2e-2 else "FAIL"
        print(f"flash seq={seq} h={h} d={d} causal={causal}: "
              f"err {err:.3e} grad-rel-err {gerr:.3e} [{status}]", flush=True)
        assert err < 2e-2 and gerr < 2e-2, case

    # timing: kernel vs XLA reference, HARDWARE-measured (one subprocess
    # trace per point — see _device_ms_one for why wall clocks are out).
    # fwd alone AND fwd+bwd (the training-step attention cost — both
    # directions are Pallas kernels).
    if not result["interpret"]:
        from multiverso_tpu.ops.flash_attention import FLASH_CROSSOVER_SEQ

        # two head shapes: (8, 128) is the historical sweep; (12, 64) is
        # the flagship LM head shape and exercises the r4 _pad_dim change
        # (sublane-aligned d=64 runs UNPADDED instead of lane-padded to
        # 128 — this sweep is the on-chip evidence for that path).
        for h, d in ((8, 128), (12, 64)):
            for mode in ("fwd", "fwdbwd"):
                for seq in (512, 1024, 2048, 4096):
                    t_fa = _device_ms("flash", seq, mode, h, d)
                    t_ra = _device_ms("reference", seq, mode, h, d)
                    row = {"seq": seq, "heads": h, "head_dim": d,
                           "mode": mode, "flash_ms": t_fa,
                           "reference_ms": t_ra,
                           "speedup": t_ra / t_fa,
                           "timing": "device (xprof)",
                           "dispatch": ("flash" if seq >= FLASH_CROSSOVER_SEQ
                                        else "reference")}
                    result["bench"].append(row)
                    print(f"bench h={h} d={d} {mode} seq={seq}: "
                          f"flash {t_fa:.3f} ms, "
                          f"xla-ref {t_ra:.3f} ms, speedup {t_ra/t_fa:.2f}x "
                          f"(device time; attention='flash' dispatches "
                          f"{row['dispatch']})", flush=True)
        # the crossover constant must make attention="flash" never slower:
        # every swept point picks the faster implementation
        bad = [r for r in result["bench"]
               if (r["speedup"] >= 1.0) != (r["dispatch"] == "flash")
               and abs(r["speedup"] - 1.0) > 0.15]
        result["crossover_seq"] = FLASH_CROSSOVER_SEQ
        result["crossover_ok"] = not bad
        if bad:
            print(f"WARNING: crossover {FLASH_CROSSOVER_SEQ} misdispatches: "
                  f"{bad}", flush=True)

    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
