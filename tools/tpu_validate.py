"""Real-TPU validation of the Pallas hot-op kernels (VERDICT r1 weak 6).

Compiles (no interpret mode) and numerically checks on the actual chip:

* the flash-attention Pallas kernel vs the reference jnp attention, over a
  shape sweep incl. causal + ragged lengths;
* a micro-benchmark of kernel vs XLA-fused reference attention, so the
  kernel's existence is justified by numbers, not vibes.

Writes a JSON artifact (default ``docs/TPU_VALIDATE.json``) with platform,
max errors and timings — the evidence that the "TPU-native kernel" has run
on a TPU.

Usage: python tools/tpu_validate.py [--out docs/TPU_VALIDATE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _bench(fn, *args, iters=20):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)        # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/TPU_VALIDATE.json")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from multiverso_tpu.ops import flash_attention, reference_attention

    platform = jax.devices()[0].platform
    result = {"platform": platform,
              "device": str(jax.devices()[0]),
              "interpret": platform not in ("tpu", "axon"),
              "cases": [], "bench": []}

    rng = np.random.default_rng(0)
    # (seq, heads, head_dim, causal)
    cases = [(256, 4, 64, False), (256, 4, 64, True),
             (512, 8, 128, True), (1024, 2, 128, True),
             (384, 4, 64, True)]            # non-power-of-two seq
    for seq, h, d, causal in cases:
        q = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        out = flash_attention(q, k, v, causal=causal,
                              interpret=result["interpret"])
        ref = reference_attention(q, k, v, causal=causal)
        err = float(jnp.max(jnp.abs(out - ref)))
        case = {"seq": seq, "heads": h, "head_dim": d, "causal": causal,
                "max_abs_err": err}
        result["cases"].append(case)
        status = "ok" if err < 2e-2 else "FAIL"
        print(f"flash seq={seq} h={h} d={d} causal={causal}: "
              f"err {err:.3e} [{status}]", flush=True)
        assert err < 2e-2, case

    # timing: kernel vs XLA reference at a production-ish shape
    for seq in (1024, 2048, 4096):
        h, d = 8, 128
        q = jnp.asarray(rng.standard_normal((seq, h, d)), jnp.float32)
        fa = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=result["interpret"]))
        ra = jax.jit(lambda q, k, v: reference_attention(q, k, v, causal=True))
        t_fa = _bench(fa, q, q, q)
        t_ra = _bench(ra, q, q, q)
        row = {"seq": seq, "heads": h, "head_dim": d,
               "flash_ms": t_fa * 1e3, "reference_ms": t_ra * 1e3,
               "speedup": t_ra / t_fa}
        result["bench"].append(row)
        print(f"bench seq={seq}: flash {t_fa*1e3:.3f} ms, "
              f"xla-ref {t_ra*1e3:.3f} ms, speedup {t_ra/t_fa:.2f}x",
              flush=True)

    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
