"""Distributed key-value table (control-plane object).

TPU-native equivalent of the reference KVTable
(``include/multiverso/table/kv_table.h`` in the Multiverso reference): a
distributed ``unordered_map<Key, Val>`` with ``Add`` = server-side ``+=`` and
a worker-local result cache (``raw()``). Parameter-sized state belongs in HBM
(see ArrayTable/MatrixTable); a KV map of scalar counters is host control
plane, so this stays a host dict — sharding by ``key % num_servers``
(``kv_table.h:36-43``) is replaced by one authoritative dict per process plus
an explicit cross-process merge (``sync()``) over the coordination service.
The reference's Store/Load stubs (``kv_table.h:100-118``) are implemented.
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from ..log import Log
from ..runtime import Session


class KVTable:
    """Host-side accumulating KV map (``KVWorkerTable``/``KVServerTable``)."""

    def __init__(self, key_dtype=np.int64, value_dtype=np.float64,
                 name: Optional[str] = None) -> None:
        sess = Session.get()
        if not sess.started:
            Log.fatal("create tables after multiverso_tpu.init()")
        self._sess = sess
        self.table_id = sess.register_table(self)
        self.name = name or f"KVTable:{self.table_id}"
        self.key_dtype = np.dtype(key_dtype)
        self.value_dtype = np.dtype(value_dtype)
        self._store: Dict[Any, Any] = {}
        self._cache: Dict[Any, Any] = {}
        self._pending: Dict[Any, Any] = {}  # adds not yet merged cross-process
        self._lock = lockwatch.rlock("tables.KVTable._lock")
        # mutation counter + incarnation epoch, mirroring TableBase's
        # contract: the checkpoint manifest watermarks it and WAL replay
        # targets version > watermark
        self.version = 0
        self.epoch = 0

    # -- worker API (kv_table.h:24-70) ------------------------------------
    def add(self, keys: Iterable, values: Iterable) -> None:
        """Server-side ``+=`` per key (``KVServerTable::ProcessAdd``)."""
        keys = list(keys)
        values = list(values)
        bus = self._sess.async_bus
        if bus is not None:   # async PS: peers fold this via their drain
            bus.publish_kv(self.table_id,
                           np.asarray(keys, np.int64),
                           np.asarray(values, np.float64))
        with self._lock:
            for k, v in zip(keys, values):
                k = self.key_dtype.type(k).item()
                v = self.value_dtype.type(v).item()
                self._store[k] = self._store.get(k, 0) + v
                if bus is None:
                    self._pending[k] = self._pending.get(k, 0) + v
            self.version += 1
            version = self.version
        if getattr(self._sess, "wal", None) is not None:
            from ..io.wal import journal_local
            from ..parallel.async_ps import KV

            journal_local(self._sess, self.table_id, KV, None,
                          [np.asarray(keys, np.int64),
                           np.asarray(values, np.float64)], version)

    def _apply_remote_kv(self, keys: np.ndarray, values: np.ndarray) -> None:
        """Drain-thread (and WAL-replay) apply of a peer's adds (no
        re-publication)."""
        with self._lock:
            for k, v in zip(keys, values):
                k = self.key_dtype.type(k).item()
                v = self.value_dtype.type(v).item()
                self._store[k] = self._store.get(k, 0) + v
            self.version += 1

    def get(self, keys: Iterable) -> List:
        """Pull values into the local cache and return them in key order."""
        with self._lock:
            out = [self._store.get(self.key_dtype.type(k).item(), 0) for k in keys]
            for k, v in zip(keys, out):
                self._cache[self.key_dtype.type(k).item()] = v
            return out

    def raw(self) -> Dict[Any, Any]:
        """Worker-local cache of previously-got entries (``kv_table.h:30``)."""
        with self._lock:
            return dict(self._cache)

    # -- cross-process merge ----------------------------------------------
    def sync(self) -> None:
        """Merge every process's pending adds (replaces hash-sharded servers).

        All processes must call this collectively (it is a barrier-like op).
        """
        with self._lock:
            pending = dict(self._pending)
            self._pending.clear()
        if self._sess.size == 1:
            return
        from jax.experimental import multihost_utils

        keys = np.array(sorted(pending), dtype=np.int64)
        vals = np.array([pending[k] for k in sorted(pending)], dtype=np.float64)
        # Fixed-size exchange: gather (keys, vals) of every process; the
        # per-rank count bounds the valid prefix (no key-value sentinels, so
        # negative keys are legal).
        all_counts = multihost_utils.process_allgather(
            np.array([keys.size], np.int64))
        max_n = max(int(all_counts.max()), 1)
        pad_k = np.zeros((max_n,), np.int64)
        pad_v = np.zeros((max_n,), np.float64)
        pad_k[: keys.size] = keys
        pad_v[: keys.size] = vals
        all_k = multihost_utils.process_allgather(pad_k)
        all_v = multihost_utils.process_allgather(pad_v)
        my_rank = self._sess.rank
        with self._lock:
            for rank in range(all_k.shape[0]):
                if rank == my_rank:
                    continue
                count = int(all_counts[rank, 0])
                for k, v in zip(all_k[rank, :count], all_v[rank, :count]):
                    k = int(k)
                    self._store[k] = self._store.get(k, 0) + v
            self.version += 1

    # -- STATE-record wire protocol (fenced-restart rebase) ----------------
    def _state_arrays(self):
        with self._lock:
            keys = np.array(sorted(self._store), dtype=np.int64)
            vals = np.array([self._store[k] for k in sorted(self._store)],
                            dtype=np.float64)
            version = self.version
        return [keys, vals], version

    def _install_state_arrays(self, arrays, version: int,
                              epoch: int = 0) -> None:
        keys, vals = arrays
        with self._lock:
            self._store = {int(k): self.value_dtype.type(v).item()
                           for k, v in zip(keys, vals)}
            self.version = int(version)
            if epoch:
                self.epoch = int(epoch)

    # -- checkpoint --------------------------------------------------------
    def store(self, stream) -> int:
        from ..io.stream import write_array

        with self._lock:
            keys = np.array(sorted(self._store), dtype=np.int64)
            vals = np.array([self._store[k] for k in sorted(self._store)],
                            dtype=np.float64)
            version = self.version
        write_array(stream, keys)
        write_array(stream, vals)
        return version

    def load(self, stream) -> None:
        from ..io.stream import read_array

        keys = read_array(stream)
        vals = read_array(stream)
        with self._lock:
            self._store = {int(k): self.value_dtype.type(v).item()
                           for k, v in zip(keys, vals)}

    def flush(self) -> None:
        pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)
