"""1-D dense parameter vector.

TPU-native equivalent of the reference ArrayTable
(``include/multiverso/table/array_table.h``, ``src/table/array_table.cpp`` in
the Multiverso reference): there, a ``vector<T>`` contiguous-range sharded
across server processes, with whole-table Get/Add fanned out per server. Here
the whole table is a single sharded ``jax.Array`` (``P("server")``); the
per-server slicing, reply reassembly and memcpy bookkeeping
(``array_table.cpp:69-96``) all disappear into the sharding layout — XLA
splits the Add and gathers the Get.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from .base import TableBase


class ArrayTable(TableBase):
    """``ArrayWorker``/``ArrayServer`` pair collapsed into one object."""

    def __init__(self, size: int, dtype: Any = jnp.float32,
                 updater: Optional[str] = None, name: Optional[str] = None,
                 init_value: Optional[np.ndarray] = None) -> None:
        super().__init__((int(size),), dtype=dtype, updater=updater,
                         name=name, init_value=init_value)

    def get_into(self, out: np.ndarray) -> None:
        """Reference signature ``Get(T* data, size_t size)``."""
        np.copyto(out, self.get())
