"""Padded gather/scatter helpers for row-keyed table access.

XLA traces fixed shapes, but table users ask for arbitrary row sets (the
reference's per-row Get/Add bucketing, ``src/table/matrix_table.cpp:288-316``).
We bucket request sizes to powers of two and pad with sentinel row 0 plus a
zero mask, so each bucket compiles exactly once and padded lanes are no-ops.
This is the static-shape answer to the reference's dynamic per-row message
loops (survey §7 "hard part (b)").
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

_MIN_BUCKET = 8


def bucket_size(n: int) -> int:
    """Smallest power-of-two >= n (floor at ``_MIN_BUCKET``)."""
    size = _MIN_BUCKET
    while size < n:
        size <<= 1
    return size


def pad_ids(ids: np.ndarray, n_valid: int, size: int) -> Tuple[np.ndarray, np.ndarray]:
    """Pad ``ids`` to ``size``; returns (padded int32 ids, float mask)."""
    padded = np.zeros((size,), dtype=np.int32)
    padded[:n_valid] = ids[:n_valid]
    mask = np.zeros((size,), dtype=np.float32)
    mask[:n_valid] = 1.0
    return padded, mask


def pad_values(values: np.ndarray, n_valid: int, size: int) -> np.ndarray:
    """Pad a [n, ...] value block with zero rows to [size, ...]."""
    out_shape = (size,) + tuple(values.shape[1:])
    padded = np.zeros(out_shape, dtype=values.dtype)
    padded[:n_valid] = values[:n_valid]
    return padded
