"""Parameter-table base: HBM-resident sharded state + jitted update dispatch.

TPU-native re-design of the reference table layer
(``include/multiverso/table_interface.h:24-85``, ``src/table.cpp`` in the
Multiverso reference). The reference splits every table into a WorkerTable
(request fan-out across server processes, per-request ``Waiter`` latches) and
a ServerTable (shard storage + updater application). Here both halves
collapse into one object:

* storage — one ``jax.Array`` laid out with ``NamedSharding`` over the
  ``server`` mesh axis: each shard is HBM-resident on its server devices
  (the reference's contiguous range-sharding, ``src/table/array_table.cpp:11-22``).
* ``Add`` — a jitted updater step dispatched on the sharded state with donated
  buffers (in-place HBM update; replaces worker->server Request_Add messages,
  the OpenMP server loop and the Reply_Add round-trip).
* ``Get`` — a device->host transfer (XLA all-gathers the shards), or the
  zero-copy ``.array`` view for device-side consumers.
* async — JAX's asynchronous dispatch *is* the worker actor: ``add_async``
  returns immediately with the update enqueued on the device stream, and an
  ``AsyncHandle`` plays the role of the reference's ``Waiter``
  (``include/multiverso/util/waiter.h:9-35``).

Sync (BSP) multi-process semantics: with ``-sync=true`` and >1 process, every
process's delta is summed before application (the SyncServer contract that
each round folds all workers' deltas, ``src/server.cpp:69-222``), via a
host-side allreduce on the compat path; jitted training steps should instead
use ``parallel.sync_step`` where the sum is an ICI ``psum``.
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config, trace
from ..dashboard import Dashboard
from ..log import Log
from ..runtime import Session
from ..topology import SERVER_AXIS
from ..updaters import AddOption, GetOption, Updater, get_updater


class AsyncHandle:
    """Future for an async table op (the reference's per-request ``Waiter``)."""

    def __init__(self, values: Any = None, callback=None) -> None:
        self._values = values
        self._callback = callback
        self._done = False

    def wait(self) -> Any:
        if not self._done:
            if self._values is not None:
                jax.block_until_ready(self._values)
            result = self._callback() if self._callback is not None else self._values
            self._values = result
            self._done = True
        return self._values


def _option_scalars(option: AddOption, dtype) -> Tuple[jax.Array, ...]:
    """AddOption -> traced scalars so hyperparameter changes don't recompile."""
    return (
        jnp.asarray(option.learning_rate, dtype=dtype),
        jnp.asarray(option.momentum, dtype=dtype),
        jnp.asarray(option.rho, dtype=dtype),
        jnp.asarray(option.lam, dtype=dtype),
        jnp.asarray(option.worker_id, dtype=jnp.int32),
    )


class TableBase:
    """Shared machinery for Array/Matrix/sparse tables."""

    def __init__(
        self,
        shape: Sequence[int],
        dtype: Any = jnp.float32,
        updater: Optional[str] = None,
        name: Optional[str] = None,
        init_value: Optional[np.ndarray] = None,
        num_sim_workers: Optional[int] = None,
    ) -> None:
        sess = Session.get()
        if not sess.started:
            Log.fatal("create tables after multiverso_tpu.init()")
        self._sess = sess
        self.mesh = sess.table_mesh
        self.shape = tuple(int(s) for s in shape)
        self.dtype = jnp.dtype(dtype)
        self.table_id = sess.register_table(self)
        self.name = name or f"{type(self).__name__}:{self.table_id}"
        self.updater: Updater = get_updater(updater, dtype=self.dtype)
        # Per-worker updater state (AdaGrad) is sized by this; worker_id in
        # AddOption must stay below it (checked host-side — XLA would
        # silently clamp/drop an OOB index inside jit).
        self.num_worker_slots = int(num_sim_workers or sess.num_workers)
        self._lock = lockwatch.rlock("tables.TableBase._lock")
        # Monotonic mutation counter: every state install (dense apply,
        # keyed apply, set_array, checkpoint load) bumps it under _lock.
        # The serving layer's copy-on-publish snapshots key off it — a
        # snapshot whose version equals the table's is bit-identical to
        # the live state (staleness 0 by definition).
        self.version = 0
        # Trainer incarnation this state derives from (epoch fencing):
        # 0 until a fenced publish/state install stamps it. Snapshot
        # pins and serving health carry (epoch, version) together.
        self.epoch = 0

        from jax.sharding import NamedSharding, PartitionSpec as P

        # Physical storage pads the leading dim up to a multiple of the
        # server axis so EVERY table shards (the reference handles the
        # remainder range explicitly, ``src/table/array_table.cpp:11-22``;
        # XLA wants equal shards, so we pad and mask instead). ``shape``
        # stays logical; get()/store() hide the tail.
        self.padded_shape = self._padded_shape()
        self.pad_rows = ((self.padded_shape[0] - self.shape[0])
                         if self.shape else 0)
        data_spec = self._data_pspec()
        self.sharding = NamedSharding(self.mesh, data_spec)
        if init_value is not None:
            init_host = np.asarray(init_value, dtype=self.dtype).reshape(self.shape)
            self._data = jax.device_put(self._pad_host(init_host), self.sharding)
        else:
            self._data = jax.jit(
                lambda: jnp.zeros(self.padded_shape, self.dtype),
                out_shardings=self.sharding
            )()

        ustate = self.updater.init_state(self.padded_shape, self.dtype,
                                         self.num_worker_slots)
        if isinstance(ustate, tuple) and len(ustate) == 0:
            self._ustate = ()
            self._ustate_sharding = ()
        else:
            extra = ustate.ndim - len(self.shape)
            spec = P(*((None,) * extra), *data_spec)
            self._ustate_sharding = NamedSharding(self.mesh, spec)
            self._ustate = jax.device_put(ustate, self._ustate_sharding)

        self._apply_fn = self._build_apply()

    # -- sharding layout ---------------------------------------------------
    def _padded_shape(self) -> Tuple[int, ...]:
        """Physical shape: leading dim rounded up to a server-axis multiple."""
        if not self.shape:
            return self.shape
        s = int(self.mesh.shape[SERVER_AXIS])
        rows = -(-self.shape[0] // s) * s
        return (rows,) + self.shape[1:]

    def _pad_host(self, host: np.ndarray) -> np.ndarray:
        """Zero-pad a logical host array out to the physical shape."""
        if not self.pad_rows:
            return host
        out = np.zeros(self.padded_shape, dtype=host.dtype)
        out[: self.shape[0]] = host
        return out

    def logical(self, data: jax.Array) -> jax.Array:
        """Logical view of a physical (padded) array; jit-safe static slice.

        Models doing whole-array math (e.g. softmax over table rows) must
        use this so padding rows never contribute; gather/scatter consumers
        can use the padded array directly (pad rows are never indexed).
        """
        return data[: self.shape[0]] if self.pad_rows else data

    def _data_pspec(self):
        """Leading dim sharded over the server axis; override for layouts."""
        from jax.sharding import PartitionSpec as P

        if self.shape:
            return P(SERVER_AXIS, *(None,) * (len(self.shape) - 1))
        return P()

    # -- jitted update step ------------------------------------------------
    def _build_apply(self):
        updater = self.updater

        def step(data, ustate, delta, lr, momentum, rho, lam, worker_id):
            option = AddOption(worker_id=worker_id, learning_rate=lr,
                               momentum=momentum, rho=rho, lam=lam)
            return updater.apply(data, ustate, delta, option)

        return jax.jit(
            step,
            donate_argnums=(0, 1),
            out_shardings=(self.sharding, self._ustate_sharding),
        )

    # -- shared keyed (row/key) machinery ---------------------------------
    def _build_keyed_apply(self, rowwise: bool):
        """Jitted scatter-apply for keyed adds, shared by Matrix/Sparse/FTRL.

        ``rowwise=True``: values are [k, cols] blocks (mask broadcast over
        cols); ``False``: values are [k] scalars. Stateless updaters
        (declared via ``Updater.stateless``) take a direct ``at[ids].add``
        scatter; stateful ones materialise a dense delta so their ``apply``
        semantics (per-worker accumulators etc.) are preserved.
        """
        updater = self.updater
        sign = updater.sign

        def expand_mask(mask, vals):
            m = mask[:, None] if rowwise else mask
            return m.astype(vals.dtype)

        if updater.stateless:
            def step(data, ustate, ids, vals, mask, lr, momentum, rho, lam, wid):
                contrib = sign * vals * expand_mask(mask, vals)
                return data.at[ids].add(contrib.astype(data.dtype)), ustate
        else:
            def step(data, ustate, ids, vals, mask, lr, momentum, rho, lam, wid):
                contrib = vals * expand_mask(mask, vals)
                dense = jnp.zeros(data.shape, data.dtype).at[ids].add(
                    contrib.astype(data.dtype))
                option = AddOption(worker_id=wid, learning_rate=lr,
                                   momentum=momentum, rho=rho, lam=lam)
                return updater.apply(data, ustate, dense, option)

        return jax.jit(step, donate_argnums=(0, 1),
                       out_shardings=(self.sharding, self._ustate_sharding))

    def _build_keyed_gather(self):
        return jax.jit(lambda data, ids: data[ids])

    def _default_option(self, option: Optional[AddOption]) -> AddOption:
        option = option or AddOption(worker_id=max(self._sess.worker_id, 0))
        if not (0 <= option.worker_id < self.num_worker_slots):
            Log.fatal(
                f"AddOption.worker_id {option.worker_id} out of range for "
                f"{self.num_worker_slots} worker slot(s) on table {self.name!r}; "
                f"pass num_sim_workers= at table creation to widen")
        return option

    def _aggregate_keyed(self, ids: np.ndarray, vals: np.ndarray):
        """Sync (BSP) mode, >1 process: union every process's (ids, vals) so
        each replica folds all workers' keyed deltas (the SyncServer
        contract). Scatter-add handles the duplicate ids."""
        if not (config.get_flag("sync") and self._sess.size > 1):
            return ids, vals
        from jax.experimental import multihost_utils

        counts = multihost_utils.process_allgather(
            np.array([ids.shape[0]], np.int64))
        max_n = int(counts.max())
        pad_i = np.zeros((max_n,), ids.dtype)
        pad_v = np.zeros((max_n,) + vals.shape[1:], vals.dtype)
        pad_i[: ids.shape[0]] = ids
        pad_v[: ids.shape[0]] = vals
        all_i = multihost_utils.process_allgather(pad_i)
        all_v = multihost_utils.process_allgather(pad_v)
        out_i = np.concatenate(
            [all_i[r, : int(counts[r, 0])] for r in range(all_i.shape[0])])
        out_v = np.concatenate(
            [all_v[r, : int(counts[r, 0])] for r in range(all_v.shape[0])])
        return out_i, out_v

    # -- delta application -------------------------------------------------
    def _apply_remote_dense(self, host: np.ndarray, option: AddOption) -> None:
        """Bus entry point for a peer's dense delta. Besides applying it,
        feed the optional remote-delta accumulator apps use to separate
        their OWN training movement from peers' contributions when they
        train on the replica directly (``apps/wordembedding``'s
        AddDeltaParameter equivalent)."""
        with self._lock:
            accum = getattr(self, "_remote_accum", None)
            if accum is not None:
                accum += np.asarray(host, accum.dtype)
            self._apply_dense(host, option)

    def _apply_remote_keyed(self, ids: np.ndarray, vals: np.ndarray,
                            option: AddOption) -> None:
        """Bus entry point for a peer's keyed (touched-row) delta. Like
        :meth:`_apply_remote_dense`, it must feed the remote-delta
        accumulator — atomically with the apply, or a concurrent pusher
        snapshot would count the peer rows as own movement and republish
        them (echo amplification)."""
        with self._lock:
            accum = getattr(self, "_remote_accum", None)
            if accum is not None:
                np.add.at(accum, np.asarray(ids, np.int64).ravel(),
                          np.asarray(vals, accum.dtype))
            self._dispatch_keyed(ids, vals, option)

    def _apply_dense(self, host: np.ndarray, option: AddOption) -> int:
        """Fold a logical-shape host delta into the replica (jitted updater
        step on the sharded state). Shared by local Adds, the async-PS
        drain thread (``parallel.async_ps``) and WAL replay — the
        server-side ``ProcessAdd`` path, ``src/server.cpp:48-60``.
        Returns the post-apply version (the WAL journals it)."""
        staged = jax.device_put(self._pad_host(host), self.sharding)
        with self._lock:
            mon = Dashboard.get_or_create(f"TABLE_ADD[{self.name}]")
            mon.begin()
            # trace twin of the TABLE_ADD monitor: tagged with the table
            # and the version this apply produced, so a serving trace's
            # snapshot_version can be joined to the training-side apply
            # that created it (NULL span while tracing is off)
            sp = trace.start_span("table.add", table=self.name,
                                  worker=option.worker_id)
            self._data, self._ustate = self._apply_fn(
                self._data, self._ustate, staged,
                *_option_scalars(option, self.dtype),
            )
            self.version += 1
            version = self.version
            sp.end(version=version)
            mon.end()
        return version

    def _install_state(self, host: Any, version: int,
                       epoch: int = 0) -> None:
        """Install an ABSOLUTE state at an exact (version, epoch) — the
        fenced restart's STATE-record rebase and the checkpoint
        restore's watermark install. Unlike :meth:`set_array` the
        version is assigned, not bumped, so the installed state IS the
        publisher's state by version identity."""
        host = np.asarray(host, dtype=self.dtype).reshape(self.shape)
        staged = jax.device_put(self._pad_host(host), self.sharding)
        with self._lock:
            self._data = staged
            self.version = int(version)
            if epoch:
                self.epoch = int(epoch)

    # STATE-record wire protocol: a table's absolute state as a LIST of
    # arrays (array tables ship one; KVTable ships keys+vals) so the
    # publish/apply sides stay table-shape-agnostic
    def _state_arrays(self) -> Tuple[list, int]:
        host, version = self._snapshot_host()
        return [host], version

    def _install_state_arrays(self, arrays, version: int,
                              epoch: int = 0) -> None:
        self._install_state(arrays[0], version, epoch)

    def _journal_local(self, kind: int, option, arrays,
                       version: int) -> None:
        """Journal one acknowledged LOCAL apply to the session WAL
        (no-op without ``-wal``). Called AFTER the apply released the
        table lock — the write/fsync must never run under it (LK203).

        Exactness contract: replay re-applies the journaled deltas
        against the restored DATA only — updater state (momentum/
        AdaGrad slots) is neither checkpointed nor journaled, so a
        stateful updater's replayed applies would silently diverge
        from the acknowledged pre-crash bytes. Refuse loudly instead
        (the online-learning deployment this protects runs the
        stateless default/FTRL accumulators)."""
        stateless = isinstance(self._ustate, tuple) \
            and len(self._ustate) == 0
        if not stateless and not getattr(self, "_wal_unsound_ok",
                                         False):
            Log.fatal(
                f"-wal journaling on table {self.name!r} with the "
                f"STATEFUL updater {self.updater.name!r}: replay "
                f"cannot reproduce updater state, so recovery would "
                f"silently diverge from the acknowledged pre-crash "
                f"bytes — use a stateless updater (default/sgd) with "
                f"-wal, or disable the journal")
        from ..io.wal import journal_local

        journal_local(self._sess, self.table_id, kind, option, arrays,
                      version)

    # -- public ops --------------------------------------------------------
    def _add_handle(self) -> AsyncHandle:
        """Waiter for a dispatched add. Later adds may donate the buffer this
        add produced, so the handle blocks on the *current* state instead of
        capturing a buffer — device-stream ordering guarantees this add has
        landed by then (the per-request Waiter contract)."""
        return AsyncHandle(callback=self.flush)

    def add_async(self, delta: Any, option: Optional[AddOption] = None) -> AsyncHandle:
        """Fold a delta into the table; returns immediately (``AddAsync``)."""
        option = self._default_option(option)
        host = np.asarray(delta, dtype=self.dtype).reshape(self.shape)
        if config.get_flag("sync") and self._sess.size > 1:
            # BSP: every replica folds the SUM of all workers' deltas
            host = host.copy()
            self._sess.aggregate(host)
        elif self._sess.async_bus is not None:
            # async PS: peers fold this delta via their drain threads; the
            # bus picks keyed touched-row or dense representation
            self._sess.async_bus.publish_delta(self, host, option)
        version = self._apply_dense(host, option)
        if getattr(self._sess, "wal", None) is not None:
            # journal BEFORE the caller gets its handle: once add()
            # returns (the acknowledgment), the update is replayable
            from ..parallel.async_ps import DENSE

            self._journal_local(DENSE, option, [host], version)
        return self._add_handle()

    def add(self, delta: Any, option: Optional[AddOption] = None) -> None:
        """Blocking Add (``WorkerTable::Add``, ``src/table.cpp:34``)."""
        self.add_async(delta, option).wait()

    def get_async(self, option: Optional[GetOption] = None) -> AsyncHandle:
        with self._lock:
            # Snapshot via an async device copy: later adds donate `_data`,
            # so the handle must own a buffer nothing else will consume.
            snap = jnp.copy(self._data)
        rows = self.shape[0] if self.shape else None
        return AsyncHandle(
            snap, callback=lambda: np.asarray(snap)[:rows])

    def get(self, option: Optional[GetOption] = None) -> np.ndarray:
        """Blocking whole-table Get -> host ndarray (``WorkerTable::Get``)."""
        return self.get_async(option).wait()

    def snapshot_array(self) -> Tuple[jax.Array, int]:
        """``(copy, version)`` for the serving read path.

        The copy dispatches UNDER the table lock, so device-stream
        ordering guarantees it reads the state as of ``version`` even
        though later adds donate ``_data`` — the same contract as
        :meth:`get_async`, but the result stays on device (padded
        physical shape; serving consumers slice via :meth:`logical`).
        Concurrent training ``Add``s can therefore never tear a response
        built from the returned buffer.
        """
        with self._lock:
            return jnp.copy(self._data), self.version

    # -- device-side view --------------------------------------------------
    @property
    def array(self) -> jax.Array:
        """Zero-copy sharded device view (the idiomatic TPU read path).

        This is the PHYSICAL array — ``padded_shape``, with ``pad_rows``
        zero rows at the tail when the logical leading dim is not a
        server-axis multiple. Gather/scatter consumers can use it directly
        (valid row ids never touch the pad); whole-array math must go
        through :meth:`logical`.
        """
        with self._lock:
            return self._data

    def set_array(self, value: jax.Array) -> None:
        """Install updated device state (used by jitted train loops that
        thread the table state through ``parallel.sync_step``). Accepts the
        physical (padded) shape or the logical shape (padded with zeros)."""
        if tuple(value.shape) == self.padded_shape:
            pass
        elif tuple(value.shape) == self.shape:
            value = self._pad_host(np.asarray(value, dtype=self.dtype))
        else:
            Log.fatal(f"set_array shape {value.shape} != table shape "
                      f"{self.shape} (physical {self.padded_shape})")
        with self._lock:
            self._data = jax.device_put(value, self.sharding)
            self.version += 1

    def flush(self) -> None:
        """Block until all dispatched updates have landed."""
        with self._lock:
            if self._data is not None:
                jax.block_until_ready(self._data)

    # -- checkpoint (``Serializable``, ``table_interface.h:59-66``) --------
    def _snapshot_host(self) -> Tuple[np.ndarray, int]:
        """``(logical host copy, version)`` captured atomically w.r.t.
        the mutation lock — the pair a checkpoint watermark (and a
        STATE rebase publish) needs: the version IS the version of
        those bytes. Rides :meth:`snapshot_array` (the ONE sanctioned
        copy-under-lock site)."""
        snap, version = self.snapshot_array()
        rows = self.shape[0] if self.shape else None
        return np.asarray(snap)[:rows], version

    def store(self, stream) -> int:
        """Write the table record; returns the stored state's version
        (the checkpoint manifest's per-table watermark)."""
        from ..io.stream import write_array

        host, version = self._snapshot_host()
        write_array(stream, host)
        return version

    def load(self, stream) -> None:
        from ..io.stream import read_array

        host = read_array(stream)
        if tuple(host.shape) != self.shape:
            Log.fatal(
                f"checkpoint shape {host.shape} != table shape {self.shape}")
        with self._lock:
            self._data = jax.device_put(
                self._pad_host(host.astype(self.dtype)), self.sharding)
            self.version += 1

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 0
