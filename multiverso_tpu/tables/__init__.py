"""Parameter tables: HBM-resident sharded state with Get/Add semantics.

Re-designs the reference table layer (``include/multiverso/table`` /
``src/table`` in the Multiverso reference) for TPU — see the per-module
docstrings for the mapping.
"""

from .base import AsyncHandle, TableBase
from .array_table import ArrayTable
from .matrix_table import MatrixTable
from .kv_table import KVTable
from .sparse_table import FTRLTable, SparseTable

__all__ = [
    "AsyncHandle",
    "TableBase",
    "ArrayTable",
    "MatrixTable",
    "KVTable",
    "SparseTable",
    "FTRLTable",
]
