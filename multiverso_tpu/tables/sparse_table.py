"""Key-addressed sparse parameter vector + FTRL variant.

TPU-native equivalent of the LogisticRegression app's custom PS tables
(``Applications/LogisticRegression/src/util/sparse_table.h`` and
``util/ftrl_sparse_table.h`` in the Multiverso reference; promoted here from
app code to a framework table). The reference hash-shards a sparse vector
over hopscotch-hash blocks per server and Gets by keyset. On TPU the feature
dimension is static, so the natural layout is a *dense sharded vector in HBM*
with keyed gather/scatter — "sparse" describes the access pattern (only
touched keys move), not the storage. A hopscotch hash in HBM would serialise
onto scalar probes; a dense vector rides the VPU.

``FTRLTable`` stores the FTRL state pair ``(z, n)`` per key as a [size, 2]
table (reference ``FTRLEntry{z, n, sqrtn}`` — ``sqrtn`` is a derived cache we
recompute on the fly) and accumulates ``FTRLGradient{delta_z, delta_n}``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..updaters import AddOption, GetOption
from . import _rowops
from .base import AsyncHandle, TableBase, _option_scalars


class SparseTable(TableBase):
    """Keyed sparse vector (``SparseWorkerTable``/``SparseServerTable``)."""

    def __init__(self, size: int, dtype: Any = jnp.float32,
                 updater: Optional[str] = None, name: Optional[str] = None,
                 init_value: Optional[np.ndarray] = None) -> None:
        super().__init__((int(size),), dtype=dtype, updater=updater,
                         name=name, init_value=init_value)
        self._key_gather = self._build_keyed_gather()
        self._key_apply = self._build_keyed_apply(rowwise=False)

    # -- keyed API (sparse_table.h:44-116) ---------------------------------
    def get_keys(self, keys: Any, option: Optional[GetOption] = None) -> np.ndarray:
        """``GetAsync(keys, data)``: gather values for a keyset."""
        ids = np.asarray(keys, dtype=np.int32).ravel()
        n = ids.shape[0]
        size = _rowops.bucket_size(n)
        padded, _ = _rowops.pad_ids(ids, n, size)
        with self._lock:
            out = self._key_gather(self._data, jnp.asarray(padded))
        return np.asarray(out)[:n]

    def _dispatch_keyed(self, ids: np.ndarray, vals: np.ndarray,
                        option: AddOption) -> int:
        ids = np.asarray(ids, dtype=np.int32).ravel()
        vals = np.asarray(vals, dtype=self.dtype).ravel()
        n = ids.shape[0]
        size = _rowops.bucket_size(n)
        padded_ids, mask = _rowops.pad_ids(ids, n, size)
        padded_vals = _rowops.pad_values(vals, n, size)
        with self._lock:
            self._data, self._ustate = self._key_apply(
                self._data, self._ustate,
                jnp.asarray(padded_ids), jnp.asarray(padded_vals),
                jnp.asarray(mask), *_option_scalars(option, self.dtype),
            )
            self.version += 1
            return self.version

    def add_keys_async(self, keys: Any, values: Any,
                       option: Optional[AddOption] = None) -> AsyncHandle:
        option = self._default_option(option)
        ids = np.asarray(keys, dtype=np.int32).ravel()
        vals = np.asarray(values, dtype=self.dtype).ravel()
        bus = self._sess.async_bus
        if bus is not None:
            bus.publish_keyed(self.table_id, ids, vals, option)
        ids, vals = self._aggregate_keyed(ids, vals)
        version = self._dispatch_keyed(ids, vals, option)
        if getattr(self._sess, "wal", None) is not None:
            from ..parallel.async_ps import KEYED

            self._journal_local(KEYED, option, [ids, vals], version)
        return self._add_handle()

    def add_keys(self, keys: Any, values: Any,
                 option: Optional[AddOption] = None) -> None:
        self.add_keys_async(keys, values, option).wait()


class FTRLTable(TableBase):
    """FTRL state table: per-key ``(z, n)`` (``ftrl_sparse_table.h:12-90``)."""

    Z, N = 0, 1  # column layout

    def __init__(self, size: int, dtype: Any = jnp.float32,
                 name: Optional[str] = None) -> None:
        # FTRL accumulation is always ``+=`` server-side (the closed-form
        # weight reconstruction happens worker-side); force default updater.
        super().__init__((int(size), 2), dtype=dtype, updater="default",
                         name=name)
        self._key_gather = self._build_keyed_gather()
        self._key_apply = jax.jit(
            lambda data, ids, vals, mask: data.at[ids].add(
                (vals * mask[:, None].astype(vals.dtype)).astype(data.dtype)),
            donate_argnums=(0,), out_shardings=self.sharding)

    def get_keys(self, keys: Any) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (z, n) arrays for the keyset."""
        ids = np.asarray(keys, dtype=np.int32).ravel()
        n = ids.shape[0]
        size = _rowops.bucket_size(n)
        padded, _ = _rowops.pad_ids(ids, n, size)
        with self._lock:
            out = self._key_gather(self._data, jnp.asarray(padded))
        zn = np.asarray(out)[:n]
        return zn[:, self.Z], zn[:, self.N]

    def _dispatch_keyed(self, ids: np.ndarray, vals: np.ndarray,
                        option=None) -> int:
        ids = np.asarray(ids, dtype=np.int32).ravel()
        vals = np.asarray(vals, dtype=self.dtype).reshape(ids.shape[0], 2)
        n = ids.shape[0]
        size = _rowops.bucket_size(n)
        padded_ids, mask = _rowops.pad_ids(ids, n, size)
        padded_vals = _rowops.pad_values(vals, n, size)
        with self._lock:
            self._data = self._key_apply(
                self._data, jnp.asarray(padded_ids), jnp.asarray(padded_vals),
                jnp.asarray(mask))
            self.version += 1
            return self.version

    def add_keys(self, keys: Any, delta_z: Any, delta_n: Any) -> None:
        """Accumulate ``FTRLGradient{delta_z, delta_n}`` per key."""
        ids = np.asarray(keys, dtype=np.int32).ravel()
        vals = np.stack([
            np.asarray(delta_z, dtype=self.dtype).ravel(),
            np.asarray(delta_n, dtype=self.dtype).ravel(),
        ], axis=1)
        bus = self._sess.async_bus
        if bus is not None:
            bus.publish_keyed(self.table_id, ids, vals, None)
        ids, vals = self._aggregate_keyed(ids, vals)
        version = self._dispatch_keyed(ids, vals)
        if getattr(self._sess, "wal", None) is not None:
            from ..parallel.async_ps import KEYED

            self._journal_local(KEYED, None, [ids, vals], version)
        jax.block_until_ready(self._data)
