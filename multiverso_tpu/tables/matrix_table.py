"""Row-major 2-D parameter table with per-row Get/Add and sparse semantics.

TPU-native equivalent of the reference matrix tables — dense
(``src/table/matrix_table.cpp``), sparse (``src/table/sparse_matrix_table.cpp``)
and the unified ``MatrixOption`` pair (``src/table/matrix.cpp``,
``include/multiverso/table/matrix.h:15-127``) in the Multiverso reference.

Reference mechanics replaced here:

* row-range sharding over servers + per-row message bucketing
  (``matrix_table.cpp:18-50,235-316``) -> one ``jax.Array`` with
  ``P("server", None)`` row sharding; row Get/Add are jitted gather /
  scatter-add on the sharded array (power-of-two padded index buckets keep
  XLA shapes static, see ``_rowops.py``).
* ``SparseFilter`` wire compression (``util/quantization_util.h:25``) —
  unnecessary: sending only touched rows is the *native* representation of a
  row-keyed update here, so Add payloads are already exactly the touched rows.
* server-side per-worker dirty-row bitmaps
  (``sparse_matrix_table.cpp:183-309``) -> a host-side bitmap (control-plane
  metadata; the rows themselves stay in HBM). ``get_dirty_rows(worker)``
  returns only rows updated by *other* workers since that worker's last call.
  Deviation: when no row is dirty we return an empty set, not the
  reference's sentinel row 0 (``UpdateGetState``, ``sparse_matrix_table.cpp:226``).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..log import Log
from ..updaters import AddOption, GetOption
from . import _rowops
from .base import AsyncHandle, TableBase, _option_scalars


class MatrixTable(TableBase):
    """Dense/sparse row-sharded matrix (``MatrixWorker``+``MatrixServer``)."""

    def __init__(
        self,
        num_row: int,
        num_col: int,
        dtype: Any = jnp.float32,
        updater: Optional[str] = None,
        name: Optional[str] = None,
        init_value: Optional[Any] = None,
        is_sparse: bool = False,
        is_pipeline: bool = False,
        seed: int = 0,
        num_sim_workers: Optional[int] = None,
    ) -> None:
        num_row, num_col = int(num_row), int(num_col)
        if isinstance(init_value, str):
            if init_value != "random":
                Log.fatal(f"unknown init_value {init_value!r}")
            # Reference random-init server ctor (matrix_table.cpp:372-384):
            # (U[0,1) - 0.5) / num_col, as used by WordEmbedding input embeddings.
            rng = np.random.default_rng(seed)
            init_value = ((rng.random((num_row, num_col)) - 0.5) / num_col).astype(
                np.dtype(dtype))
        super().__init__((num_row, num_col), dtype=dtype, updater=updater,
                         name=name, init_value=init_value,
                         num_sim_workers=num_sim_workers)
        self.num_row, self.num_col = num_row, num_col
        self.is_sparse = bool(is_sparse)
        self.is_pipeline = bool(is_pipeline)  # kept for option parity; JAX's
        # async dispatch already overlaps what the x2 bitmap buffered.
        self._dirty = (np.zeros((self.num_worker_slots, num_row), dtype=bool)
                       if self.is_sparse else None)
        self._row_apply = self._build_keyed_apply(rowwise=True)
        self._row_gather = self._build_keyed_gather()

    # -- row API (reference matrix_table.h:25-75) --------------------------
    def get_rows(self, row_ids: Any, option: Optional[GetOption] = None) -> np.ndarray:
        """Gather a list of rows -> host [len(row_ids), num_col]."""
        ids = np.asarray(row_ids, dtype=np.int32).ravel()
        n = ids.shape[0]
        size = _rowops.bucket_size(n)
        padded, _ = _rowops.pad_ids(ids, n, size)
        with self._lock:
            # dispatch under the lock: a concurrent add would donate _data
            out = self._row_gather(self._data, jnp.asarray(padded))
        return np.asarray(out)[:n]

    def get_row(self, row_id: int) -> np.ndarray:
        return self.get_rows([row_id])[0]

    def _dispatch_keyed(self, ids: np.ndarray, vals: np.ndarray,
                        option: AddOption) -> int:
        """Pad/bucket + jitted scatter-apply of row deltas; shared by local
        Adds, the async-PS drain thread and WAL replay. Returns the
        post-apply version."""
        ids = np.asarray(ids, dtype=np.int32).ravel()
        vals = np.asarray(vals, dtype=self.dtype).reshape(
            ids.shape[0], self.num_col)
        n = ids.shape[0]
        size = _rowops.bucket_size(n)
        padded_ids, mask = _rowops.pad_ids(ids, n, size)
        padded_vals = _rowops.pad_values(vals, n, size)
        if self._dirty is not None:
            self._mark_dirty(ids, option.worker_id)
        with self._lock:
            self._data, self._ustate = self._row_apply(
                self._data, self._ustate,
                jnp.asarray(padded_ids), jnp.asarray(padded_vals),
                jnp.asarray(mask), *_option_scalars(option, self.dtype),
            )
            self.version += 1
            return self.version

    def add_rows_async(self, row_ids: Any, values: Any,
                       option: Optional[AddOption] = None) -> AsyncHandle:
        """Scatter-apply deltas into a set of rows (``Add(row_ids, ...)``)."""
        option = self._default_option(option)
        ids = np.asarray(row_ids, dtype=np.int32).ravel()
        vals = np.asarray(values, dtype=self.dtype).reshape(ids.shape[0], self.num_col)
        bus = self._sess.async_bus
        if bus is not None:
            bus.publish_keyed(self.table_id, ids, vals, option)
        ids, vals = self._aggregate_keyed(ids, vals)
        version = self._dispatch_keyed(ids, vals, option)
        if getattr(self._sess, "wal", None) is not None:
            from ..parallel.async_ps import KEYED

            # journal the POST-aggregate (ids, vals): exactly what this
            # replica applied, so replay reproduces it bit-for-bit
            self._journal_local(KEYED, option, [ids, vals], version)
        return self._add_handle()

    def add_rows(self, row_ids: Any, values: Any,
                 option: Optional[AddOption] = None) -> None:
        self.add_rows_async(row_ids, values, option).wait()

    def add_row(self, row_id: int, values: Any,
                option: Optional[AddOption] = None) -> None:
        self.add_rows([row_id], np.asarray(values)[None, :], option)

    # whole-table add also feeds the dirty bitmap
    def add_async(self, delta: Any, option: Optional[AddOption] = None) -> AsyncHandle:
        if self._dirty is not None:
            wid = option.worker_id if option else max(self._sess.worker_id, 0)
            self._mark_dirty(np.arange(self.num_row), wid)
        return super().add_async(delta, option)

    def _apply_remote_dense(self, host: np.ndarray, option: AddOption) -> None:
        # a peer's whole-table delta dirties every row for local pullers,
        # exactly like a local whole-table add (the reference server runs
        # UpdateAddState for EVERY add; keyed remote applies mark their
        # touched rows via _dispatch_keyed, so the two wire forms agree)
        if self._dirty is not None:
            self._mark_dirty(np.arange(self.num_row), option.worker_id)
        super()._apply_remote_dense(host, option)

    # -- sparse dirty-row protocol ----------------------------------------
    def _mark_dirty(self, rows: np.ndarray, adding_worker: int) -> None:
        """``UpdateAddState``: rows become dirty for every *other* worker
        (``sparse_matrix_table.cpp:200-224``)."""
        with self._lock:
            for w in range(self._dirty.shape[0]):
                if w != adding_worker:
                    self._dirty[w, rows] = True

    def get_dirty_rows(self, worker_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """``UpdateGetState`` + sparse reply: (row_ids, rows) updated by other
        workers since this worker's last call; clears the bitmap."""
        if self._dirty is None:
            Log.fatal("get_dirty_rows requires is_sparse=True")
        with self._lock:
            rows = np.flatnonzero(self._dirty[worker_id])
            self._dirty[worker_id, rows] = False
        if rows.size == 0:
            return rows.astype(np.int32), np.empty((0, self.num_col), self.dtype)
        return rows.astype(np.int32), self.get_rows(rows)
