"""Version-compat shims shared across modules."""

from __future__ import annotations

import jax

# jax >= 0.8 exposes shard_map at the top level; older versions under
# jax.experimental. One shim here instead of a copy per module.
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:  # pragma: no cover — jax < 0.8
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["shard_map"]
