"""Version-compat shims shared across modules."""

from __future__ import annotations

import jax

# jax >= 0.8 exposes shard_map at the top level; older versions under
# jax.experimental. One shim here instead of a copy per module.
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:  # pragma: no cover — jax < 0.8
    from jax.experimental.shard_map import shard_map  # type: ignore

# jax < 0.6 spells the replication-check kwarg ``check_rep``; newer versions
# renamed it to ``check_vma``. Callers use the new spelling; translate here.
import inspect as _inspect

if "check_vma" not in _inspect.signature(shard_map).parameters:
    _raw_shard_map = shard_map

    def shard_map(f, **kwargs):  # type: ignore[no-redef]
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _raw_shard_map(f, **kwargs)

__all__ = ["shard_map"]
