"""Process-wide session: the reference's ``Zoo`` re-expressed for TPU.

The reference Zoo (``include/multiverso/zoo.h:19``, ``src/zoo.cpp`` in the
Multiverso reference) is a singleton that starts actor threads, registers the
node with rank 0, owns the table registry, and provides barrier/rank/size
queries. On TPU there are no actor threads to start — the data plane is SPMD
programs over a mesh — so the Session reduces to: flag parsing, topology
discovery, the table registry, the train-mode switches (sync / async / ma),
and lifecycle (init / barrier / shutdown with a dashboard dump,
``src/zoo.cpp:96-101``).
"""

from __future__ import annotations

import threading
from .analysis import lockwatch
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import config, topology
from .dashboard import Dashboard
from .log import Log, LogLevel

_ROLE_NONE, _ROLE_WORKER, _ROLE_SERVER, _ROLE_ALL = 0, 1, 2, 3
_ROLES = {"none": _ROLE_NONE, "worker": _ROLE_WORKER,
          "server": _ROLE_SERVER, "default": _ROLE_ALL, "all": _ROLE_ALL}


class Session:
    """Singleton runtime state (``Zoo::Get()`` analogue)."""

    _instance: Optional["Session"] = None
    _lock = lockwatch.rlock("runtime.Session._lock")

    def __init__(self) -> None:
        self.topo: Optional[topology.Topology] = None
        self.tables: List[Any] = []
        self.servers: List[Any] = []  # serving.InferenceServer registry
        self.role: int = _ROLE_ALL
        self.started = False
        self.async_bus: Optional[Any] = None  # cross-process async PS plane
        self.wal: Optional[Any] = None  # -wal write-ahead delta journal
        self.failure_detector: Optional[Any] = None  # -failure_timeout_s
        self.metrics_exporter: Optional[Any] = None  # -metrics_jsonl
        self.obs_agent: Optional[Any] = None  # -obs_plane fleet agent
        # stop() handshake: the claiming caller's completion event +
        # thread id, so a concurrent stop() can wait for the teardown
        # to finish without wedging the Session lock behind it
        self._teardown_done: Optional[threading.Event] = None
        self._teardown_thread: Optional[int] = None

    # -- singleton --------------------------------------------------------
    @classmethod
    def get(cls) -> "Session":
        with cls._lock:
            if cls._instance is None:
                cls._instance = Session()
            return cls._instance

    # -- lifecycle --------------------------------------------------------
    def start(self, argv: Optional[Sequence[str]] = None) -> List[str]:
        """``MV_Init`` (``src/multiverso.cpp:10`` → ``Zoo::Start``).

        A previous stop()'s teardown may still be draining OUTSIDE the
        Session lock (see :meth:`stop`); initializing over it would
        race the old teardown's barriers and distributed shutdown
        against the new session's coordination service — wait for its
        completion event first (same-thread re-entry skips the wait:
        it would deadlock on our own event).
        """
        while True:
            with self._lock:
                done = self._teardown_done
                if (done is None or done.is_set()
                        or self._teardown_thread == threading.get_ident()):
                    return self._start_locked(argv)
            done.wait()

    def _start_locked(self, argv: Optional[Sequence[str]]) -> List[str]:
        with self._lock:
            rest = config.parse_cmd_flags(list(argv) if argv else None)
            Log.reset_log_level_by_name(config.get_flag("log_level"))
            log_file = config.get_flag("log_file")
            if log_file:
                Log.reset_log_file(log_file)
            if self.started:
                return rest
            self.role = _ROLES.get(config.get_flag("ps_role"), _ROLE_ALL)
            self.topo = topology.discover()
            if self.topo.num_workers % self.topo.size != 0:
                Log.fatal(
                    f"mesh worker axis ({self.topo.num_workers}) must be a "
                    f"multiple of the process count ({self.topo.size}) so "
                    f"every process owns the same number of worker lanes; "
                    f"pass -mesh_shape to fix the layout")
            self.started = True
            if config.get_flag("lockwatch"):
                lockwatch.enable()
            if config.get_flag("trace"):
                from . import trace

                # enable() resets the span ring; the not-enabled() guard
                # keeps a redundant init from wiping a live collector
                if not trace.enabled():
                    tail = None
                    if config.get_flag("trace_tail"):
                        tail = trace.TailConfig(
                            slo_ms=float(config.get_flag("trace_slo_ms")),
                            head_n=int(config.get_flag("trace_head_n")))
                    trace.enable(int(config.get_flag("trace_buffer")),
                                 tail=tail)
            metrics_path = config.get_flag("metrics_jsonl")
            if metrics_path and self.metrics_exporter is None:
                # started only once init validation passed: a failed
                # init must not leak a reporter thread, and a retried
                # init must not double-write the JSONL sink
                from .dashboard import MetricsExporter

                self.metrics_exporter = MetricsExporter(
                    interval_s=float(config.get_flag("metrics_interval_s")),
                    sink=metrics_path).start()
            if config.get_flag("wal") and self.wal is None:
                wal_dir = config.get_flag("wal_dir")
                if not wal_dir:
                    Log.fatal("-wal=true requires -wal_dir=PATH (the "
                              "journal must land somewhere durable)")
                from .io.wal import DeltaWAL

                # construction runs torn-tail recovery and opens a
                # fresh segment for this incarnation
                self.wal = DeltaWAL(
                    wal_dir, rank=self.topo.rank,
                    segment_bytes=int(
                        config.get_flag("wal_segment_mb")) << 20,
                    fsync=config.get_flag("wal_fsync"))
            topology.barrier("mv_init")
            from .parallel.async_ps import AsyncDeltaBus

            self.async_bus = AsyncDeltaBus.maybe_start(self)
            timeout = float(config.get_flag("failure_timeout_s"))
            if timeout > 0 and self.size > 1:
                from .parallel.health import FailureDetector

                self.failure_detector = FailureDetector(
                    interval_s=max(min(1.0, timeout / 5), 0.1), session=self)
                # survivor mode when the async bus is up (dead peers leave
                # the ack quorum and training continues); fail-fast default
                # otherwise (sync collectives can't run degraded)
                self.failure_detector.start_watchdog(
                    timeout,
                    self.async_bus.mark_dead
                    if self.async_bus is not None else None)
            if config.get_flag("obs_plane") and self.obs_agent is None:
                # the fleet observability plane: one agent per node
                # (rank 0 doubles as collector); single-process sessions
                # run it in loopback — same reports, no sockets
                from .serving.obs_plane import ObsAgent

                client = None
                if self.size > 1:
                    from jax._src import distributed

                    client = distributed.global_state.client
                sink = config.get_flag("obs_jsonl")
                if sink and self.size > 1:
                    sink = f"{sink}.{self.rank}"
                self.obs_agent = ObsAgent(
                    rank=self.rank, size=self.size, client=client,
                    report_ms=int(config.get_flag("obs_report_ms")),
                    sink=sink)
            Log.info(
                "multiverso-tpu initialised: rank %d/%d, mesh %s, mode %s",
                self.rank, self.size, dict(self.topo.mesh.shape),
                "ma" if config.get_flag("ma")
                else ("sync" if config.get_flag("sync") else "async"),
            )
            return rest

    def stop(self, finalize: bool = True) -> None:
        """``MV_ShutDown`` → ``Zoo::Stop`` (``src/zoo.cpp:96-101``).

        CLAIMS the session state under the lock, then tears it down
        OUTSIDE: the teardown joins server/batcher threads, blocks on
        cross-process barriers, and invokes the dashboard's log callback
        — seconds of work during which a concurrent ``Session.get()`` or
        table registration must not wedge behind the Session lock
        (locklint LK202/LK203; tests/test_runtime.py covers it).

        stop() still MEANS stopped to its caller: a second concurrent
        stop() finds ``started`` already False and blocks on the first
        caller's completion event instead of returning mid-teardown
        (the old held-lock behavior, minus the lock). Same-thread
        re-entry (a drain callback calling stop()) returns immediately
        — waiting on our own event would self-deadlock.
        """
        claimed = False
        with self._lock:
            if not self.started:
                done = self._teardown_done
                wait = (done is not None and not done.is_set()
                        and self._teardown_thread != threading.get_ident())
            else:
                claimed, wait = True, False
                done = self._teardown_done = threading.Event()
                self._teardown_thread = threading.get_ident()
                self.started = False
                topo, self.topo = self.topo, None
                servers, self.servers = self.servers, []
                tables, self.tables = self.tables, []
                detector, self.failure_detector = self.failure_detector, None
                bus, self.async_bus = self.async_bus, None
                wal, self.wal = self.wal, None
                exporter, self.metrics_exporter = self.metrics_exporter, None
                obs, self.obs_agent = self.obs_agent, None
        if not claimed:
            if wait:
                done.wait()
            return
        try:
            self._teardown(topo, servers, tables, detector, bus, exporter,
                           obs, wal)
        finally:
            done.set()

    def _teardown(self, topo, servers, tables, detector, bus,
                  exporter, obs=None, wal=None) -> None:
        # the obs agent ships its FINAL report first, while the engines
        # it summarizes are still alive to be read
        if obs is not None:
            try:
                obs.stop(final_report=True)
            except Exception as exc:
                Log.error("obs plane shutdown failed: %s", exc)
        # serving drains next: in-flight replies read tables, so the
        # inference plane must quiesce before any table is torn down
        for srv in servers:
            try:
                srv.stop()
            except Exception as exc:
                Log.error("serving shutdown failed: %s", exc)
        if detector is not None:
            detector.stop()
        live = None
        if bus is not None and bus._survivor_mode:
            # survivor mode: ALWAYS rendezvous via the KV live-set
            # barrier, not just when the LOCAL dead set is non-empty —
            # a survivor whose watchdog hasn't fired yet would
            # otherwise take the all-process device barrier while its
            # peer takes the live-set one, and both would hang.
            # _live_ranks() unions the KV declarations so all
            # survivors agree on the participant list.
            live = bus._live_ranks()
        topology.barrier("mv_shutdown", live)
        survivor = bus is not None and bus._survivor_mode
        if bus is not None:
            # collective: every in-flight delta lands everywhere before
            # any table is torn down (the reference's FinishTrain drain,
            # src/zoo.cpp:96-101)
            dead = set(bus._dead)
            bus.stop()
        if survivor and topo.size > 1:
            # recoverable tasks skip JAX's synchronized shutdown
            # barrier (the coordination service says so explicitly),
            # so an unsynchronized exit lets the coordinator die
            # mid-peer-disconnect (CANCELLED -> fatal error poll).
            # Rendezvous the live set once more, give peers' own
            # disconnects a grace window on rank 0, and disconnect
            # HERE so the atexit teardown finds nothing left to race.
            live = [r for r in range(topo.size) if r not in dead]
            try:
                topology.barrier("mv_exit", live)
            except Exception as exc:
                Log.info("exit rendezvous incomplete (%s); "
                         "proceeding with shutdown", exc)
            import time as _time

            import jax as _jax

            if topo.rank == 0:
                _time.sleep(1.0)
            try:
                _jax.distributed.shutdown()
            except Exception as exc:
                Log.info("distributed shutdown raced a peer exit "
                         "(benign in survivor mode): %s", exc)
        for table in tables:
            flush = getattr(table, "flush", None)
            if flush is not None:
                flush()
        if wal is not None:
            # after the table flushes: no apply path can append anymore
            # (the registry was emptied when the state was claimed)
            wal.close()
        if exporter is not None:
            # final report: the shutdown snapshot lands in the JSONL
            # archive even when the session dies mid-interval
            exporter.stop(final_report=True)
        Dashboard.display()

    def barrier(self) -> None:
        """``MV_Barrier``. In async mode with >1 process this also quiesces
        the delta bus, so barrier-separated phases observe each other's Adds
        — the property the reference's binding tests rely on ("barriers
        between phases make the async PS deterministic", SURVEY §4)."""
        self._require_started()
        if self.async_bus is not None:
            self.async_bus.drain("barrier")
            if self.async_bus._dead:
                # survivor mode: drain's live-set barriers were the
                # rendezvous; a device barrier would wait on the dead peer
                return
        topology.barrier()

    # -- registry ---------------------------------------------------------
    def register_table(self, table: Any) -> int:
        """Assign the next table id (``Zoo::RegisterTable``, ``src/zoo.cpp:172``)."""
        with self._lock:
            self._require_started()
            table_id = len(self.tables)
            self.tables.append(table)
            return table_id

    def table(self, table_id: int) -> Any:
        return self.tables[table_id]

    def register_server(self, server: Any) -> None:
        """Track a serving.InferenceServer so shutdown stops it before
        the tables it reads are torn down."""
        with self._lock:
            self._require_started()
            self.servers.append(server)

    # -- queries (``multiverso.h:18-29``) ---------------------------------
    def _require_started(self) -> None:
        if not self.started or self.topo is None:
            Log.fatal("multiverso-tpu session not initialised; call init() first")

    @property
    def mesh(self):
        self._require_started()
        return self.topo.mesh

    @property
    def table_mesh(self):
        """Mesh parameter tables shard over.

        Sync/MA/single-process: the global mesh (one logical array, BSP
        collectives). Multi-process ASYNC: the process-LOCAL mesh — each
        process holds an independent replica it updates without collective
        participation, and the delta bus (``parallel.async_ps``) provides
        eventual cross-process visibility (the reference's async contract).
        """
        self._require_started()
        if self.async_bus is not None:
            return self.topo.local_mesh
        return self.topo.mesh

    @property
    def rank(self) -> int:
        self._require_started()
        return self.topo.rank

    @property
    def size(self) -> int:
        self._require_started()
        return self.topo.size

    @property
    def num_workers(self) -> int:
        """Size of the ONE worker-id space (dense ids 0..num_workers-1).

        Defined as the mesh ``worker`` axis — the same space the data plane
        shards batches over, the per-worker updater state (AdaGrad slots) is
        sized by, and the bindings' ``workers_num`` reports (the reference's
        dense Zoo worker ids, ``src/zoo.cpp:119-138``). In the canonical
        deployment the worker axis equals the process count (one
        data-parallel worker per process); a single process may declare a
        wider axis (``-mesh_shape``) to drive several worker lanes from one
        host, and then owns all of them.
        """
        self._require_started()
        return self.topo.num_workers

    @property
    def local_workers(self) -> int:
        """Worker lanes owned by this process (num_workers / size)."""
        self._require_started()
        return self.topo.num_workers // self.topo.size

    @property
    def num_servers(self) -> int:
        self._require_started()
        return self.topo.num_servers

    @property
    def worker_id(self) -> int:
        """First worker lane owned by this process (host-side Adds act as
        this worker); lanes are contiguous per process."""
        self._require_started()
        if not (self.role & _ROLE_WORKER):
            return -1
        return self.topo.rank * self.local_workers

    @property
    def server_id(self) -> int:
        self._require_started()
        return self.topo.rank if self.role & _ROLE_SERVER else -1

    def is_worker(self) -> bool:
        return bool(self.role & _ROLE_WORKER)

    def is_server(self) -> bool:
        return bool(self.role & _ROLE_SERVER)

    # -- model averaging ---------------------------------------------------
    def aggregate(self, data: np.ndarray) -> np.ndarray:
        """``MV_Aggregate`` (``src/multiverso.cpp:47-50``): in-place sum of a
        host buffer across all processes. Rides DCN through the JAX
        coordination service instead of ``MPI_Allreduce``; the per-device
        collective form lives in ``parallel.collectives``.
        """
        self._require_started()
        if self.size == 1:
            return data
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray(data))
        summed = np.sum(gathered, axis=0).astype(data.dtype)
        np.copyto(data, summed)
        return data
