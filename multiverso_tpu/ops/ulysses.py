"""Ulysses-style all-to-all sequence parallelism (head-resharded attention).

The second canonical long-context scheme next to ring attention
(``ops/ring_attention.py``): instead of rotating K/V blocks around the ICI
ring, two ``all_to_all`` collectives reshard the activations from
sequence-sharded to head-sharded and back (DeepSpeed-Ulysses, Jacobs et al.,
2023):

  1. q/k/v arrive ``[seq/S, H, d]`` per device (sequence sharded over the
     ``seq`` mesh axis);
  2. ``all_to_all`` (split heads, concat sequence) gives each device the
     FULL sequence for ``H/S`` of the heads;
  3. exact attention runs locally per head — one big MXU matmul chain, no
     per-step collectives;
  4. the reverse ``all_to_all`` restores sequence sharding over all heads.

Compared to ring attention: 2 collectives total instead of S ``ppermute``
steps (better when heads >= devices and the sequence fits in HBM per
device), but requires ``H % S == 0`` where the ring has no head constraint.
Differentiable end-to-end (AD transposes the all_to_alls).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from .._compat import shard_map
from ..topology import SEQ_AXIS
from .ring_attention import _prefix_chunk_attn, reference_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "xla",
    **attn_kwargs,
) -> jax.Array:
    """Exact attention with sequence sharded over ``axis`` via all_to_all.

    Shapes: q/k/v ``[seq, heads, dim]`` sharded ``P(axis, None, None)``;
    requires ``heads % mesh.shape[axis] == 0`` and
    ``seq % mesh.shape[axis] == 0``. Returns the same shape/sharding as
    ``q``. Matches :func:`ring_attention` / :func:`reference_attention`.

    ``impl="flash"`` runs the local per-head full-sequence attention
    through the crossover dispatch (:func:`ops.flash_attention.
    best_attention`) — at long sequences (the regime Ulysses exists for)
    that is the Pallas kernel fwd AND bwd, never slower than the XLA path
    at any length. Extra ``attn_kwargs`` (``min_flash_seq``,
    ``interpret``, block sizes) pass through to the dispatch, which is
    how CI exercises the kernel branch off-TPU (interpret mode).
    """
    if impl not in ("xla", "flash"):
        raise ValueError(f"unknown ulysses impl {impl!r}")
    if impl == "xla" and attn_kwargs:
        raise ValueError("attn_kwargs only apply to impl='flash'")
    n_shards = int(mesh.shape[axis])
    seq, heads = int(q.shape[0]), int(q.shape[1])
    if heads % n_shards != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by mesh axis "
            f"{axis}={n_shards}; use ring_attention for fewer heads")
    if seq % n_shards != 0:
        raise ValueError(f"seq {seq} must divide over {n_shards} shards")
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    spec = P(axis, None, None)
    if impl == "flash":
        from .flash_attention import best_attention as _local_attn
    else:
        _local_attn = reference_attention

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def _ulysses(q_blk, k_blk, v_blk):
        # [seq/S, H, d] -> [seq, H/S, d]: gather the full sequence for a
        # slice of the heads
        def to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=0,
                                      tiled=True)

        qf, kf, vf = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        # the local per-head computation IS the oracle (xla impl) or the
        # crossover-dispatched kernel (flash impl); f32 accumulation inside
        out = _local_attn(qf, kf, vf, causal=causal, scale=scale,
                          **attn_kwargs)
        # [seq, H/S, d] -> [seq/S, H, d]
        return jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=1,
                                  tiled=True).astype(q_blk.dtype)

    return _ulysses(q, k, v)


def ulysses_prefill_attention(q, kc, vc, n_heads: int, offset, mesh,
                              axis: str = SEQ_AXIS) -> jax.Array:
    """All-to-all-resharded serving chunk attention, bit-exact vs the engine.

    The serving face of :func:`ulysses_attention`: ``q [C, D]`` chunk
    rows sharded ``P(axis, None)``, ``kc``/``vc`` ``[T, D]`` the slot's
    gathered paged view HEAD-sharded ``P(None, axis)`` — the paged
    pool's native layout, so the prefix K/V never reshards. One
    ``all_to_all`` turns the row shard of q into a head shard (full
    chunk rows, ``H/n`` whole heads per device — the contiguous
    ``D/n`` slice matches the pool shard by construction), the local
    computation is the engine's exact `_chunk_attention` math over the
    full ``T`` for those heads, and the reverse ``all_to_all`` restores
    row sharding. Per-head math is untouched by the resharding, hence
    bit-identical rows. Requires ``C % n == 0`` and ``n_heads % n == 0``
    (whole heads per device; ``offset`` is the traced global base row).
    """
    n = int(mesh.shape[axis])
    C, D = int(q.shape[0]), int(q.shape[1])
    T = int(kc.shape[0])
    if C % n != 0:
        raise ValueError(f"chunk rows {C} must divide over {n} shards")
    if n_heads % n != 0:
        raise ValueError(
            f"ulysses needs heads ({n_heads}) divisible by mesh axis "
            f"{axis}={n}; use ring_prefill_attention for fewer heads")
    hl = n_heads // n
    dh = D // n_heads

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis, None), P(None, axis), P(None, axis), P()),
             out_specs=P(axis, None), check_vma=False)
    def _ulysses_sp(q_blk, k_blk, v_blk, off):
        # [C/n, D] -> [C, D/n]: full chunk rows for a whole-heads slice
        # (tiled concat lands peer p's rows at p*C/n — global row order)
        qf = jax.lax.all_to_all(q_blk, axis, split_axis=1, concat_axis=0,
                                tiled=True)
        rows = off + jnp.arange(C)
        out = _prefix_chunk_attn(qf.reshape(C, hl, dh),
                                 k_blk.reshape(T, hl, dh),
                                 v_blk.reshape(T, hl, dh), rows, dh)
        # [C, D/n] -> [C/n, D]
        return jax.lax.all_to_all(out.reshape(C, D // n), axis,
                                  split_axis=0, concat_axis=1,
                                  tiled=True).astype(q_blk.dtype)

    return _ulysses_sp(q, kc, vc, offset)
