"""Ulysses-style all-to-all sequence parallelism (head-resharded attention).

The second canonical long-context scheme next to ring attention
(``ops/ring_attention.py``): instead of rotating K/V blocks around the ICI
ring, two ``all_to_all`` collectives reshard the activations from
sequence-sharded to head-sharded and back (DeepSpeed-Ulysses, Jacobs et al.,
2023):

  1. q/k/v arrive ``[seq/S, H, d]`` per device (sequence sharded over the
     ``seq`` mesh axis);
  2. ``all_to_all`` (split heads, concat sequence) gives each device the
     FULL sequence for ``H/S`` of the heads;
  3. exact attention runs locally per head — one big MXU matmul chain, no
     per-step collectives;
  4. the reverse ``all_to_all`` restores sequence sharding over all heads.

Compared to ring attention: 2 collectives total instead of S ``ppermute``
steps (better when heads >= devices and the sequence fits in HBM per
device), but requires ``H % S == 0`` where the ring has no head constraint.
Differentiable end-to-end (AD transposes the all_to_alls).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np

from jax.sharding import PartitionSpec as P

from .._compat import shard_map
from ..topology import SEQ_AXIS
from .ring_attention import reference_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "xla",
    **attn_kwargs,
) -> jax.Array:
    """Exact attention with sequence sharded over ``axis`` via all_to_all.

    Shapes: q/k/v ``[seq, heads, dim]`` sharded ``P(axis, None, None)``;
    requires ``heads % mesh.shape[axis] == 0`` and
    ``seq % mesh.shape[axis] == 0``. Returns the same shape/sharding as
    ``q``. Matches :func:`ring_attention` / :func:`reference_attention`.

    ``impl="flash"`` runs the local per-head full-sequence attention
    through the crossover dispatch (:func:`ops.flash_attention.
    best_attention`) — at long sequences (the regime Ulysses exists for)
    that is the Pallas kernel fwd AND bwd, never slower than the XLA path
    at any length. Extra ``attn_kwargs`` (``min_flash_seq``,
    ``interpret``, block sizes) pass through to the dispatch, which is
    how CI exercises the kernel branch off-TPU (interpret mode).
    """
    if impl not in ("xla", "flash"):
        raise ValueError(f"unknown ulysses impl {impl!r}")
    if impl == "xla" and attn_kwargs:
        raise ValueError("attn_kwargs only apply to impl='flash'")
    n_shards = int(mesh.shape[axis])
    seq, heads = int(q.shape[0]), int(q.shape[1])
    if heads % n_shards != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by mesh axis "
            f"{axis}={n_shards}; use ring_attention for fewer heads")
    if seq % n_shards != 0:
        raise ValueError(f"seq {seq} must divide over {n_shards} shards")
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    spec = P(axis, None, None)
    if impl == "flash":
        from .flash_attention import best_attention as _local_attn
    else:
        _local_attn = reference_attention

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def _ulysses(q_blk, k_blk, v_blk):
        # [seq/S, H, d] -> [seq, H/S, d]: gather the full sequence for a
        # slice of the heads
        def to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=0,
                                      tiled=True)

        qf, kf, vf = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        # the local per-head computation IS the oracle (xla impl) or the
        # crossover-dispatched kernel (flash impl); f32 accumulation inside
        out = _local_attn(qf, kf, vf, causal=causal, scale=scale,
                          **attn_kwargs)
        # [seq, H/S, d] -> [seq/S, H, d]
        return jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=1,
                                  tiled=True).astype(q_blk.dtype)

    return _ulysses(q, k, v)
