"""Pallas TPU flash attention: the framework's hot-op kernel.

The reference framework's hot loops are hand-written C++ (word2vec inner
products, ``Applications/WordEmbedding/src/wordembedding.cpp:57-168``); the
TPU-native analogue is a Pallas kernel feeding the MXU. This module provides
blockwise exact attention (Dao et al. flash schedule) as:

* :func:`flash_attention` — fused single-device attention, O(seq) memory,
  differentiable (custom VJP with a blockwise XLA backward that recomputes
  probabilities from the saved row statistics instead of storing the
  ``[seq, seq]`` score matrix).
* :func:`flash_attention_partial` — the un-normalised building block
  ``(acc, m, l)`` used by ring attention: each ring step runs the kernel on
  the resident K/V block and the cheap running-max merge happens in XLA
  while ``ppermute`` rotates the next block in over ICI.

Layout contract: ``[seq, heads, head_dim]`` at the API boundary (matching
``ops.ring_attention``); kernels run ``[heads, seq, head_dim]`` with the
head as the outer grid axis so each program works on MXU-shaped
``[block_q, head_dim] x [head_dim, block_k]`` tiles. Sequence lengths and
head_dim are padded to tile multiples; padded keys are masked, padded query
rows are sliced away on return.

On non-TPU backends the kernel runs in Pallas interpret mode, which is how
the CPU test suite validates numerics; set ``interpret=False`` to force
compilation (TPU).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jax.Array, size: int, axis: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _pad_dim(d: int) -> int:
    """Head-dim block width: sublane-aligned d stays UNPADDED.

    Pallas pads partial lane blocks inside the VMEM pipeline for free;
    padding d to the 128 lane width in HBM instead (the r3 design)
    materialises pad/slice copies around every kernel call AND doubles
    every d-axis buffer at the common head_dim=64. Measured A/B on-chip
    at the flagship LM shape (r5, xprof device time, 2 runs each,
    docs/LM_MFU.md): lane-padded 53.94 ms vs unpadded 54.08 ms/step at
    seq 1024 — a 0.27% wash. The unpadded form is kept for its halved
    VMEM/HBM d-axis footprint, not for step time; the r4 snapshot's
    "30% of the train step" attribution was the whole flash-vs-XLA
    attention saving (78.2 -> 52.3 ms/step), not the padding delta —
    corrected here.
    ``MV_FLASH_PAD_LANES=1`` re-enables lane padding for measurement.
    Only a non-multiple-of-8 d (never seen in practice) otherwise pads,
    to the f32 sublane tile.
    """
    import os

    if os.environ.get("MV_FLASH_PAD_LANES") == "1":
        return -(-d // _LANES) * _LANES
    return d if d % 8 == 0 else -(-d // 8) * 8


def _fa_kernel(offs_ref, q_ref, k_ref, v_ref,
               o_ref, m_ref, l_ref,
               m_scr, l_scr, acc_scr,
               *, scale: float, causal: bool, normalize: bool,
               kv_len: int, block_q: int, block_k: int, precision):
    """One (head, q-block, k-block) grid step of the flash schedule.

    ``offs_ref`` (scalar prefetch) holds ``[q_base, k_base]`` — global
    position offsets so the same kernel serves both whole-sequence attention
    (zeros) and one ring step (block offsets of the resident shards).
    Running row statistics live in VMEM scratch, carried across the
    innermost (k-block) grid dimension; outputs are written on the last
    k-step.
    """
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_base = offs_ref[0]
    k_base = offs_ref[1]
    qi = pl.program_id(1)

    # Local (padded) k indices of this block and their global positions.
    k_local0 = ki * block_k
    run = jnp.logical_or(
        not causal,
        # last global q position of the block >= first global k position
        q_base + (qi + 1) * block_q - 1 >= k_base + k_local0)
    # Skip key blocks that are entirely padding.
    run = jnp.logical_and(run, k_local0 < kv_len)

    @pl.when(run)
    def _step():
        q = q_ref[0]                                    # [bq, d]
        k = k_ref[0]                                    # [bk, d]
        v = v_ref[0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), precision=precision,
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        k_local = k_local0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_local < kv_len
        if causal:
            q_pos = q_base + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, k_base + k_local <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                           # [bq, 1]
        m_blk = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        m_safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
        corr = jnp.exp(m_prev - m_safe) * (m_prev > _NEG_INF)
        p = jnp.exp(s - m_safe) * (s > _NEG_INF)        # [bq, bk]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            precision=precision,
            preferred_element_type=jnp.float32)          # [bq, d]
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        # m/l outputs are (8, block_q) tiles per (head, q-block) — the
        # minimal f32 tile the TPU lowering accepts; row 0 is the payload.
        m_ref[0, 0] = jnp.broadcast_to(m_scr[:, 0][None, :], m_ref.shape[2:])
        l_ref[0, 0] = jnp.broadcast_to(l_scr[:, 0][None, :], l_ref.shape[2:])
        if normalize:
            denom = jnp.maximum(l_scr[:, :1], 1e-20)
            o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        else:
            o_ref[0] = acc_scr[:].astype(o_ref.dtype)


def _fa_kernel_single(offs_ref, q_ref, k_ref, v_ref,
                      o_ref, m_ref, l_ref,
                      *, scale: float, causal: bool, normalize: bool,
                      kv_len: int, block_q: int, precision):
    """One-k-block forward (``nk == 1``): plain softmax, no online pass.

    With the whole K/V in one block the flash running-max/correction
    machinery (VMEM scratch carries, acc rescale per k-step) is pure
    overhead — the r5 trace measured the general kernel at ~25% of bf16
    peak at the flagship LM shape vs ~43% for the one-pass backward.
    This kernel computes max/exp/sum/divide in one sweep. Outputs match
    the general kernel's contract exactly (same m/l row-stat tiles), so
    the custom VJP and ring merges are unchanged.
    """
    q_base = offs_ref[0]
    k_base = offs_ref[1]
    qi = pl.program_id(1)
    q = q_ref[0]                                        # [bq, d]
    k = k_ref[0]                                        # [sk, d]
    v = v_ref[0]
    sk = k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) * scale      # [bq, sk]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, sk), 1)
    mask = k_pos < kv_len
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, sk), 0)
        mask = jnp.logical_and(mask, k_base + k_pos <= q_base + q_pos)
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)                # [bq, 1]
    m_safe = jnp.where(m <= _NEG_INF, 0.0, m)
    p = jnp.exp(s - m_safe) * (s > _NEG_INF)             # [bq, sk]
    l = jnp.sum(p, axis=1, keepdims=True)                # [bq, 1]
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32)
    m_ref[0, 0] = jnp.broadcast_to(m[:, 0][None, :], m_ref.shape[2:])
    l_ref[0, 0] = jnp.broadcast_to(l[:, 0][None, :], l_ref.shape[2:])
    if normalize:
        o_ref[0] = (pv / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
    else:
        o_ref[0] = pv.astype(o_ref.dtype)


def _fa_call(q, k, v, q_base, k_base, *, causal: bool, scale: float,
             normalize: bool, block_q: int, block_k: int,
             interpret: Optional[bool], precision=None):
    """Pad to tiles, run the kernel, return ([s,h,d] out, [h,s] m, [h,s] l)."""
    if interpret is None:
        interpret = _interpret_default()
    sq, h, d = q.shape
    sk = k.shape[0]
    block_q = min(block_q, max(8, 1 << (sq - 1).bit_length()))
    block_k = min(block_k, max(_LANES, 1 << (sk - 1).bit_length()))
    sq_p = -(-sq // block_q) * block_q
    sk_p = -(-sk // block_k) * block_k
    d_p = _pad_dim(d)

    # [s, h, d] -> [h, s, d], padded
    qt = _pad_to(_pad_to(jnp.transpose(q, (1, 0, 2)), sq_p, 1), d_p, 2)
    kt = _pad_to(_pad_to(jnp.transpose(k, (1, 0, 2)), sk_p, 1), d_p, 2)
    vt = _pad_to(_pad_to(jnp.transpose(v, (1, 0, 2)), sk_p, 1), d_p, 2)
    offs = jnp.asarray([q_base, k_base], jnp.int32)

    nq = sq_p // block_q
    nk = sk_p // block_k

    # normalized attention matches the input dtype — written AT that
    # dtype inside the kernel epilogue (see out_dtype below)
    if nk == 1:
        # whole K/V in one block: plain-softmax kernel, no online pass
        single_grid = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(h, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d_p), lambda hi, qi, offs: (hi, qi, 0)),
                pl.BlockSpec((1, sk_p, d_p), lambda hi, qi, offs: (hi, 0, 0)),
                pl.BlockSpec((1, sk_p, d_p), lambda hi, qi, offs: (hi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d_p), lambda hi, qi, offs: (hi, qi, 0)),
                pl.BlockSpec((1, 1, 8, block_q), lambda hi, qi, offs: (hi, qi, 0, 0)),
                pl.BlockSpec((1, 1, 8, block_q), lambda hi, qi, offs: (hi, qi, 0, 0)),
            ],
        )
        out_dtype = q.dtype if normalize else jnp.float32
        out, m, l = pl.pallas_call(
            functools.partial(
                _fa_kernel_single, scale=scale, causal=causal,
                normalize=normalize, kv_len=sk, block_q=block_q,
                precision=precision),
            grid_spec=single_grid,
            out_shape=[
                jax.ShapeDtypeStruct((h, sq_p, d_p), out_dtype),
                jax.ShapeDtypeStruct((h, nq, 8, block_q), jnp.float32),
                jax.ShapeDtypeStruct((h, nq, 8, block_q), jnp.float32),
            ],
            interpret=interpret,
        )(offs, qt, kt, vt)
        out = jnp.transpose(out[:, :sq, :d], (1, 0, 2))
        m = m[:, :, 0, :].reshape(h, sq_p)[:, :sq]
        l = l[:, :, 0, :].reshape(h, sq_p)[:, :sq]
        return out, m, l

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, normalize=normalize,
        kv_len=sk, block_q=block_q, block_k=block_k, precision=precision)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda hi, qi, ki, offs: (hi, qi, 0)),
            pl.BlockSpec((1, block_k, d_p), lambda hi, qi, ki, offs: (hi, ki, 0)),
            pl.BlockSpec((1, block_k, d_p), lambda hi, qi, ki, offs: (hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda hi, qi, ki, offs: (hi, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda hi, qi, ki, offs: (hi, qi, 0, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda hi, qi, ki, offs: (hi, qi, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d_p), jnp.float32),
        ],
    )
    # normalized attention matches the input dtype — written AT that
    # dtype inside the kernel epilogue, so no f32 round trip through HBM
    # (a post-kernel convert measured ~1 ms/step at the flagship LM
    # shape). Un-normalized partials stay f32 so ring-step merges don't
    # accumulate rounding.
    out_dtype = q.dtype if normalize else jnp.float32
    out, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((h, sq_p, d_p), out_dtype),
            jax.ShapeDtypeStruct((h, nq, 8, block_q), jnp.float32),
            jax.ShapeDtypeStruct((h, nq, 8, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(offs, qt, kt, vt)
    out = jnp.transpose(out[:, :sq, :d], (1, 0, 2))
    m = m[:, :, 0, :].reshape(h, sq_p)[:, :sq]
    l = l[:, :, 0, :].reshape(h, sq_p)[:, :sq]
    return out, m, l


# ---------------------------------------------------------------------------
# public ops
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: Optional[bool] = None,
                    precision=None) -> jax.Array:
    """Fused exact attention. ``q/k/v: [seq, heads, head_dim]``.

    ``precision``: MXU pass precision for the kernel dots (``None`` =
    backend default bf16 passes, ~7e-3 abs error in f32 terms;
    ``jax.lax.Precision.HIGHEST`` for full f32).
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                        precision)
    return out


def _resolve_scale(q, scale):
    return float(scale) if scale is not None else 1.0 / np.sqrt(q.shape[-1])


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               precision=None):
    s = _resolve_scale(q, scale)
    out, m, l = _fa_call(q, k, v, 0, 0, causal=causal, scale=s,
                         normalize=True, block_q=block_q, block_k=block_k,
                         interpret=interpret, precision=precision)
    return out, (q, k, v, out, m, l)


def _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale: float, causal: bool, kv_len: int,
                   block_q: int, block_k: int, precision):
    """dq pass: grid (h, q-block, k-block); dq accumulates in VMEM over the
    innermost k dimension. Probabilities recompute from the saved row
    logsumexp — the flash backward's no-[s,s]-buffer property.

    ``offs_ref`` (scalar prefetch) holds ``[q_base, k_base]`` — global
    position offsets, zeros for whole-sequence backward, shard offsets for
    one ring step (mirrors the forward kernel's contract)."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_base = offs_ref[0]
    k_base = offs_ref[1]
    qi = pl.program_id(1)
    k_local0 = ki * block_k
    run = jnp.logical_or(
        not causal,
        q_base + (qi + 1) * block_q - 1 >= k_base + k_local0)
    run = jnp.logical_and(run, k_local0 < kv_len)

    @pl.when(run)
    def _step():
        q = q_ref[0]                                    # [bq, d]
        k = k_ref[0]                                    # [bk, d]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0, 0][0]                          # [bq]
        delta = delta_ref[0, 0][0]                      # [bq]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), precision=precision,
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_pos = k_local0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, k_base + k_pos <= q_base + q_pos)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), precision=precision,
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale: float, causal: bool, kv_len: int,
                    block_q: int, block_k: int, precision):
    """dk/dv pass: grid (h, k-block, q-block); both accumulate in VMEM over
    the innermost q dimension. ``offs_ref`` as in :func:`_bwd_dq_kernel`."""
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_base = offs_ref[0]
    k_base = offs_ref[1]
    ki = pl.program_id(1)
    k_local0 = ki * block_k
    # causal: q blocks strictly above the diagonal contribute nothing
    run = jnp.logical_or(
        not causal,
        q_base + (qi + 1) * block_q - 1 >= k_base + k_local0)
    run = jnp.logical_and(run, k_local0 < kv_len)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0, 0][0]
        delta = delta_ref[0, 0][0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), precision=precision,
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_pos = k_local0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, k_base + k_pos <= q_base + q_pos)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), precision=precision,
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale           # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, lse_ref,
                      delta_ref, dq_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                      *, scale: float, causal: bool, kv_len: int,
                      block_q: int, precision):
    """Single-pass dq+dk+dv for the ONE-k-block case (``nk == 1``).

    When the whole K/V fits one block (seq <= block_k — the flagship LM
    shape), the two-pass backward recomputes ``s``/``p`` and ``g v^T``
    twice (dq kernel + dkv kernel: 7 block dots, 2 exp sweeps). With
    K/V resident across the q grid this kernel computes them once —
    5 dots, 1 exp — and accumulates dk/dv in VMEM over the sequential
    q dimension (the same revisited-output pattern as the dkv pass).
    Measured on-chip at the flagship LM shape this cuts the train
    step's flash backward cost (docs/LM_MFU.md r5 numbers).
    """
    qi = pl.program_id(1)
    nq = pl.num_programs(1)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_base = offs_ref[0]
    k_base = offs_ref[1]
    q = q_ref[0]                                        # [bq, d]
    k = k_ref[0]                                        # [sk, d]
    v = v_ref[0]
    g = g_ref[0]                                        # [bq, d]
    lse = lse_ref[0, 0][0]                              # [bq]
    delta = delta_ref[0, 0][0]                          # [bq]
    sk = k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) * scale      # [bq, sk]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, sk), 1)
    mask = k_pos < kv_len
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, sk), 0)
        mask = jnp.logical_and(mask, k_base + k_pos <= q_base + q_pos)
    p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)  # [bq, sk]
    dv_scr[:] += jax.lax.dot_general(
        p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)              # [bq, sk]
    ds = p * (dp - delta[:, None]) * scale               # [bq, sk]
    dq_ref[0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_scr[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _stat_tiles(x, h, n_blocks, block: int):
    """[h, s] row statistic -> [h, n_blocks, 8, block] blocked tiles (row 0
    carries the payload; 8 sublanes is the minimal f32 tile height)."""
    xp = _pad_to(x, n_blocks * block, 1).reshape(h, n_blocks, 1, block)
    return jnp.broadcast_to(xp, (h, n_blocks, 8, block))


def _bwd_call(q, k, v, g, lse, delta, q_base, k_base, *, causal: bool,
              scale: float, block_q: int, block_k: int,
              interpret: Optional[bool], precision=None):
    """Backward of one (q rows x k rows) attention block pair.

    ``lse``/``delta`` are the q rows' logsumexp and ``rowsum(g*out)``
    ([h, sq]); ``q_base``/``k_base`` are the rows' global positions for
    causal masking (zeros = whole-sequence). Returns f32
    ``(dq [sq,h,d], dk [sk,h,d], dv [sk,h,d])`` — the contribution of
    THIS k-block to dq and of this q-block to dk/dv, so ring callers can
    accumulate across steps.
    """
    if interpret is None:
        interpret = _interpret_default()
    sq, h, d = q.shape
    sk = k.shape[0]
    block_q = min(block_q, max(8, 1 << (sq - 1).bit_length()))
    block_k = min(block_k, max(_LANES, 1 << (sk - 1).bit_length()))
    sq_p = -(-sq // block_q) * block_q
    sk_p = -(-sk // block_k) * block_k
    d_p = _pad_dim(d)
    nq = sq_p // block_q
    nk = sk_p // block_k

    qt = _pad_to(_pad_to(jnp.transpose(q, (1, 0, 2)), sq_p, 1), d_p, 2)
    kt = _pad_to(_pad_to(jnp.transpose(k, (1, 0, 2)), sk_p, 1), d_p, 2)
    vt = _pad_to(_pad_to(jnp.transpose(v, (1, 0, 2)), sk_p, 1), d_p, 2)
    gt = _pad_to(_pad_to(jnp.transpose(g, (1, 0, 2)), sq_p, 1), d_p, 2)
    # padded q rows get +LARGE lse so their recomputed p == 0
    lse_p = jnp.where((jnp.arange(sq_p) < sq)[None, :],
                      _pad_to(lse, sq_p, 1), -_NEG_INF)
    lse_t = _stat_tiles(lse_p, h, nq, block_q)
    delta_t = _stat_tiles(_pad_to(delta, sq_p, 1), h, nq, block_q)
    offs = jnp.asarray([q_base, k_base], jnp.int32)

    if nk == 1:
        # whole K/V resident -> fused single-pass backward (5 dots and
        # one exp sweep vs the two-pass 7 dots / two sweeps); dk/dv
        # accumulate across the sequential q grid dimension
        fq_spec = pl.BlockSpec((1, block_q, d_p), lambda hi, a, offs: (hi, a, 0))
        fk_spec = pl.BlockSpec((1, sk_p, d_p), lambda hi, a, offs: (hi, 0, 0))
        fstat_spec = pl.BlockSpec((1, 1, 8, block_q),
                                  lambda hi, a, offs: (hi, a, 0, 0))
        fused_grid = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(h, nq),
            in_specs=[fq_spec, fk_spec, fk_spec, fq_spec, fstat_spec,
                      fstat_spec],
            out_specs=[
                pl.BlockSpec((1, block_q, d_p), lambda hi, a, offs: (hi, a, 0)),
                pl.BlockSpec((1, sk_p, d_p), lambda hi, a, offs: (hi, 0, 0)),
                pl.BlockSpec((1, sk_p, d_p), lambda hi, a, offs: (hi, 0, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((sk_p, d_p), jnp.float32),
                            pltpu.VMEM((sk_p, d_p), jnp.float32)],
        )
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                              kv_len=sk, block_q=block_q,
                              precision=precision),
            grid_spec=fused_grid,
            out_shape=[
                jax.ShapeDtypeStruct((h, sq_p, d_p), jnp.float32),
                jax.ShapeDtypeStruct((h, sk_p, d_p), jnp.float32),
                jax.ShapeDtypeStruct((h, sk_p, d_p), jnp.float32),
            ],
            interpret=interpret,
        )(offs, qt, kt, vt, gt, lse_t, delta_t)
        dq = jnp.transpose(dq[:, :sq, :d], (1, 0, 2))
        dk = jnp.transpose(dk[:, :sk, :d], (1, 0, 2))
        dv = jnp.transpose(dv[:, :sk, :d], (1, 0, 2))
        return dq, dk, dv

    q_spec = pl.BlockSpec((1, block_q, d_p),
                          lambda hi, a, b, offs: (hi, a, 0))
    k_spec = pl.BlockSpec((1, block_k, d_p),
                          lambda hi, a, b, offs: (hi, b, 0))
    stat_spec = pl.BlockSpec((1, 1, 8, block_q),
                             lambda hi, a, b, offs: (hi, a, 0, 0))

    dq_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, stat_spec, stat_spec],
        out_specs=pl.BlockSpec((1, block_q, d_p),
                               lambda hi, a, b, offs: (hi, a, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          kv_len=sk, block_q=block_q, block_k=block_k,
                          precision=precision),
        grid_spec=dq_grid,
        out_shape=jax.ShapeDtypeStruct((h, sq_p, d_p), jnp.float32),
        interpret=interpret,
    )(offs, qt, kt, vt, gt, lse_t, delta_t)

    # dk/dv grid: second axis is the K block, innermost is the Q block
    q_spec2 = pl.BlockSpec((1, block_q, d_p),
                           lambda hi, a, b, offs: (hi, b, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d_p),
                           lambda hi, a, b, offs: (hi, a, 0))
    stat_spec2 = pl.BlockSpec((1, 1, 8, block_q),
                              lambda hi, a, b, offs: (hi, b, 0, 0))
    dkv_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, stat_spec2,
                  stat_spec2],
        out_specs=[
            pl.BlockSpec((1, block_k, d_p),
                         lambda hi, a, b, offs: (hi, a, 0)),
            pl.BlockSpec((1, block_k, d_p),
                         lambda hi, a, b, offs: (hi, a, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                        pltpu.VMEM((block_k, d_p), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          kv_len=sk, block_q=block_q, block_k=block_k,
                          precision=precision),
        grid_spec=dkv_grid,
        out_shape=[
            jax.ShapeDtypeStruct((h, sk_p, d_p), jnp.float32),
            jax.ShapeDtypeStruct((h, sk_p, d_p), jnp.float32),
        ],
        interpret=interpret,
    )(offs, qt, kt, vt, gt, lse_t, delta_t)

    dq = jnp.transpose(dq[:, :sq, :d], (1, 0, 2))
    dk = jnp.transpose(dk[:, :sk, :d], (1, 0, 2))
    dv = jnp.transpose(dv[:, :sk, :d], (1, 0, 2))
    return dq, dk, dv


def flash_attention_partial_bwd(q, k, v, g, lse, delta, q_base, k_base,
                                causal: bool = False,
                                scale: Optional[float] = None,
                                block_q: int = 1024, block_k: int = 1024,
                                interpret: Optional[bool] = None,
                                precision=None):
    """One ring step's backward: Pallas dq/dk/dv for a (q-shard, k-shard)
    pair in GLOBAL coordinates (the gradient twin of
    :func:`flash_attention_partial`). ``lse = m + log l`` comes from the
    forward ring's merged statistics; ``delta = rowsum(g * out)`` from the
    normalized output. Returns f32 partials for the caller to accumulate.
    """
    s = _resolve_scale(q, scale)
    return _bwd_call(q, k, v, g, lse, delta, q_base, k_base, causal=causal,
                     scale=s, block_q=block_q, block_k=block_k,
                     interpret=interpret, precision=precision)


def _flash_bwd(causal, scale, block_q, block_k, interpret, precision, res, g):
    """Pallas blockwise backward from saved row stats (no [s,s] buffer).

    Standard flash backward: with row logsumexp ``L = m + log l`` the
    probabilities of any k-block recompute as ``exp(s - L)``; then
    ``dv = p^T g``, ``ds = p * (g v^T - rowsum(g*o))``, ``dq = ds k``,
    ``dk = ds^T q`` — dq in one kernel (k innermost), dk/dv in a second
    (q innermost), both accumulating in VMEM scratch.
    """
    q, k, v, out, m, l = res
    s_scale = _resolve_scale(q, scale)
    lse = m + jnp.log(jnp.maximum(l, 1e-20))                    # [h, sq]
    delta = jnp.einsum("shd,shd->hs", g.astype(jnp.float32),
                       out.astype(jnp.float32))                 # [h, sq]
    dq, dk, dv = _bwd_call(q, k, v, g, lse, delta, 0, 0, causal=causal,
                           scale=s_scale, block_q=block_q, block_k=block_k,
                           interpret=interpret, precision=precision)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# Measured on-chip crossover (docs/TPU_VALIDATE.json): XLA-fused reference
# attention wins below ~1.5k sequence, the Pallas kernel above. Override by
# passing min_flash_seq to best_attention (or monkeypatching this).
FLASH_CROSSOVER_SEQ = 1536


def best_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = False, scale: Optional[float] = None,
                   min_flash_seq: Optional[int] = None,
                   **flash_kwargs) -> jax.Array:
    """Crossover dispatch: never slower than XLA at any sequence length.

    Below the measured crossover the XLA-fused reference attention is
    faster than the Pallas kernel (kernel launch + un-fused epilogue
    dominate at small seq); at/above it the flash schedule's O(seq) memory
    and tiling win 3-5x. ``TransformerConfig(attention="flash")`` routes
    here so users can't be slowed down by picking the kernel at short
    sequences; ``attention="flash_force"`` pins the kernel.
    """
    thr = FLASH_CROSSOVER_SEQ if min_flash_seq is None else int(min_flash_seq)
    # Off-TPU the kernel only exists in Pallas INTERPRET mode (a numerics
    # test vehicle, orders of magnitude slower than XLA) — the crossover
    # constants are TPU measurements, so the dispatch answer off-TPU is
    # always the XLA path unless the caller explicitly asks for the
    # interpreted kernel (interpret=True, as the tests do).
    kernel_viable = (not _interpret_default()
                     or flash_kwargs.get("interpret"))
    if max(q.shape[0], k.shape[0]) < thr or not kernel_viable:
        from .ring_attention import reference_attention

        return reference_attention(q, k, v, causal=causal, scale=scale)
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           **flash_kwargs)


def flash_attention_partial(
        q: jax.Array, k: jax.Array, v: jax.Array,
        q_base, k_base, causal: bool = False,
        scale: Optional[float] = None,
        block_q: int = 1024, block_k: int = 1024,
        interpret: Optional[bool] = None, precision=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Un-normalised flash block: returns ``(acc [s,h,d], m [h,s], l [h,s])``.

    ``q_base``/``k_base`` are the global positions of ``q[0]``/``k[0]``
    (traced scalars are fine) — ring attention passes the shard offsets so
    causal masking applies in global coordinates.
    """
    s = _resolve_scale(q, scale)
    return _fa_call(q, k, v, q_base, k_base, causal=causal, scale=s,
                    normalize=False, block_q=block_q, block_k=block_k,
                    interpret=interpret, precision=precision)


def merge_partials(m_a, l_a, acc_a, m_b, l_b, acc_b):
    """Combine two flash partials (the associative running-max merge)."""
    m = jnp.maximum(m_a, m_b)
    m_safe = jnp.where(m <= _NEG_INF, 0.0, m)
    ca = jnp.exp(m_a - m_safe) * (m_a > _NEG_INF)
    cb = jnp.exp(m_b - m_safe) * (m_b > _NEG_INF)
    l = l_a * ca + l_b * cb
    acc = (acc_a * ca.transpose(1, 0)[:, :, None]
           + acc_b * cb.transpose(1, 0)[:, :, None])
    return m, l, acc
