"""Compute ops: embedding gather/scatter, ring attention, pallas kernels."""

from .embedding import embedding_lookup, scatter_add_rows, segment_mean_rows
from .flash_attention import (flash_attention, flash_attention_partial,
                              merge_partials)
from .moe import (EXPERT_AXIS, init_moe_params, mlp_expert, moe_apply,
                  top1_gating)
from .ring_attention import (reference_attention, ring_attention,
                             ring_prefill_attention)
from .ulysses import ulysses_attention, ulysses_prefill_attention

__all__ = [
    "embedding_lookup",
    "scatter_add_rows",
    "segment_mean_rows",
    "flash_attention",
    "flash_attention_partial",
    "merge_partials",
    "EXPERT_AXIS",
    "init_moe_params",
    "mlp_expert",
    "moe_apply",
    "top1_gating",
    "reference_attention",
    "ring_attention",
    "ring_prefill_attention",
    "ulysses_attention",
    "ulysses_prefill_attention",
]
