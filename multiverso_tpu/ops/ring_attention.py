"""Ring attention: sequence-parallel exact attention over the ``seq`` axis.

The reference predates transformers and has no sequence dimension (survey
§5.7); long-context support is a first-class requirement of this framework,
so it is built on the same substrate as everything else: sharded arrays +
ICI collectives. Q/K/V are sharded along sequence over the ``seq`` mesh
axis; each step computes one block of scores flash-style (running max /
normaliser accumulation, so the full [seq, seq] score matrix never
materialises) while K/V blocks rotate around the ring via ``ppermute`` —
compute overlaps the neighbour exchange, the classic ring-attention
schedule (Liu et al., 2023).

Differentiable end-to-end (autodiff through the scan + ppermute), causal or
full; exact (not windowed) attention.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..topology import SEQ_AXIS
from .flash_attention import flash_attention_partial, merge_partials

from .._compat import shard_map

from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, q_pos, k_pos, causal, scale, m, l, acc):
    """One flash-attention block accumulation step.

    q: [sq, h, d]; k/v: [sk, h, d]; positions: [sq], [sk].
    m/l: [h, sq] running max / normaliser; acc: [sq, h, d].
    """
    scores = jnp.einsum("qhd,khd->hqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = (k_pos[None, :] <= q_pos[:, None])[None, :, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # guard fully-masked rows (m_new == -inf) against NaNs
    m_safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
    correction = jnp.exp(m - m_safe) * (m > _NEG_INF)
    p = jnp.exp(scores - m_safe[:, :, None]) * (scores > _NEG_INF)
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("hqk,khd->qhd", p, v)
    acc_new = acc * correction.transpose(1, 0)[:, :, None] + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "xla",
) -> jax.Array:
    """Exact attention with sequence sharded over ``axis``.

    Shapes: q/k/v ``[seq, heads, dim]`` (batch handled via vmap by callers),
    sharded ``P(axis, None, None)``. Returns same shape/sharding as ``q``.

    ``impl="pallas"`` runs each ring step's block attention as the Pallas
    flash kernel (``ops.flash_attention_partial``) — the MXU-heavy part —
    with the cheap running-max merge in XLA while ``ppermute`` rotates K/V.
    Differentiable: the custom VJP runs a SECOND ring that rotates
    ``(k, v, dk, dv)`` together while the Pallas backward kernels
    (``flash_attention_partial_bwd``) produce each (q-shard, k-shard)
    pair's gradient contribution — dk/dv accumulators arrive back home
    after a full revolution, and activation memory stays O(seq/n) per
    device (only the forward's row statistics are saved; probabilities
    recompute blockwise from the logsumexp).
    """
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    n_blocks = int(mesh.shape[axis])
    seq = q.shape[0]
    if seq % n_blocks != 0:
        raise ValueError(f"seq {seq} must divide over {n_blocks} ring steps")
    block = seq // n_blocks
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    spec = P(axis, None, None)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def _fwd_shard(q_blk, k_blk, v_blk, my_idx):
        """One shard's forward ring; returns (out, lse [h, block])."""
        h = q_blk.shape[1]
        q_pos = my_idx * block + jnp.arange(block)
        # f32 carry regardless of input dtype: both impls produce f32
        # un-normalized partials (bf16 inputs would hit a fori_loop carry
        # dtype mismatch otherwise); cast back to q.dtype at the end
        m0 = jnp.full((h, block), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((h, block), jnp.float32)
        acc0 = jnp.zeros(q_blk.shape, jnp.float32)

        def body(step, carry):
            m, l, acc, k_cur, v_cur = carry
            # after `step` rotations, we hold the block that started at
            # ring position (my_idx - step) mod n
            src = jnp.mod(my_idx - step, n_blocks)
            if impl == "pallas":
                acc_b, m_b, l_b = flash_attention_partial(
                    q_blk, k_cur, v_cur, my_idx * block, src * block,
                    causal=causal, scale=scale)
                m, l, acc = merge_partials(m, l, acc, m_b, l_b, acc_b)
            else:
                k_pos = src * block + jnp.arange(block)
                m, l, acc = _block_attn(q_blk, k_cur, v_cur, q_pos, k_pos,
                                        causal, scale, m, l, acc)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc, _, _ = jax.lax.fori_loop(
            0, n_blocks, body, (m0, l0, acc0, k_blk, v_blk))
        denom = jnp.maximum(l, 1e-20).transpose(1, 0)[:, :, None]
        lse = m + jnp.log(jnp.maximum(l, 1e-20))
        # keep the two impls interchangeable: partial-merge math runs in
        # f32, but the contract is out.dtype == q.dtype
        return (acc / denom).astype(q_blk.dtype), lse

    if impl == "xla":
        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def _ring(q_blk, k_blk, v_blk):
            my_idx = jax.lax.axis_index(axis)
            return _fwd_shard(q_blk, k_blk, v_blk, my_idx)[0]

        return _ring(q, k, v)

    # -- Pallas impl: custom VJP with a backward ring -----------------------
    from .flash_attention import flash_attention_partial_bwd

    lse_spec = P(None, axis)   # [h, seq] row statistics, seq-sharded

    @jax.custom_vjp
    def _ring_pallas(q, k, v):
        return _ring_pallas_fwd(q, k, v)[0]

    def _ring_pallas_fwd(q, k, v):
        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=(spec, lse_spec), check_vma=False)
        def _fwd(q_blk, k_blk, v_blk):
            my_idx = jax.lax.axis_index(axis)
            return _fwd_shard(q_blk, k_blk, v_blk, my_idx)

        out, lse = _fwd(q, k, v)
        return out, (q, k, v, out, lse)

    def _ring_pallas_bwd(res, g):
        q, k, v, out, lse = res

        @partial(shard_map, mesh=mesh,
                 in_specs=(spec, spec, spec, spec, spec, lse_spec),
                 out_specs=(spec, spec, spec), check_vma=False)
        def _bwd(q_blk, k_blk, v_blk, out_blk, g_blk, lse_blk):
            my_idx = jax.lax.axis_index(axis)
            delta = jnp.einsum("shd,shd->hs", g_blk.astype(jnp.float32),
                               out_blk.astype(jnp.float32))   # [h, block]

            def body(step, carry):
                dq, k_cur, v_cur, dk_cur, dv_cur = carry
                src = jnp.mod(my_idx - step, n_blocks)
                dq_p, dk_p, dv_p = flash_attention_partial_bwd(
                    q_blk, k_cur, v_cur, g_blk, lse_blk, delta,
                    my_idx * block, src * block, causal=causal, scale=scale)
                dq = dq + dq_p
                dk_cur = dk_cur + dk_p
                dv_cur = dv_cur + dv_p
                # dk/dv accumulators TRAVEL WITH their k/v block: after a
                # full revolution they are back home carrying every
                # q-shard's contribution
                k_nxt = jax.lax.ppermute(k_cur, axis, perm)
                v_nxt = jax.lax.ppermute(v_cur, axis, perm)
                dk_nxt = jax.lax.ppermute(dk_cur, axis, perm)
                dv_nxt = jax.lax.ppermute(dv_cur, axis, perm)
                return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

            dq0 = jnp.zeros(q_blk.shape, jnp.float32)
            dkv0 = jnp.zeros(k_blk.shape, jnp.float32)
            dq, _, _, dk, dv = jax.lax.fori_loop(
                0, n_blocks, body, (dq0, k_blk, v_blk, dkv0, dkv0))
            return (dq.astype(q_blk.dtype), dk.astype(k_blk.dtype),
                    dv.astype(v_blk.dtype))

        return _bwd(q, k, v, out, g, lse)

    _ring_pallas.defvjp(_ring_pallas_fwd, _ring_pallas_bwd)
    return _ring_pallas(q, k, v)


# -- serving-shaped entry points ----------------------------------------------
#
# The decode engine's chunked prefill attends 2-D operands: a chunk of
# query rows ``q [C, D]`` against the slot's gathered paged view
# ``kc/vc [T, D]`` with a traced global row offset (the prefix-causal
# mask ``t <= offset + row``). The entry points below run that exact
# computation sequence-parallel over a mesh axis — the serving face of
# the [seq, heads, dim] training kernels above. They deliberately do
# NOT reuse the flash-style running-max accumulation (`_block_attn`):
# its reduction order differs from the engine's single-softmax
# `_chunk_attention` math, and the seqpar serving contract is
# bit-identical outputs against the single-lane path.


def _prefix_chunk_attn(qh, kh, vh, rows, dh):
    """The engine's exact chunk-attention math on pre-split heads.

    ``qh [C, H, dh]``, ``kh/vh [T, H, dh]``, ``rows [C]`` global row
    positions (the causal mask bound). Mirrors
    ``models.transformer._chunk_attention`` expression-for-expression —
    one full f32 softmax per row, single P@V over the full ``T`` — so a
    per-head (or per-row-shard) slice of this computation is bitwise
    the single-device computation's slice.
    """
    T = kh.shape[0]
    scores = jnp.einsum("chd,thd->hct", qh, kh,
                        preferred_element_type=jnp.float32) / np.sqrt(dh)
    mask = (jnp.arange(T)[None, :] <= rows[:, None])[None, :, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("hct,thd->chd", probs.astype(vh.dtype), vh)


def ring_prefill_attention(q, kc, vc, n_heads: int, offset, mesh,
                           axis: str = SEQ_AXIS) -> jax.Array:
    """Ring-sharded serving chunk attention, bit-exact vs the engine.

    ``q [C, D]`` chunk rows (sharded ``P(axis, None)`` — each device
    owns ``C/n`` consecutive rows), ``kc``/``vc`` ``[T, D]`` the slot's
    gathered paged view (resharded to ``P(axis, None)`` sequence
    shards), ``offset`` the chunk's traced global base position.
    ``n - 1`` ``ppermute`` rotations reassemble the K/V shards in
    GLOBAL order on every device, then each device runs the engine's
    exact `_chunk_attention` math on its local query rows — same
    softmax, same full-``T`` contraction, hence bit-identical rows.
    Requires ``C % n == 0`` and ``T % n == 0`` (no padding: padding
    would change the reduction length and break bit-exactness).
    """
    n = int(mesh.shape[axis])
    C, D = int(q.shape[0]), int(q.shape[1])
    T = int(kc.shape[0])
    if C % n != 0:
        raise ValueError(f"chunk rows {C} must divide over {n} ring shards")
    if T % n != 0:
        raise ValueError(f"kv length {T} must divide over {n} ring shards")
    dh = D // n_heads
    perm = [(i, (i + 1) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis, None), P(axis, None), P(axis, None), P()),
             out_specs=P(axis, None), check_vma=False)
    def _ring(q_blk, k_blk, v_blk, off):
        idx = jax.lax.axis_index(axis)
        # collect every K/V shard via a static ring of rotations; after
        # j steps we hold the shard that lives at ring position
        # (idx - j) mod n
        k_parts, v_parts = [k_blk], [v_blk]
        k_cur, v_cur = k_blk, v_blk
        for _ in range(n - 1):
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            k_parts.append(k_cur)
            v_parts.append(v_cur)
        # global-order reassembly: shard s sits at part (idx - s) mod n
        order = jnp.mod(idx - jnp.arange(n), n)
        k_full = jnp.take(jnp.stack(k_parts), order, axis=0).reshape(T, D)
        v_full = jnp.take(jnp.stack(v_parts), order, axis=0).reshape(T, D)
        rows = off + idx * (C // n) + jnp.arange(C // n)
        out = _prefix_chunk_attn(q_blk.reshape(C // n, n_heads, dh),
                                 k_full.reshape(T, n_heads, dh),
                                 v_full.reshape(T, n_heads, dh), rows, dh)
        return out.reshape(C // n, D).astype(q_blk.dtype)

    return _ring(q, kc, vc, offset)


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Unsharded O(seq^2) attention — the correctness oracle for tests, and
    the local per-head computation of :func:`ops.ulysses_attention` (scores
    and softmax accumulate in f32 regardless of input dtype)."""
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("qhd,khd->hqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        seq = q.shape[0]
        mask = jnp.tril(jnp.ones((seq, seq), bool))[None, :, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("hqk,khd->qhd", probs.astype(v.dtype),
                      v).astype(q.dtype)
