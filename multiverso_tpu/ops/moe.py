"""Mixture-of-experts layer with expert parallelism over an ``expert`` axis.

The reference has no expert parallelism (SURVEY §2.5). As with pipeline
parallelism, the TPU-first mesh design makes it a natural extension of the
framework's model-parallel substrate: expert weights are sharded over an
``expert`` mesh axis exactly like parameter-table shards over the ``server``
axis, and token routing is two ``lax.all_to_all`` collectives over ICI (the
canonical Switch-Transformer dispatch):

  1. top-1 gating with capacity ``C`` builds one-hot dispatch/combine tensors
     (tokens over capacity are dropped — their combine weight is zero);
  2. tokens are packed into per-expert buffers ``[E, C, d]`` and exchanged
     with ``all_to_all`` so each device holds ``[E/S, S*C, d]`` for its local
     experts;
  3. local experts run as a ``vmap`` over the expert dim (big batched matmuls
     on the MXU);
  4. the reverse ``all_to_all`` returns expert outputs, combined with the
     gate weights.

Everything is expressed with einsums over one-hot tensors, so the layer is
differentiable end-to-end (gate weights carry the gradient through routing).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from .._compat import shard_map

EXPERT_AXIS = "expert"


def top1_gating(logits: jax.Array, capacity: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Switch-style top-1 gating.

    Args:
      logits: ``[T, E]`` router logits for T tokens over E experts.
      capacity: per-expert token budget C.

    Returns ``(dispatch, combine, aux_loss)`` where ``dispatch`` is a
    ``[T, E, C]`` 0/1 routing tensor, ``combine = dispatch * gate`` carries
    the gate probabilities, and ``aux_loss`` is the load-balancing loss
    (mean over experts of fraction-routed x mean-gate x E^2, the Switch
    formulation).
    """
    n_tokens, n_experts = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)                     # [T, E]
    expert_idx = jnp.argmax(gates, axis=-1)                     # [T]
    # Buffer positions are counters: keep them int32 regardless of the
    # logits dtype — a bf16 cumsum loses integer exactness past 256 tokens
    # and would pack multiple tokens into one slot.
    onehot_i = jax.nn.one_hot(expert_idx, n_experts,
                              dtype=jnp.int32)                  # [T, E]
    onehot = onehot_i.astype(logits.dtype)
    # Position of each token within its expert's buffer (0-based).
    position = jnp.cumsum(onehot_i, axis=0) * onehot_i - onehot_i  # [T, E]
    keep = ((position < capacity) & (onehot_i > 0)).astype(
        logits.dtype)                                           # [T, E]
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        position, capacity, dtype=logits.dtype)                 # [T, E, C]
    gate_val = jnp.sum(gates * onehot, axis=-1)                 # [T]
    combine = dispatch * gate_val[:, None, None]                # [T, E, C]
    frac_routed = jnp.mean(onehot, axis=0)                      # [E]
    mean_gate = jnp.mean(gates, axis=0)                         # [E]
    aux = jnp.sum(frac_routed * mean_gate) * n_experts
    return dispatch, combine, aux


def moe_apply(
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    expert_params: Any,
    router_w: jax.Array,
    x: jax.Array,
    mesh,
    axis: str = EXPERT_AXIS,
    capacity_factor: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel MoE layer.

    Args:
      expert_fn: ``(one_expert_params, tokens[c, d]) -> tokens[c, d]``.
      expert_params: pytree with leading dim ``E`` on every leaf, sharded
        over ``axis``.
      router_w: ``[d, E]`` router weights (replicated).
      x: ``[T, d]`` tokens, sharded over ``axis`` on dim 0 (data-parallel
        token groups).
      mesh: mesh containing ``axis`` of size S; requires ``E % S == 0`` and
        ``T % S == 0``.
      capacity_factor: per-expert buffer = ``ceil(cf * T_local / E)``.

    Returns ``(y, aux_loss)`` with ``y`` sharded like ``x``.
    """
    n_shards = int(mesh.shape[axis])
    n_experts = int(router_w.shape[-1])
    if n_experts % n_shards != 0:
        raise ValueError(f"E={n_experts} not divisible by mesh axis "
                         f"{axis}={n_shards}")
    if int(x.shape[0]) % n_shards != 0:
        raise ValueError(f"token count T={int(x.shape[0])} not divisible by "
                         f"mesh axis {axis}={n_shards}")
    tokens_local = int(x.shape[0]) // n_shards
    capacity = int(np.ceil(capacity_factor * tokens_local / n_experts))

    param_spec = jax.tree.map(
        lambda leaf: P(axis, *(None,) * (np.ndim(leaf) - 1)), expert_params)
    x_spec = P(axis, *(None,) * (x.ndim - 1))

    @partial(shard_map, mesh=mesh,
             in_specs=(param_spec, P(), x_spec),
             out_specs=(x_spec, P()),
             check_vma=False)
    def _moe(p_local, rw, x_local):
        logits = x_local @ rw                                   # [t, E]
        dispatch, combine, aux = top1_gating(logits, capacity)
        # Pack per-expert send buffers, then exchange: each device ends up
        # with the [E/S local experts, S*C tokens, d] it is responsible for.
        buf = jnp.einsum("tec,td->ecd", dispatch, x_local)      # [E, C, d]
        buf = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=1,
                                 tiled=True)                    # [E/S, S*C, d]
        out = jax.vmap(expert_fn)(p_local, buf)                 # [E/S, S*C, d]
        out = jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                                 tiled=True)                    # [E, C, d]
        y = jnp.einsum("tec,ecd->td", combine, out)             # [t, d]
        return y, jax.lax.pmean(aux, axis)

    return _moe(expert_params, router_w, x)


def mlp_expert(params: Any, tokens: jax.Array) -> jax.Array:
    """Default expert: 2-layer GELU MLP ``{w1: [d, h], w2: [h, d]}``."""
    h = jax.nn.gelu(tokens @ params["w1"])
    return h @ params["w2"]


def init_moe_params(rng: np.random.Generator, n_experts: int, d_model: int,
                    d_hidden: int, dtype=jnp.float32):
    """Random router + stacked expert MLP params (numpy rng for portability)."""
    scale_in = 1.0 / np.sqrt(d_model)
    scale_hid = 1.0 / np.sqrt(d_hidden)
    router_w = jnp.asarray(
        rng.standard_normal((d_model, n_experts)) * scale_in, dtype)
    expert_params = {
        "w1": jnp.asarray(
            rng.standard_normal((n_experts, d_model, d_hidden)) * scale_in,
            dtype),
        "w2": jnp.asarray(
            rng.standard_normal((n_experts, d_hidden, d_model)) * scale_hid,
            dtype),
    }
    return router_w, expert_params
