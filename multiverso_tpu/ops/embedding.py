"""Embedding gather/scatter ops: the sparse-access substrate.

The reference's closest analog is the row-keyed MatrixTable traffic that
WordEmbedding drives (row Gets of touched vocab rows, row Adds of deltas —
``Applications/WordEmbedding/src/communicator.cpp:105,194`` in the Multiverso
reference). On TPU these are ``take`` gathers and ``segment_sum`` scatters
over an HBM-resident embedding matrix; XLA fuses the surrounding elementwise
work. Used by the word2vec model's hot loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def embedding_lookup(table: jax.Array, ids: jax.Array) -> jax.Array:
    """Gather rows: [vocab, dim] x [n] -> [n, dim]."""
    return jnp.take(table, ids, axis=0)


def scatter_add_rows(table: jax.Array, ids: jax.Array,
                     deltas: jax.Array) -> jax.Array:
    """Scatter-accumulate row deltas (duplicates sum, XLA scatter-add)."""
    return table.at[ids].add(deltas.astype(table.dtype))


def segment_mean_rows(values: jax.Array, segment_ids: jax.Array,
                      num_segments: int) -> jax.Array:
    """Mean-combine rows per segment (CBOW context averaging)."""
    sums = jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)
    counts = jax.ops.segment_sum(
        jnp.ones((values.shape[0],), values.dtype), segment_ids,
        num_segments=num_segments)
    return sums / jnp.maximum(counts, 1.0)[:, None]
