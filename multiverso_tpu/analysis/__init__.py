"""Repo-native static analysis + runtime concurrency witnesses.

The stack has two correctness regimes that runtime asserts alone cannot
enforce at review time:

* **XLA trace discipline** — one compiled trace per engine config, block
  tables traced as data, no host coercion of traced values. Violations
  do not crash; they silently recompile and torpedo the p99.
* **host thread discipline** — batcher, decode loop, watchdog, metrics
  exporter and the async-PS bus all share locks. PRs 4-6 each hand-fixed
  a concurrency bug (DerivedCache compute race, reporter detach under
  the registry lock, leaked reporter threads) a checker would have
  caught mechanically.

Three tools enforce them:

* :mod:`~multiverso_tpu.analysis.retrace_lint` — AST pass flagging
  recompile/trace hazards in jit-reachable code (RT1xx rules).
* :mod:`~multiverso_tpu.analysis.locklint` — AST pass extracting every
  ``with <lock>`` region, building the inter-lock acquisition graph and
  flagging cycles, callbacks and blocking calls under locks (LK2xx).
* :mod:`~multiverso_tpu.analysis.lockwatch` — a runtime witness: an
  instrumented Lock wrapper recording per-thread acquisition order into
  a global DAG, tripping ``LOCK_ORDER_VIOLATIONS`` (and a watchdog
  ``lock_order`` trip) on cycles. Autouse in the test suite; behind the
  ``-lockwatch`` flag in serving.

Driven by ``tools/lint.py`` with a justified-suppression baseline
(``tools/lint_baseline.txt``). See docs/ANALYSIS.md for the rule
catalog and triage guidance.

This ``__init__`` stays import-light on purpose: ``lockwatch`` is
imported by the serving hot path (dashboard/batcher/engine lock
construction), so pulling the AST passes in here would tax every
process start for tooling only ``tools/lint.py`` needs.
"""
