"""Shared linter plumbing: findings, baselines, file walking.

A :class:`Finding` is identified by ``rule path::qualname::slug`` —
deliberately WITHOUT a line number, so a justified suppression survives
unrelated edits to the same file. The baseline file
(``tools/lint_baseline.txt``) holds one suppression per line::

    LK203 multiverso_tpu/runtime.py::Session.stop::join -- shutdown is \
the serialization point; nothing re-enters the Session lock

Everything after ``--`` is the REQUIRED justification: a baseline line
without one is itself an error (``tools/lint.py`` refuses to run with
an unjustified suppression — the whole point is that every silenced
finding carries its defense in-tree).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass
class Finding:
    rule: str          # e.g. "LK203"
    path: str          # repo-relative posix path
    line: int          # 1-based line (display only, not identity)
    qualname: str      # enclosing scope, e.g. "Session.stop" or "<module>"
    slug: str          # short stable discriminator, e.g. "join"
    message: str

    @property
    def identity(self) -> str:
        return f"{self.rule} {self.path}::{self.qualname}::{self.slug}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.qualname}"
                f"::{self.slug}] {self.message}")


class BaselineError(ValueError):
    """Malformed baseline file (missing justification, bad shape)."""


def load_baseline(path: str) -> Dict[str, str]:
    """``{finding identity: justification}`` from a baseline file."""
    entries: Dict[str, str] = {}
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "--" not in line:
                raise BaselineError(
                    f"{path}:{i}: baseline entry has no '-- justification' "
                    f"part: {line!r}")
            ident, _, why = line.partition("--")
            ident = " ".join(ident.split())
            why = why.strip()
            if not why:
                raise BaselineError(
                    f"{path}:{i}: empty justification for {ident!r}")
            parts = ident.split(" ")
            if len(parts) != 2 or "::" not in parts[1]:
                raise BaselineError(
                    f"{path}:{i}: expected 'RULE path::qual::slug', "
                    f"got {ident!r}")
            entries[ident] = why
    return entries


def split_findings(findings: Iterable[Finding], baseline: Dict[str, str]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """``(unsuppressed, suppressed, stale baseline identities)``."""
    fresh: List[Finding] = []
    silenced: List[Finding] = []
    seen = set()
    for f in findings:
        if f.identity in baseline:
            silenced.append(f)
            seen.add(f.identity)
        else:
            fresh.append(f)
    stale = [ident for ident in baseline if ident not in seen]
    return fresh, silenced, stale


def iter_py_files(paths: Iterable[str],
                  exclude_parts: Tuple[str, ...] = ("__pycache__",)
                  ) -> List[str]:
    """Expand files/directories into a sorted .py file list."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in exclude_parts)
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return sorted(set(out))


def rel_posix(path: str, root: Optional[str] = None) -> str:
    root = root or os.getcwd()
    try:
        rel = os.path.relpath(os.path.abspath(path), root)
    except ValueError:      # pragma: no cover - cross-drive on win
        rel = path
    return rel.replace(os.sep, "/")


@dataclass
class Module:
    """One parsed source file plus the derived names other passes need."""

    path: str              # repo-relative posix path
    name: str              # dotted module name ("multiverso_tpu.trace")
    tree: ast.Module
    source: str
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    functions: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    # import name -> (module dotted name, attr or None)
    imports: Dict[str, Tuple[str, Optional[str]]] = field(
        default_factory=dict)


def module_name_for(relpath: str) -> str:
    stem = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = stem.replace("\\", "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p)


def parse_module(path: str, root: Optional[str] = None) -> Optional[Module]:
    rel = rel_posix(path, root)
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel)
    except (OSError, SyntaxError):
        return None
    mod = Module(path=rel, name=module_name_for(rel), tree=tree,
                 source=source)
    pkg_parts = mod.name.split(".")[:-1]
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            mod.classes[node.name] = node
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = node
        elif isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[alias.asname or alias.name.split(".")[0]] = (
                    alias.name, None)
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:      # resolve relative to this module's package
                up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(up + ([base] if base else []))
            for alias in node.names:
                mod.imports[alias.asname or alias.name] = (base, alias.name)
    return mod
