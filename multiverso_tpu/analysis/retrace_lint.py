"""Trace-hazard linter: recompile/retrace hazards in jit-reachable code.

The repo's hardest-won perf invariant is ONE compiled trace per engine
config (ROADMAP "one-trace invariant"): block tables ride the jitted
step as data, shapes never depend on the request mix, and a single
accidental host round-trip or per-iteration ``jax.jit`` erases the
continuous-batching win silently — no crash, just a p99 that rots. This
AST pass flags the hazard patterns statically:

* **RT101 jit-in-loop** — ``jax.jit``/``pjit`` CONSTRUCTED inside a
  ``for``/``while`` body or comprehension. Each construction is a fresh
  callable with a cold cache: the loop recompiles every iteration.
* **RT102 traced-host-coercion** — inside a jit-traced function,
  ``int()``/``float()``/``bool()`` of a traced value, ``.item()``/
  ``.tolist()``, or ``np.*`` applied to traced arguments. Under trace
  these either raise ``ConcretizationTypeError`` or silently force a
  host sync + constant-fold that retraces per value. ``x.shape``/
  ``.dtype``/``.ndim``/``len(x)`` are static under trace and exempt.
* **RT103 traced-python-branch** — ``if``/``while``/``assert``/ternary
  on a traced value (or a Python ``for`` iterating one): control flow
  must go through ``jnp.where``/``lax.cond``; a Python branch bakes the
  taken side into the trace and retraces (or raises) on the other.
* **RT104 mutable-static** — a jitted closure capturing a name bound to
  a mutable literal (list/dict/set/``np.array``) in an enclosing scope,
  or a call site passing a list/dict/set literal in a
  ``static_argnums``/``static_argnames`` position. Statics key the
  compile cache by hash/equality; mutables either throw
  (unhashable) or — worse — mutate without retriggering a trace.
* **RT105 donated-reuse** — a value read again after being passed in a
  ``donate_argnums`` position of a jitted handle without reassignment.
  The donated buffer may already be aliased into the output; reading it
  is use-after-free on accelerators (and a silent defensive copy +
  retrace on CPU — the 2.4->22 ms/step regression PR 2 measured).
* **RT106 jit-in-iteration-path** — the one-trace invariant, enforced
  structurally: in any class with a ``_loop`` method (the engine
  shape), no ``jax.jit``/``pjit`` construction may be reachable from
  ``_loop`` via self-calls — neither directly nor through a
  module-level BUILDER function that (transitively) constructs one
  (the sharded-program-builder shape:
  ``models.transformer.make_sharded_decode_programs`` and friends
  return pre-partitioned pjit handles). Same-module builders are
  caught per-module; imported ones link in whole-tree runs
  (:func:`lint_modules` — the ``tools/lint.py`` path), including
  function-level imports. Jits and builder calls belong
  to construction (``__init__``) and ``warmup`` only — those are
  construction-time sites by contract, not per-iteration hazards.

Jit-traced functions are found per module (decorated ``@jax.jit`` /
``@partial(jax.jit, ...)``, wrapped ``jax.jit(f)``, jitted lambdas) and
taint propagates intra-module: a helper called from a traced function
with traced arguments is analyzed with those parameters traced too —
which is how ``models/transformer.py``'s kernel helpers get covered
without any annotations.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import Finding, Module

_SHAPE_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
_COERCERS = {"int", "float", "bool", "complex"}
_NP_NAMES = {"np", "numpy", "onp"}


def _chain(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _own_nodes(stmt: ast.stmt) -> List[ast.AST]:
    """AST nodes belonging directly to ``stmt`` — its own expressions,
    NOT the statements nested in its body/orelse/handlers. Those nested
    statements appear in the flattened statement list themselves; walking
    into them here would scan every ``with lock: x = f(x)`` body twice
    (once via the With, once via the Assign) and mis-order the
    read-vs-donate phases."""
    out: List[ast.AST] = []
    work: List[ast.AST] = [stmt]
    while work:
        node = work.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.stmt):
                work.append(child)
    return out


def _is_jit_func(node: ast.AST) -> bool:
    """``jax.jit`` / ``jax.pjit`` / bare ``jit``/``pjit`` reference."""
    ch = _chain(node)
    if not ch:
        return False
    if ch in (["jax", "jit"], ["jax", "pjit"], ["pjit", "pjit"]):
        return True
    return len(ch) == 1 and ch[0] in ("jit", "pjit")


def _jit_construction(call: ast.Call) -> bool:
    if _is_jit_func(call.func):
        return True
    # functools.partial(jax.jit, ...)
    ch = _chain(call.func)
    if ch and ch[-1] == "partial" and call.args \
            and _is_jit_func(call.args[0]):
        return True
    return False


def _literal_int_tuple(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _literal_str_tuple(node: Optional[ast.AST]) -> Optional[Tuple[str, ...]]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _mutable_literal(node: Optional[ast.AST]) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        ch = _chain(node.func)
        if ch and len(ch) == 1 and ch[0] in ("list", "dict", "set",
                                             "bytearray"):
            return True
        if ch and len(ch) == 2 and ch[0] in _NP_NAMES \
                and ch[1] in ("array", "zeros", "ones", "empty", "full",
                              "arange"):
            return True
    return False


@dataclass
class _JitSite:
    """One jax.jit/pjit construction."""

    call: ast.Call
    qualname: str
    target: Optional[ast.AST]            # FunctionDef | Lambda | None
    target_name: Optional[str]
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Optional[Tuple[int, ...]] = None
    handle: Optional[Tuple[str, ...]] = None   # assignment target chain
    in_loop: bool = False


@dataclass
class _Scope:
    node: ast.AST                         # FunctionDef | Lambda | Module
    qualname: str
    parent: Optional["_Scope"]
    defs: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    mutable_names: Dict[str, int] = field(default_factory=dict)
    assigned: Set[str] = field(default_factory=set)


class _ScopeCollector(ast.NodeVisitor):
    """First pass: scope tree, function defs, jit sites, loop nesting."""

    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.root = _Scope(mod.tree, "<module>", None)
        self.scopes: Dict[int, _Scope] = {id(mod.tree): self.root}
        self.jit_sites: List[_JitSite] = []
        self._stack: List[_Scope] = [self.root]
        self._loop_depth = 0

    def _qual(self, name: str) -> str:
        cur = self._stack[-1].qualname
        return name if cur == "<module>" else f"{cur}.{name}"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        scope = _Scope(node, self._qual(node.name), self._stack[-1])
        self.scopes[id(node)] = scope
        self._stack.append(scope)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._stack[-1].defs[node.name] = node
        scope = _Scope(node, self._qual(node.name), self._stack[-1])
        self.scopes[id(node)] = scope
        self._stack.append(scope)
        outer_loop, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_loop
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        scope = _Scope(node, self._qual("<lambda>"), self._stack[-1])
        self.scopes[id(node)] = scope
        self._stack.append(scope)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_loop(self, node: ast.AST) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _visit_loop
    visit_ListComp = visit_SetComp = visit_DictComp = \
        visit_GeneratorExp = _visit_loop

    def visit_Assign(self, node: ast.Assign) -> None:
        scope = self._stack[-1]
        for tgt in node.targets:
            for name_node in ast.walk(tgt):
                if isinstance(name_node, ast.Name):
                    scope.assigned.add(name_node.id)
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name) \
                and _mutable_literal(node.value):
            scope.mutable_names[node.targets[0].id] = node.lineno
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _jit_construction(node):
            site = self._make_site(node)
            site.in_loop = self._loop_depth > 0
            self.jit_sites.append(site)
        self.generic_visit(node)

    def _make_site(self, call: ast.Call) -> _JitSite:
        target = None
        target_name = None
        args = call.args
        if _chain(call.func) and _chain(call.func)[-1] == "partial":
            args = call.args[1:]
        if args:
            arg0 = args[0]
            if isinstance(arg0, ast.Lambda):
                target = arg0
            elif isinstance(arg0, ast.Name):
                target_name = arg0.id
                target = self._lookup_def(arg0.id)
        statics: Tuple[int, ...] = ()
        static_names: Tuple[str, ...] = ()
        donate = None
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                statics = _literal_int_tuple(kw.value) or ()
            elif kw.arg == "static_argnames":
                static_names = _literal_str_tuple(kw.value) or ()
            elif kw.arg == "donate_argnums":
                donate = _literal_int_tuple(kw.value)
        return _JitSite(call=call, qualname=self._stack[-1].qualname,
                        target=target, target_name=target_name,
                        static_argnums=statics,
                        static_argnames=static_names,
                        donate_argnums=donate)

    def _lookup_def(self, name: str) -> Optional[ast.FunctionDef]:
        scope: Optional[_Scope] = self._stack[-1]
        while scope is not None:
            if name in scope.defs:
                return scope.defs[name]
            scope = scope.parent
        return None


class _TaintChecker(ast.NodeVisitor):
    """Flags host-coercion / python-branch hazards inside one traced
    function, given its traced parameter names. Records intra-module
    call propagation requests."""

    def __init__(self, linter: "RetraceLint", func: ast.AST,
                 qualname: str, tainted: Set[str]) -> None:
        self.linter = linter
        self.func = func
        self.qualname = qualname
        self.tainted = set(tainted)
        self.calls: List[Tuple[str, List[bool], int]] = []

    # -- taint of an expression ---------------------------------------------
    def _expr_tainted(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                # x.shape / len(x) are static under trace: only a use
                # OUTSIDE such metadata contexts makes the expr dynamic
                if self._under_shape_attr(node, sub):
                    continue
                return True
        return False

    @staticmethod
    def _under_shape_attr(root: ast.AST, target: ast.Name) -> bool:
        """True when ``target`` only appears as ``target.shape``-style
        static metadata (or inside ``len(...)``) within ``root``."""
        class Finder(ast.NodeVisitor):
            def __init__(self) -> None:
                self.dynamic = False

            def visit_Attribute(self, node: ast.Attribute) -> None:
                if node.attr in _SHAPE_ATTRS:
                    return          # subtree is static metadata
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                ch = _chain(node.func)
                if ch == ["len"]:
                    return          # len() of traced is static
                self.generic_visit(node)

            def visit_Compare(self, node: ast.Compare) -> None:
                # `x is None` / `x is not None` is an IDENTITY check —
                # static under trace (a tracer is never None), and the
                # standard JAX optional-argument dispatch idiom
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in node.ops) \
                        and all(isinstance(c, ast.Constant)
                                and c.value is None
                                for c in node.comparators):
                    return
                self.generic_visit(node)

            def visit_Name(self, node: ast.Name) -> None:
                if node is target:
                    self.dynamic = True

        f = Finder()
        f.visit(root)
        return not f.dynamic

    def _flag(self, rule: str, slug: str, node: ast.AST,
              msg: str) -> None:
        self.linter.add_finding(rule, slug, getattr(node, "lineno", 1),
                                self.qualname, msg)

    # -- statements ---------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        if self._expr_tainted(node.value):
            for tgt in node.targets:
                for nn in ast.walk(tgt):
                    if isinstance(nn, ast.Name):
                        self.tainted.add(nn.id)
        self.generic_visit_targets(node)

    def generic_visit_targets(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                self.visit(tgt)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if self._expr_tainted(node.value) and isinstance(node.target,
                                                         ast.Name):
            self.tainted.add(node.target.id)

    def visit_If(self, node: ast.If) -> None:
        if self._expr_tainted(node.test):
            self._flag("RT103", "branch", node,
                       "Python `if` on a traced value — use jnp.where/"
                       "lax.cond (a traced branch bakes one side into "
                       "the compiled trace)")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._expr_tainted(node.test):
            self._flag("RT103", "branch", node,
                       "Python `while` on a traced value — use "
                       "lax.while_loop")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        if self._expr_tainted(node.test):
            self._flag("RT103", "branch", node,
                       "ternary on a traced value — use jnp.where")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if self._expr_tainted(node.test):
            self._flag("RT103", "assert", node,
                       "assert on a traced value forces a host sync "
                       "under trace")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        # A tuple-unpacking target (`for rows, occ in sets`) or an
        # enumerate/zip/range iterator means a *Python container* of
        # traced values — static-length unrolling, the sanctioned JAX
        # idiom — not iteration over a traced array's leading axis.
        container = isinstance(node.target, (ast.Tuple, ast.List))
        if isinstance(node.iter, ast.Call):
            fch = _chain(node.iter.func)
            if fch and fch[-1] in ("enumerate", "zip", "range"):
                container = True
        if not container and self._expr_tainted(node.iter):
            self._flag("RT103", "iterate", node,
                       "Python `for` over a traced value unrolls (and "
                       "retraces per length) — use lax.scan/fori_loop")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        ch = _chain(node.func)
        if ch:
            if len(ch) == 1 and ch[0] in _COERCERS \
                    and any(self._expr_tainted(a) for a in node.args):
                self._flag("RT102", "coerce", node,
                           f"{ch[0]}() of a traced value — a host "
                           "concretization under trace")
            elif ch[-1] in ("item", "tolist") and len(ch) >= 2 \
                    and isinstance(node.func, ast.Attribute) \
                    and self._expr_tainted(node.func.value):
                self._flag("RT102", "item", node,
                           f".{ch[-1]}() on a traced value — device "
                           "sync + concretization under trace")
            elif ch[0] in _NP_NAMES and len(ch) >= 2 \
                    and any(self._expr_tainted(a) for a in node.args):
                self._flag("RT102", "numpy", node,
                           f"{'.'.join(ch)}() applied to a traced value "
                           "— numpy concretizes (use jnp)")
            elif len(ch) == 1:
                # intra-module propagation request
                taint_mask = [self._expr_tainted(a) for a in node.args]
                if any(taint_mask):
                    self.calls.append((ch[0], taint_mask, node.lineno))
        self.generic_visit(node)

    def run(self) -> None:
        body = self.func.body if isinstance(
            self.func, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            else [ast.Expr(value=self.func.body)]
        for stmt in body:
            self.visit(stmt)


class RetraceLint:
    """Per-module trace-hazard analysis."""

    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.findings: List[Finding] = []
        self._emitted: Set[Tuple[str, str, str]] = set()
        self._builders: Optional[Set[str]] = None
        collector = _ScopeCollector(mod)
        collector.visit(mod.tree)
        self.collector = collector

    def add_finding(self, rule: str, slug: str, line: int, qual: str,
                    msg: str) -> None:
        key = (rule, qual, slug)
        if key in self._emitted:
            return
        self._emitted.add(key)
        self.findings.append(Finding(rule=rule, path=self.mod.path,
                                     line=line, qualname=qual, slug=slug,
                                     message=msg))

    # -- entry --------------------------------------------------------------
    def run(self, extern_builders: Set[str] = frozenset()) -> List[Finding]:
        """``extern_builders``: local names imported from OTHER modules
        that :func:`lint_modules` resolved to jit/pjit builders there —
        the cross-module half of RT106's builder detection."""
        self._rt101_jit_in_loop()
        jit_targets = self._traced_targets()
        self._rt102_103_taint(jit_targets)
        self._rt104_mutable_static()
        self._rt105_donated_reuse()
        self._rt106_loop_reachable_jit(extern_builders)
        return self.findings

    # -- RT101 --------------------------------------------------------------
    def _rt101_jit_in_loop(self) -> None:
        for site in self.collector.jit_sites:
            if site.in_loop:
                self.add_finding(
                    "RT101", "jit-in-loop", site.call.lineno, site.qualname,
                    "jax.jit constructed inside a loop — every iteration "
                    "builds a fresh callable with a cold compile cache; "
                    "hoist the construction out of the loop")

    # -- RT102/RT103 --------------------------------------------------------
    def _decorated_targets(self) -> List[Tuple[ast.FunctionDef,
                                               Tuple[int, ...],
                                               Tuple[str, ...], str]]:
        out = []
        for scope in self.collector.scopes.values():
            node = scope.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                statics: Tuple[int, ...] = ()
                static_names: Tuple[str, ...] = ()
                is_jit = _is_jit_func(dec)
                if isinstance(dec, ast.Call) and _jit_construction(dec):
                    is_jit = True
                    for kw in dec.keywords:
                        if kw.arg == "static_argnums":
                            statics = _literal_int_tuple(kw.value) or ()
                        elif kw.arg == "static_argnames":
                            static_names = _literal_str_tuple(
                                kw.value) or ()
                if is_jit:
                    out.append((node, statics, static_names,
                                scope.qualname))
        return out

    def _traced_params(self, func: ast.AST, statics: Tuple[int, ...],
                       static_names: Tuple[str, ...]) -> Set[str]:
        args = func.args
        names = [a.arg for a in args.args]
        traced = set()
        for i, name in enumerate(names):
            if i in statics or name in static_names:
                continue
            if name in ("self", "cls"):
                continue
            traced.add(name)
        traced.update(a.arg for a in args.kwonlyargs
                      if a.arg not in static_names)
        return traced

    def _traced_targets(self) -> List[Tuple[ast.AST, str, Set[str]]]:
        """(function node, qualname, traced param names) for every
        jit-traced function in the module."""
        out: List[Tuple[ast.AST, str, Set[str]]] = []
        seen: Set[int] = set()
        for site in self.collector.jit_sites:
            if site.target is None or id(site.target) in seen:
                continue
            seen.add(id(site.target))
            scope = self.collector.scopes.get(id(site.target))
            qual = scope.qualname if scope else site.qualname
            out.append((site.target, qual, self._traced_params(
                site.target, site.static_argnums, site.static_argnames)))
        for node, statics, static_names, qual in self._decorated_targets():
            if id(node) in seen:
                continue
            seen.add(id(node))
            out.append((node, qual, self._traced_params(
                node, statics, static_names)))
        return out

    def _rt102_103_taint(self, targets: List[Tuple[ast.AST, str,
                                                   Set[str]]]) -> None:
        # worklist: (func node, qual, traced names); propagate through
        # same-module calls whose arguments are tainted
        taints: Dict[int, Set[str]] = {}
        queue: List[Tuple[ast.AST, str, Set[str]]] = list(targets)
        guard = 0
        while queue and guard < 500:
            guard += 1
            func, qual, traced = queue.pop()
            prev = taints.get(id(func), set())
            merged = prev | traced
            if merged == prev and guard > len(targets):
                continue
            taints[id(func)] = merged
            checker = _TaintChecker(self, func, qual, merged)
            checker.run()
            for callee_name, mask, _line in checker.calls:
                callee = self._lookup_any_def(callee_name)
                if callee is None:
                    continue
                params = [a.arg for a in callee.args.args]
                callee_traced = {params[i] for i, t in enumerate(mask)
                                 if t and i < len(params)}
                if not callee_traced:
                    continue
                scope = self.collector.scopes.get(id(callee))
                cqual = scope.qualname if scope else callee_name
                if not callee_traced <= taints.get(id(callee), set()):
                    queue.append((callee, cqual, callee_traced))

    def _lookup_any_def(self, name: str) -> Optional[ast.FunctionDef]:
        fn = self.mod.functions.get(name)
        if fn is not None:
            return fn
        for scope in self.collector.scopes.values():
            if name in scope.defs:
                return scope.defs[name]
        return None

    # -- RT104 --------------------------------------------------------------
    def _rt104_mutable_static(self) -> None:
        for site in self.collector.jit_sites:
            target = site.target
            if target is not None:
                scope = self.collector.scopes.get(id(target))
                free = self._free_names(target)
                enclosing = scope.parent if scope else None
                while enclosing is not None:
                    hits = free & set(enclosing.mutable_names)
                    for name in sorted(hits):
                        self.add_finding(
                            "RT104", "mutable-capture",
                            site.call.lineno, site.qualname,
                            f"jitted function closes over {name!r}, "
                            f"bound to a mutable literal at line "
                            f"{enclosing.mutable_names[name]} — a "
                            "mutation never retriggers tracing "
                            "(stale constant baked into the trace)")
                    free -= hits
                    enclosing = enclosing.parent
        self._rt104_static_callsites()

    def _rt104_static_callsites(self) -> None:
        """Calls of a jitted handle passing a list/dict/set literal in a
        ``static_argnums`` position (unhashable compile-cache key)."""
        handles: Dict[Tuple[str, ...], Tuple[int, ...]] = {}
        jit_calls = {id(s.call) for s in self.collector.jit_sites}
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call) \
                    and id(node.value) in jit_calls:
                statics = ()
                for kw in node.value.keywords:
                    if kw.arg == "static_argnums":
                        statics = _literal_int_tuple(kw.value) or ()
                tch = _chain(node.targets[0])
                if tch and statics:
                    handles[tuple(tch)] = statics
        if not handles:
            return
        for scope in self.collector.scopes.values():
            node = scope.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fch = _chain(sub.func)
                key = tuple(fch) if fch else None
                if key not in handles:
                    continue
                for pos in handles[key]:
                    if pos < len(sub.args) and isinstance(
                            sub.args[pos], (ast.List, ast.Dict, ast.Set)):
                        self.add_finding(
                            "RT104", "unhashable-static", sub.lineno,
                            scope.qualname,
                            f"call of jitted {'.'.join(key)} passes a "
                            f"mutable literal at static position {pos} "
                            "— statics key the compile cache by "
                            "hash/equality; pass a tuple or hashable "
                            "config")

    def _free_names(self, func: ast.AST) -> Set[str]:
        bound = {a.arg for a in func.args.args + func.args.kwonlyargs}
        if func.args.vararg:
            bound.add(func.args.vararg.arg)
        if func.args.kwarg:
            bound.add(func.args.kwarg.arg)
        loaded: Set[str] = set()
        body = func.body if isinstance(func.body, list) else [func.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name):
                    if isinstance(node.ctx, ast.Store):
                        bound.add(node.id)
                    elif isinstance(node.ctx, ast.Load):
                        loaded.add(node.id)
        return loaded - bound

    # -- RT105 --------------------------------------------------------------
    def _rt105_donated_reuse(self) -> None:
        # handle chain -> (donated positions, jit-call node id), for jit
        # sites assigned to a name/attr with a literal donate_argnums.
        # The call id lets the per-function scan notice the handle name
        # being REBOUND to something else (a non-donating jit in another
        # branch) and stop treating its calls as donations.
        handles: Dict[Tuple[str, ...], Tuple[Tuple[int, ...], int]] = {}
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call) \
                    and _jit_construction(node.value):
                donate = None
                for kw in node.value.keywords:
                    if kw.arg == "donate_argnums":
                        donate = _literal_int_tuple(kw.value)
                if not donate:
                    continue
                tch = _chain(node.targets[0])
                if tch:
                    handles[tuple(tch)] = (donate, id(node.value))
        if not handles:
            return
        for scope in self.collector.scopes.values():
            node = scope.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            self._scan_donated_in(node, scope.qualname, handles)

    def _scan_donated_in(self, func: ast.FunctionDef, qual: str,
                         handles: Dict[Tuple[str, ...],
                                       Tuple[Tuple[int, ...], int]]
                         ) -> None:
        consumed: Dict[Tuple[str, ...], int] = {}   # chain -> donate line
        dead: Set[Tuple[str, ...]] = set()          # handles rebound here
        # statement-ordered scan over the flattened body (nested blocks
        # in source order; nested defs excluded). _own_nodes keeps each
        # statement's expressions from being scanned again under its
        # enclosing compound statement (with/if/try).
        for stmt in self._ordered_stmts(func):
            # phase 1: reads of already-donated chains in THIS statement
            for node in _own_nodes(stmt):
                if not consumed:
                    break
                if isinstance(node, (ast.Name, ast.Attribute)) \
                        and isinstance(getattr(node, "ctx", None),
                                       ast.Load):
                    ch = _chain(node)
                    if ch and tuple(ch) in consumed:
                        self.add_finding(
                            "RT105", "donated-reuse", node.lineno, qual,
                            f"{'.'.join(ch)} read after being donated at "
                            f"line {consumed[tuple(ch)]} — the buffer may "
                            "already be aliased into the jit output "
                            "(use-after-donate)")
                        del consumed[tuple(ch)]
            # phase 2: new donations from calls in this statement (a
            # same-statement assignment back to the chain revokes it)
            assigned: Set[Tuple[str, ...]] = set()
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    elts = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for e in elts:
                        ech = _chain(e)
                        if ech:
                            assigned.add(tuple(ech))
                # the handle name rebound to anything other than its
                # registered donating jit: its later calls don't donate
                for ch_t in assigned:
                    entry = handles.get(ch_t)
                    if entry is None:
                        continue
                    if id(stmt.value) != entry[1]:
                        dead.add(ch_t)
                    else:
                        dead.discard(ch_t)   # the registering assign
            for node in _own_nodes(stmt):
                if isinstance(node, ast.Call):
                    fch = _chain(node.func)
                    key = tuple(fch) if fch else None
                    if key in handles and key not in dead:
                        for pos in handles[key][0]:
                            if pos < len(node.args):
                                ach = _chain(node.args[pos])
                                if ach and tuple(ach) not in assigned:
                                    consumed[tuple(ach)] = node.lineno
            for ch_t in assigned:
                consumed.pop(ch_t, None)

    @staticmethod
    def _ordered_stmts(func: ast.FunctionDef) -> List[ast.stmt]:
        """Statements of ``func`` in source order, flattened through
        nested blocks but NOT into nested function defs."""
        out: List[ast.stmt] = []

        def rec(body: List[ast.stmt]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                out.append(stmt)
                for name in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, name, None)
                    if sub:
                        rec(sub)
                for handler in getattr(stmt, "handlers", []) or []:
                    rec(handler.body)

        rec(func.body)
        return out

    # -- RT106 --------------------------------------------------------------
    # construction-time methods by contract: the engine shape builds its
    # (possibly sharded/pjit) programs in __init__ and may rebuild them
    # in warmup — the decode-mesh builders are sanctioned there, and
    # ONLY there
    _RT106_CONSTRUCTION = frozenset({"__init__", "warmup"})

    def _module_jit_builders(self) -> Set[str]:
        """Module-level functions that (transitively) construct a
        jit/pjit IN THEIR BODY — the sharded-program-builder shape. A
        call to one from the iteration path is the same per-call
        recompile as an inline ``jax.jit``, just hidden behind a
        helper. Decorators are excluded on purpose: a
        ``@jax.jit``/``@partial(jax.jit, ...)``-decorated function IS a
        pre-built cached handle, and calling it is sanctioned dispatch,
        not construction. Memoized (``lint_modules`` reads it for the
        cross-module map before ``run()`` needs it again)."""
        if self._builders is not None:
            return self._builders
        builders: Set[str] = set()
        calls: Dict[str, Set[str]] = {}
        for name, fn in self.mod.functions.items():
            # decorators are excluded from BOTH scans: the jit check
            # (a decorated function is a pre-built handle) and the
            # closure map (a `@my_jit_factory(...)` decoration must not
            # make the wrapped function read as calling a builder)
            deco_nodes = {id(n) for dec in fn.decorator_list
                          for n in ast.walk(dec)}
            if any(isinstance(n, ast.Call) and id(n) not in deco_nodes
                   and _jit_construction(n) for n in ast.walk(fn)):
                builders.add(name)
            calls[name] = {c[0] for n in ast.walk(fn)
                           if isinstance(n, ast.Call)
                           and id(n) not in deco_nodes
                           for c in (_chain(n.func),)
                           if c and len(c) == 1}
        changed = True
        while changed:
            changed = False
            for name, callees in calls.items():
                if name not in builders and callees & builders:
                    builders.add(name)
                    changed = True
        self._builders = builders
        return builders

    def _rt106_loop_reachable_jit(
            self, extern_builders: Set[str] = frozenset()) -> None:
        builders = self._module_jit_builders() | set(extern_builders)
        for cls_name, cls_node in self.mod.classes.items():
            methods = {n.name: n for n in cls_node.body
                       if isinstance(n, ast.FunctionDef)}
            if "_loop" not in methods:
                continue
            reachable: Set[str] = set()
            queue = ["_loop"]
            while queue:
                mname = queue.pop()
                if mname in reachable or mname not in methods:
                    continue
                reachable.add(mname)
                for node in ast.walk(methods[mname]):
                    if isinstance(node, ast.Call):
                        ch = _chain(node.func)
                        if ch and len(ch) == 2 and ch[0] == "self":
                            queue.append(ch[1])
            reachable -= self._RT106_CONSTRUCTION
            for mname in sorted(reachable):
                for node in ast.walk(methods[mname]):
                    if not isinstance(node, ast.Call):
                        continue
                    if _jit_construction(node):
                        self.add_finding(
                            "RT106", "jit-in-iteration-path", node.lineno,
                            f"{cls_name}.{mname}",
                            "jax.jit constructed in a method reachable "
                            "from the engine iteration path (_loop) — "
                            "the one-trace invariant allows jit "
                            "construction only in __init__/warmup")
                        continue
                    ch = _chain(node.func)
                    if ch and len(ch) == 1 and ch[0] in builders:
                        self.add_finding(
                            "RT106", "builder-in-iteration-path",
                            node.lineno, f"{cls_name}.{mname}",
                            f"{ch[0]}() — a module-level jit/pjit "
                            "builder — called from the engine iteration "
                            "path (_loop): every call constructs fresh "
                            "programs with cold compile caches; build "
                            "in __init__/warmup and dispatch the "
                            "handles")


def _all_imported_names(mod: Module) -> Dict[str, Tuple[str, str]]:
    """Local name -> (source module, attr) for EVERY ``from X import Y``
    in the module — including function-level imports (the engine's
    construction-time import idiom), which ``parse_module`` does not
    record. Relative levels resolve against the module's package."""
    pkg_parts = mod.name.split(".")[:-1]
    out: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        base = node.module or ""
        if node.level:
            up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            base = ".".join(up + ([base] if base else []))
        for alias in node.names:
            out[alias.asname or alias.name] = (base, alias.name)
    return out


def lint_module(mod: Module) -> List[Finding]:
    return RetraceLint(mod).run()


def lint_modules(modules: Sequence[Module]) -> List[Finding]:
    """Whole-tree pass: RT106's builder detection links ACROSS modules
    here — pass 1 collects every module's jit/pjit-constructing
    module-level functions, pass 2 lints each module with the imported
    names that resolve to another module's builders marked as builders
    too (so `from models.transformer import make_sharded_decode_programs`
    called from an iteration path fires exactly like a local one)."""
    linters = [RetraceLint(mod) for mod in modules]
    builders_by_module = {lt.mod.name: lt._module_jit_builders()
                          for lt in linters}
    out: List[Finding] = []
    for lt in linters:
        extern = {
            local for local, (src, attr)
            in _all_imported_names(lt.mod).items()
            if attr in builders_by_module.get(src, ())
        }
        out.extend(lt.run(extern_builders=extern))
    return out
