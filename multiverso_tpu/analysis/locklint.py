"""Lock-discipline checker: ``with <lock>`` regions -> acquisition graph.

An AST pass over the package that (1) finds every lock a class or
module creates (``threading.Lock/RLock/Condition``, the
:mod:`~multiverso_tpu.analysis.lockwatch` factories), (2) extracts every
``with <lock>:`` region, and (3) walks each region's body —
*through* resolvable calls (``self.m()``, ``Class.m()``, typed-attribute
methods and properties, module functions) — collecting what happens
while the lock is held. Rules:

* **LK201 lock-order-cycle** — the package-wide inter-lock acquisition
  graph (edge ``A -> B`` when B is acquired while A is held, at any
  call depth) contains a cycle: two code paths disagree about lock
  order, which is a deadlock waiting for the right interleaving.
  Lock identity is name-level (``module.Class.attr``), so the check
  spans instances; name-level self-edges are skipped (they cannot
  distinguish an instance hierarchy from an inversion).
* **LK202 callback-under-lock** — foreign code invoked while a lock is
  held: an ``on_*``/callback-shaped attribute, a parameter (or a
  parameter-sourced attribute — the constructor-injected ``fn``), or a
  Future's ``set_result``/``set_exception``/``add_done_callback``
  (done-callbacks run inline). The callee can block, re-enter, or take
  its own locks in an order the holder never audited — the PR 6
  reporter-detach-under-registry-lock bug, generalized.
* **LK203 blocking-under-lock** — a call that can park the thread while
  it holds the lock: ``join``, Event/foreign-Condition ``wait``,
  ``Queue.get``, ``Future.result``, ``sleep``, socket/subprocess, file
  I/O, explicit ``acquire``, and JAX work (``jnp.*`` dispatch,
  ``block_until_ready``, ``device_put``, jitted handles — a dispatch
  can hide a multi-second compile). Waiting on the Condition you hold
  is the sanctioned pattern and is exempt.
* **LK204 lock-fanout-under-lock** — a call made under a lock that
  transitively acquires ``FANOUT_THRESHOLD`` (3) or more *other* locks:
  a registry-wide fan-out (``Dashboard.snapshot``/``display``)
  serializes every instrument behind the caller's private lock.

Heuristic resolution is deliberately conservative: unresolvable calls
are checked only against the blocking/callback name patterns above, and
unresolvable ``with`` subjects are ignored. Findings that are by-design
(e.g. a snapshot copy dispatched under the table lock — the torn-read
contract) belong in ``tools/lint_baseline.txt`` with a justification.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import Finding, Module

FANOUT_THRESHOLD = 3

# attribute names whose zero/timeout-arg call parks the thread
_BLOCKING_ATTRS = {
    "join": "join", "result": "future-result", "get": "queue-get",
    "recv": "socket", "recv_into": "socket", "sendall": "socket",
    "send": "socket", "connect": "socket", "accept": "socket",
    "write": "io", "flush": "io", "fsync": "io",
}
_OS_BLOCKING = {"makedirs", "rename", "replace", "remove", "unlink",
                "fsync", "system"}
_SUBPROCESS = {"run", "call", "check_call", "check_output", "Popen"}
_CALLBACK_ATTR_NAMES = {"callback", "_callback", "emit", "_emit", "hook",
                        "_hook"}
_FUTURE_CALLBACK_ATTRS = {"set_result", "set_exception",
                          "add_done_callback"}


def _chain(node: ast.AST) -> Optional[List[str]]:
    """Attribute/Name chain as names, e.g. ``self._pool.alloc`` ->
    ``['self', '_pool', 'alloc']``; None for non-name bases."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _is_ctor(call: ast.AST, owner: str, names: Set[str]) -> bool:
    """``<owner>.<name>(...)`` e.g. threading.Lock()."""
    if not isinstance(call, ast.Call):
        return False
    ch = _chain(call.func)
    return bool(ch and len(ch) == 2 and ch[0] == owner and ch[1] in names)


@dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    bases: List[Tuple[str, str]] = field(default_factory=list)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    properties: Set[str] = field(default_factory=set)
    classmethods: Set[str] = field(default_factory=set)
    lock_attrs: Dict[str, str] = field(default_factory=dict)   # attr -> id
    cv_alias: Dict[str, str] = field(default_factory=dict)     # cv -> lock id
    event_attrs: Set[str] = field(default_factory=set)
    queue_attrs: Set[str] = field(default_factory=set)
    jit_attrs: Set[str] = field(default_factory=set)
    callback_attrs: Set[str] = field(default_factory=set)      # param-sourced
    attr_types: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module, self.name)


@dataclass
class Summary:
    """What one function does, transitively through resolved calls."""

    acquires: Set[str] = field(default_factory=set)
    # (slug, detail) pairs; 'wait-on' entries carry the cv's lock id in
    # detail so callers holding ONLY that lock stay exempt
    blocking: Set[Tuple[str, str]] = field(default_factory=set)
    waits_on: Set[str] = field(default_factory=set)
    callbacks: Set[Tuple[str, str]] = field(default_factory=set)


class PackageIndex:
    """Cross-module symbol table the analyzer resolves against."""

    def __init__(self, modules: Sequence[Module]) -> None:
        self.modules: Dict[str, Module] = {m.name: m for m in modules}
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self.module_locks: Dict[Tuple[str, str], str] = {}   # (mod, var) -> id
        self.module_var_types: Dict[Tuple[str, str],
                                    Tuple[str, str]] = {}
        for m in modules:
            self._index_module(m)

    # -- build --------------------------------------------------------------
    def _lock_rhs(self, value: ast.AST) -> Optional[str]:
        """'lock' | 'rlock' | 'condition' | 'event' | 'queue' | 'jit'
        for recognized constructor calls, else None."""
        if not isinstance(value, ast.Call):
            return None
        if _is_ctor(value, "threading", {"Lock"}):
            return "lock"
        if _is_ctor(value, "threading", {"RLock"}):
            return "rlock"
        if _is_ctor(value, "threading", {"Condition"}):
            return "condition"
        if _is_ctor(value, "threading", {"Event"}):
            return "event"
        if _is_ctor(value, "lockwatch", {"lock"}):
            return "lock"
        if _is_ctor(value, "lockwatch", {"rlock"}):
            return "rlock"
        if _is_ctor(value, "lockwatch", {"condition"}):
            return "condition"
        if _is_ctor(value, "queue", {"Queue", "SimpleQueue", "LifoQueue",
                                     "PriorityQueue"}):
            return "queue"
        if _is_ctor(value, "jax", {"jit", "pjit"}):
            return "jit"
        return None

    def _ann_type(self, ann: Optional[ast.AST], mod: Module
                  ) -> Optional[Tuple[str, str]]:
        """Resolve ``BlockPool`` / ``Optional[BlockPool]`` annotations."""
        if ann is None:
            return None
        if isinstance(ann, ast.Subscript):
            return self._ann_type(ann.slice, mod)
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
            return self._ann_type(ann, mod)
        ch = _chain(ann)
        if not ch:
            return None
        return self.resolve_class(ch[-1], mod)

    def resolve_class(self, name: str, mod: Module
                      ) -> Optional[Tuple[str, str]]:
        if (mod.name, name) in self.classes:
            return (mod.name, name)
        imp = mod.imports.get(name)
        if imp:
            target_mod, attr = imp
            if attr and (target_mod, attr) in self.classes:
                return (target_mod, attr)
            if attr is None and name in self.modules:
                return None
        return None

    def _value_type(self, value: ast.AST, mod: Module
                    ) -> Optional[Tuple[str, str]]:
        """Type of ``ClassName(...)`` / ``ClassName.of(...)`` RHS."""
        if not isinstance(value, ast.Call):
            return None
        ch = _chain(value.func)
        if not ch:
            return None
        if len(ch) == 1:
            return self.resolve_class(ch[0], mod)
        if len(ch) == 2:
            # Class.of(...) style alternate constructors
            cls = self.resolve_class(ch[0], mod)
            if cls and ch[1] in self.classes[cls].classmethods:
                return cls
            # module.Class(...)
            imp = mod.imports.get(ch[0])
            if imp and imp[1] is None:
                target = imp[0]
                if (target, ch[1]) in self.classes:
                    return (target, ch[1])
        return None

    def _index_module(self, mod: Module) -> None:
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                var = stmt.targets[0].id
                kind = self._lock_rhs(stmt.value)
                if kind in ("lock", "rlock", "condition"):
                    self.module_locks[(mod.name, var)] = \
                        f"{mod.name}.{var}"
                else:
                    t = self._value_type(stmt.value, mod)
                    if t:
                        self.module_var_types[(mod.name, var)] = t
        for cls_name, cls_node in mod.classes.items():
            info = ClassInfo(mod.name, cls_name, cls_node)
            for base in cls_node.bases:
                ch = _chain(base)
                if ch:
                    resolved = self.resolve_class(ch[-1], mod)
                    if resolved:
                        info.bases.append(resolved)
            for stmt in cls_node.body:
                if isinstance(stmt, ast.FunctionDef):
                    info.methods[stmt.name] = stmt
                    for dec in stmt.decorator_list:
                        dch = _chain(dec)
                        if dch == ["property"]:
                            info.properties.add(stmt.name)
                        elif dch == ["classmethod"]:
                            info.classmethods.add(stmt.name)
                elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    kind = self._lock_rhs(stmt.value)
                    attr = stmt.targets[0].id
                    if kind in ("lock", "rlock", "condition"):
                        info.lock_attrs[attr] = \
                            f"{mod.name}.{cls_name}.{attr}"
                    elif kind == "event":
                        info.event_attrs.add(attr)
            self.classes[info.key] = info
        # second pass: self.<attr> assignments inside methods need the
        # class table complete for attr typing
        for cls_name in mod.classes:
            info = self.classes[(mod.name, cls_name)]
            for meth in info.methods.values():
                self._index_method_attrs(info, meth, mod)

    def _index_method_attrs(self, info: ClassInfo, meth: ast.FunctionDef,
                            mod: Module) -> None:
        params = {a.arg for a in meth.args.args + meth.args.kwonlyargs
                  if a.arg not in ("self", "cls")}
        for node in ast.walk(meth):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            ann = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value, ann = [node.target], node.value, \
                    node.annotation
            for tgt in targets:
                ch = _chain(tgt)
                if not ch or len(ch) != 2 or ch[0] != "self":
                    continue
                attr = ch[1]
                mid = f"{mod.name}.{info.name}.{attr}"
                kind = self._lock_rhs(value) if value is not None else None
                if kind in ("lock", "rlock"):
                    info.lock_attrs[attr] = mid
                elif kind == "condition":
                    arg_ch = (_chain(value.args[0])
                              if isinstance(value, ast.Call) and value.args
                              else None)
                    if (arg_ch and len(arg_ch) == 2 and arg_ch[0] == "self"
                            and arg_ch[1] in info.lock_attrs):
                        info.cv_alias[attr] = info.lock_attrs[arg_ch[1]]
                    else:
                        info.lock_attrs[attr] = mid
                elif kind == "event":
                    info.event_attrs.add(attr)
                elif kind == "queue":
                    info.queue_attrs.add(attr)
                elif kind == "jit":
                    info.jit_attrs.add(attr)
                elif (isinstance(value, ast.Name)
                      and value.id in params):
                    info.callback_attrs.add(attr)
                elif (isinstance(value, ast.Attribute)
                      and value.attr.startswith("on_")):
                    info.callback_attrs.add(attr)
                t = self._ann_type(ann, mod) or (
                    self._value_type(value, mod)
                    if value is not None else None)
                if t:
                    info.attr_types[attr] = t

    # -- lookups ------------------------------------------------------------
    def class_attr(self, key: Tuple[str, str], table: str, attr: str):
        """Walk a class and its bases for ``attr`` in ``table``."""
        seen = set()
        stack = [key]
        while stack:
            k = stack.pop()
            if k in seen or k not in self.classes:
                continue
            seen.add(k)
            info = self.classes[k]
            val = getattr(info, table).get(attr) \
                if isinstance(getattr(info, table), dict) \
                else (attr if attr in getattr(info, table) else None)
            if val is not None:
                return val
            stack.extend(info.bases)
        return None

    def find_method(self, key: Tuple[str, str], name: str
                    ) -> Optional[Tuple[ClassInfo, ast.FunctionDef]]:
        seen = set()
        stack = [key]
        while stack:
            k = stack.pop()
            if k in seen or k not in self.classes:
                continue
            seen.add(k)
            info = self.classes[k]
            if name in info.methods:
                return info, info.methods[name]
            stack.extend(info.bases)
        return None


class _FunctionAnalyzer(ast.NodeVisitor):
    """Walks one function body with a held-lock stack, populating the
    function's :class:`Summary` and emitting findings for work done
    while locks are held."""

    def __init__(self, linter: "LockLint", mod: Module,
                 cls: Optional[ClassInfo], func: ast.FunctionDef,
                 qualname: str) -> None:
        self.linter = linter
        self.mod = mod
        self.cls = cls
        self.func = func
        self.qualname = qualname
        self.summary = Summary()
        self.held: List[str] = []
        self.local_types: Dict[str, Tuple[str, str]] = {}
        self.local_callbacks: Set[str] = set()
        self.params = {a.arg for a in func.args.args + func.args.kwonlyargs
                       + ([func.args.vararg] if func.args.vararg else [])
                       + ([func.args.kwarg] if func.args.kwarg else [])
                       if a is not None and a.arg not in ("self", "cls")}
        self.local_funcs: Dict[str, ast.FunctionDef] = {}
        self._emitted: Set[Tuple[str, str]] = set()

    # -- helpers ------------------------------------------------------------
    def _finding(self, rule: str, slug: str, line: int, msg: str) -> None:
        if (rule, slug) in self._emitted:
            return
        self._emitted.add((rule, slug))
        self.linter.findings.append(Finding(
            rule=rule, path=self.mod.path, line=line,
            qualname=self.qualname, slug=slug, message=msg))

    def _resolve_lock(self, node: ast.AST) -> Optional[str]:
        ch = _chain(node)
        if not ch:
            return None
        if len(ch) == 1:
            return self.linter.index.module_locks.get((self.mod.name, ch[0]))
        if len(ch) == 2:
            base, attr = ch
            if base in ("self", "cls") and self.cls is not None:
                key = self.cls.key
            else:
                key = self.linter.index.resolve_class(base, self.mod)
                if key is None:
                    return None
            cv = self.linter.index.class_attr(key, "cv_alias", attr)
            if cv:
                return cv
            return self.linter.index.class_attr(key, "lock_attrs", attr)
        return None

    def _receiver_type(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        ch = _chain(node)
        if not ch:
            return None
        if len(ch) == 1:
            name = ch[0]
            if name in self.local_types:
                return self.local_types[name]
            t = self.linter.index.module_var_types.get(
                (self.mod.name, name))
            if t:
                return t
            return None
        if len(ch) == 2 and ch[0] in ("self", "cls") \
                and self.cls is not None:
            return self.linter.index.class_attr(
                self.cls.key, "attr_types", ch[1])
        return None

    def _apply_summary(self, summary: Summary, line: int,
                       via: str) -> None:
        """Fold a callee summary into this function (and, when locks are
        held here, into findings/edges)."""
        self.summary.acquires |= summary.acquires
        self.summary.blocking |= summary.blocking
        self.summary.callbacks |= summary.callbacks
        self.summary.waits_on |= summary.waits_on
        if not self.held:
            return
        heldset = set(self.held)
        for lid in summary.acquires:
            for h in self.held:
                if h != lid:
                    self.linter.add_edge(h, lid, self.mod.path, line)
        for slug, detail in sorted(summary.blocking):
            self._finding("LK203", slug, line,
                          f"blocking call ({detail}) via {via}() while "
                          f"holding {self.held[-1]}")
        for cvlock in sorted(summary.waits_on):
            if heldset - {cvlock}:
                self._finding(
                    "LK203", "wait", line,
                    f"condition wait on {cvlock} via {via}() while also "
                    f"holding {sorted(heldset - {cvlock})}")
        for slug, detail in sorted(summary.callbacks):
            self._finding("LK202", slug, line,
                          f"callback invocation ({detail}) via {via}() "
                          f"while holding {self.held[-1]}")
        others = {lid for lid in summary.acquires if lid not in heldset}
        if len(others) >= FANOUT_THRESHOLD:
            self._finding(
                "LK204", "fanout", line,
                f"call to {via}() acquires {len(others)} other locks "
                f"({sorted(others)[:4]}...) while holding "
                f"{self.held[-1]} — a registry fan-out serialized behind "
                f"a private lock")

    def _blocking(self, slug: str, detail: str, line: int) -> None:
        self.summary.blocking.add((slug, detail))
        if self.held:
            self._finding("LK203", slug, line,
                          f"blocking call ({detail}) while holding "
                          f"{self.held[-1]}")

    def _callback(self, slug: str, detail: str, line: int) -> None:
        self.summary.callbacks.add((slug, detail))
        if self.held:
            self._finding("LK202", slug, line,
                          f"callback invocation ({detail}) while holding "
                          f"{self.held[-1]}")

    # -- visitors -----------------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.func:
            self.generic_visit(node)
        else:
            self.local_funcs[node.name] = node    # body analyzed on call

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return                                    # deferred code

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lid = self._resolve_lock(item.context_expr)
            if lid is not None:
                if self.held:
                    self.summary.acquires.add(lid)
                    for h in self.held:
                        if h != lid:
                            self.linter.add_edge(h, lid, self.mod.path,
                                                 node.lineno)
                else:
                    self.summary.acquires.add(lid)
                self.held.append(lid)
                pushed += 1
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            t = None
            if isinstance(node.value, (ast.Attribute, ast.Name)):
                t = self._receiver_type(node.value)
                ch = _chain(node.value)
                if (ch and len(ch) == 2 and ch[0] in ("self", "cls")
                        and self.cls is not None):
                    if (ch[1].startswith("on_")
                            or self.linter.index.class_attr(
                                self.cls.key, "callback_attrs", ch[1])):
                        self.local_callbacks.add(name)
            elif isinstance(node.value, ast.Call):
                t = self.linter.index._value_type(node.value, self.mod)
            if t:
                self.local_types[name] = t
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # property loads acquire locks too (BlockPool.n_free)
        if isinstance(node.ctx, ast.Load):
            t = self._receiver_type(node.value)
            if t is not None:
                info = self.linter.index.classes.get(t)
                if info and node.attr in info.properties:
                    found = self.linter.index.find_method(t, node.attr)
                    if found:
                        summ = self.linter.summarize(
                            found[0], found[1],
                            f"{found[0].name}.{node.attr}")
                        self._apply_summary(summ, node.lineno,
                                            f"{t[1]}.{node.attr}")
        self.generic_visit(node)

    def _resolve_call(self, node: ast.Call
                      ) -> Optional[Tuple[ClassInfo, ast.FunctionDef, str]]:
        ch = _chain(node.func)
        idx = self.linter.index
        if not ch:
            return None
        if len(ch) == 1:
            name = ch[0]
            if name in self.local_funcs:
                return (self.cls, self.local_funcs[name],
                        f"{self.qualname}.{name}")
            if name in self.mod.functions:
                return (None, self.mod.functions[name], name)
            imp = self.mod.imports.get(name)
            if imp and imp[1] is not None:
                target = idx.modules.get(imp[0])
                if target and imp[1] in target.functions:
                    return (None, target.functions[imp[1]],
                            f"{imp[0]}.{imp[1]}")
            cls_key = idx.resolve_class(name, self.mod)
            if cls_key:
                found = idx.find_method(cls_key, "__init__")
                if found:
                    return (found[0], found[1], f"{cls_key[1]}.__init__")
            return None
        if len(ch) == 2:
            base, meth = ch
            if base in ("self", "cls") and self.cls is not None:
                found = idx.find_method(self.cls.key, meth)
                if found:
                    return (found[0], found[1],
                            f"{self.cls.name}.{meth}")
                return None
            cls_key = idx.resolve_class(base, self.mod)
            if cls_key:
                found = idx.find_method(cls_key, meth)
                if found:
                    return (found[0], found[1], f"{cls_key[1]}.{meth}")
                return None
            imp = self.mod.imports.get(base)
            if imp and imp[1] is None:
                target = idx.modules.get(imp[0])
                if target and meth in target.functions:
                    return (None, target.functions[meth],
                            f"{imp[0]}.{meth}")
            t = self._receiver_type(ast.Name(id=base, ctx=ast.Load()))
            if t:
                found = idx.find_method(t, meth)
                if found:
                    return (found[0], found[1], f"{t[1]}.{meth}")
            return None
        if len(ch) == 3 and ch[0] in ("self", "cls") \
                and self.cls is not None:
            t = self.linter.index.class_attr(
                self.cls.key, "attr_types", ch[1])
            if t:
                found = idx.find_method(t, ch[2])
                if found:
                    return (found[0], found[1], f"{t[1]}.{ch[2]}")
        return None

    def visit_Call(self, node: ast.Call) -> None:
        line = node.lineno
        ch = _chain(node.func)
        handled = False
        if ch:
            handled = self._check_call_chain(node, ch, line)
        if not handled:
            resolved = self._resolve_call(node)
            if resolved is not None:
                cls, fn, qual = resolved
                summ = self.linter.summarize(cls, fn, qual)
                self._apply_summary(summ, line, qual)
            elif ch:
                self._heuristic_call(node, ch, line)
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    def _check_call_chain(self, node: ast.Call, ch: List[str],
                          line: int) -> bool:
        """Pattern checks that preempt resolution. Returns True when the
        call is fully handled."""
        idx = self.linter.index
        last = ch[-1]
        # waiting on a condition variable
        if last in ("wait", "wait_for") and len(ch) >= 2:
            recv = ch[:-1]
            lid = self._resolve_lock(
                ast.parse(".".join(recv), mode="eval").body
                if all(p.isidentifier() for p in recv) else ast.Name(
                    id="?", ctx=ast.Load()))
            if lid is not None:
                # cv.wait: releases its own lock; blocking only if OTHER
                # locks are held across the sleep
                self.summary.waits_on.add(lid)
                others = set(self.held) - {lid}
                if others:
                    self._finding(
                        "LK203", "wait", line,
                        f"condition wait on {lid} while also holding "
                        f"{sorted(others)}")
                return True
            # event / foreign wait — flagged alike (any .wait() while a
            # lock is held blocks the holder); a recognized Event attr
            # gets named in the detail so the report reads as intent
            is_event = (len(ch) == 2 and ch[0] in ("self", "cls")
                        and self.cls is not None
                        and idx.class_attr(self.cls.key, "event_attrs",
                                           ch[1]))
            detail = ".".join(ch) + (" (threading.Event)" if is_event else "")
            self._blocking("wait", detail, line)
            return True
        if last == "sleep" and len(ch) == 2 and ch[0] == "time":
            self._blocking("sleep", "time.sleep", line)
            return True
        if last == "acquire" and len(ch) >= 2:
            recv_lock = self._resolve_lock(node.func.value)
            if recv_lock is not None:
                self.summary.acquires.add(recv_lock)
                for h in self.held:
                    if h != recv_lock:
                        self.linter.add_edge(h, recv_lock, self.mod.path,
                                             line)
                if self.held:
                    self._finding(
                        "LK203", "acquire", line,
                        f"explicit acquire of {recv_lock} while holding "
                        f"{self.held[-1]}")
                return True
        if ch[0] == "os" and last in _OS_BLOCKING:
            self._blocking("io", ".".join(ch), line)
            return True
        if ch[0] == "subprocess" and last in _SUBPROCESS:
            self._blocking("subprocess", ".".join(ch), line)
            return True
        if ch == ["open"]:
            self._blocking("io", "open", line)
            return True
        if ch[0] == "jax" and last in ("block_until_ready",):
            self._blocking("jax-sync", "jax.block_until_ready", line)
            return True
        if ch[0] == "jax" and last in ("device_put", "device_get"):
            self._blocking("jax-dispatch", ".".join(ch), line)
            return True
        if ch[0] == "jax" and len(ch) == 3 and ch[1] == "tree" \
                and last == "map":
            self._blocking("jax-dispatch", "jax.tree.map", line)
            return True
        if ch[0] in ("jnp", "lax"):
            self._blocking("jax-dispatch", ".".join(ch), line)
            return True
        # jitted-handle dispatch: self._step(...) where _step = jax.jit(..)
        if len(ch) == 2 and ch[0] in ("self", "cls") \
                and self.cls is not None \
                and idx.class_attr(self.cls.key, "jit_attrs", ch[1]):
            self._blocking("jax-dispatch",
                           f"jitted handle self.{ch[1]}", line)
            return True
        # callbacks
        if last in _FUTURE_CALLBACK_ATTRS and len(ch) >= 2:
            self._callback("future-callbacks", ".".join(ch), line)
            return True
        if last.startswith("on_") or last in _CALLBACK_ATTR_NAMES:
            self._callback("callback", ".".join(ch), line)
            return True
        if len(ch) == 1 and (ch[0] in self.params
                             or ch[0] in self.local_callbacks):
            self._callback("param-call", f"parameter {ch[0]}()", line)
            return True
        if len(ch) == 2 and ch[0] in ("self", "cls") \
                and self.cls is not None \
                and idx.class_attr(self.cls.key, "callback_attrs", ch[1]):
            self._callback("param-call",
                           f"constructor-injected self.{ch[1]}()", line)
            return True
        return False

    def _heuristic_call(self, node: ast.Call, ch: List[str],
                        line: int) -> None:
        """Unresolvable callee: name-pattern blocking checks only."""
        last = ch[-1]
        slug = _BLOCKING_ATTRS.get(last)
        if slug is None:
            return
        if last == "join" and (node.args or len(ch) < 2):
            return                     # str.join / os.path.join
        if last == "result" and node.args:
            return
        if last == "get":
            # `.get` is hopelessly overloaded (dict.get, Gauge/Counter
            # .get, Queue.get): flag only a receiver that is a KNOWN
            # queue attribute of this class or whose name says queue
            # (`self._queue.get()`, `work_q.get()`) — anything else is
            # overwhelmingly a non-blocking read
            is_queue = (len(ch) >= 2 and ch[0] in ("self", "cls")
                        and self.cls is not None
                        and self.linter.index.class_attr(
                            self.cls.key, "queue_attrs", ch[-2]))
            recv = ch[-2].lower() if len(ch) >= 2 else ""
            queueish = ("queue" in recv or recv == "q"
                        or recv.endswith("_q"))
            if not is_queue and not queueish:
                return
        if last in ("write", "flush", "fsync") and len(ch) < 2:
            return
        self._blocking(slug, ".".join(ch), line)

    def run(self) -> Summary:
        for stmt in self.func.body:
            self.visit(stmt)
        return self.summary


class LockLint:
    """Package-wide lock-discipline analysis."""

    def __init__(self, modules: Sequence[Module]) -> None:
        self.index = PackageIndex(modules)
        self.modules = list(modules)
        self.findings: List[Finding] = []
        self._summaries: Dict[int, Summary] = {}
        self._in_progress: Set[int] = set()
        # edge -> (path, line) of first sighting
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(self, held: str, acquired: str, path: str,
                 line: int) -> None:
        if held == acquired:
            return
        self.edges.setdefault((held, acquired), (path, line))

    def summarize(self, cls: Optional[ClassInfo], fn: ast.FunctionDef,
                  qual: str) -> Summary:
        key = id(fn)
        if key in self._summaries:
            return self._summaries[key]
        if key in self._in_progress:           # recursion: fixpoint-lite
            return Summary()
        self._in_progress.add(key)
        mod = None
        if cls is not None:
            mod = self.index.modules.get(cls.module)
        if mod is None:
            mod = self._module_of(fn)
        if mod is None:                        # pragma: no cover
            self._in_progress.discard(key)
            return Summary()
        analyzer = _FunctionAnalyzer(self, mod, cls, fn, qual)
        summary = analyzer.run()
        self._in_progress.discard(key)
        self._summaries[key] = summary
        return summary

    def _module_of(self, fn: ast.FunctionDef) -> Optional[Module]:
        for m in self.modules:
            for node in ast.walk(m.tree):
                if node is fn:
                    return m
        return None

    # -- entry --------------------------------------------------------------
    def run(self) -> List[Finding]:
        for mod in self.modules:
            for fname, fnode in mod.functions.items():
                self._analyze_entry(mod, None, fnode, fname)
            for cname, cnode in mod.classes.items():
                info = self.index.classes[(mod.name, cname)]
                for mname, mnode in info.methods.items():
                    self._analyze_entry(mod, info, mnode,
                                        f"{cname}.{mname}")
        self._cycle_findings()
        return self.findings

    def _analyze_entry(self, mod: Module, cls: Optional[ClassInfo],
                       fn: ast.FunctionDef, qual: str) -> None:
        key = id(fn)
        if key in self._summaries:
            return
        self._in_progress.add(key)
        analyzer = _FunctionAnalyzer(self, mod, cls, fn, qual)
        summary = analyzer.run()
        self._in_progress.discard(key)
        self._summaries[key] = summary

    def _cycle_findings(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        sccs = _tarjan(adj)
        for scc in sccs:
            if len(scc) < 2:
                continue
            nodes = sorted(scc)
            example = [(a, b) for (a, b) in sorted(self.edges)
                       if a in scc and b in scc]
            path, line = self.edges[example[0]]
            short = "+".join(n.rsplit(".", 2)[-2] + "." + n.rsplit(".", 1)[-1]
                             for n in nodes)
            self.findings.append(Finding(
                rule="LK201", path=path, line=line,
                qualname="<lock-graph>", slug=short,
                message=(f"lock-order cycle among {nodes}: edges "
                         f"{example[:6]} — two paths disagree about "
                         f"acquisition order (latent deadlock)")))

    def graph_report(self) -> str:
        lines = ["inter-lock acquisition graph (held -> acquired):"]
        for (a, b), (path, line) in sorted(self.edges.items()):
            lines.append(f"  {a} -> {b}   (first: {path}:{line})")
        return "\n".join(lines)


def _tarjan(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Strongly connected components (iterative Tarjan)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]
    nodes = set(adj) | {b for vs in adj.values() for b in vs}

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return sccs


def lint_modules(modules: Sequence[Module]) -> Tuple[List[Finding],
                                                     LockLint]:
    linter = LockLint(modules)
    findings = linter.run()
    return findings, linter
