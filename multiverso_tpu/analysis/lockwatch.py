"""Runtime lock-order witness: acquisition-order DAG + cycle trips.

``locklint`` proves what the *source* can do; this module watches what
the *process* actually does. Locks created through the :func:`lock` /
:func:`rlock` / :func:`condition` factories are thin wrappers over the
``threading`` primitives that, while the witness is enabled, record
every acquisition into a per-thread held stack and every (held ->
acquired) pair into one process-global order graph. The first edge that
closes a cycle — thread 1 takes A then B, thread 2 takes B then A,
*ever*, even minutes apart — is a latent deadlock, and it trips:

* the ``LOCK_ORDER_VIOLATIONS`` Dashboard counter increments,
* the violation (edge, cycle path, holder stack, thread) is recorded
  for the conftest guard and :class:`~..serving.watchdog.EngineWatchdog`
  (which turns new violations into a ``lock_order`` trip), and
* an error line is logged with the full cycle.

Identity is the CANONICAL NAME given at construction (e.g.
``serving.decode_engine.DecodeEngine._lock``), not the object: two
engines share one node, so an ordering proven safe for one instance is
demanded of all of them. Edges between two locks of the *same* name
(instance A's lock then instance B's) are not recorded — a name-level
self-edge cannot distinguish a deliberate instance hierarchy from an
inversion, and the repo has no same-class nesting today.

Cost posture: disabled (the default outside tests), an acquisition pays
one module-global boolean read. Enabled, it pays a thread-local list
append/pop, and the global graph lock ONLY when a never-before-seen
edge appears (bounded by the number of distinct lock *pairs*, not
acquisitions) — measured within container noise on the serving bench
(docs/ANALYSIS.md). Enable with the ``-lockwatch`` flag in serving, or
``enable()`` directly; the test suite enables it autouse and asserts
the DAG is acyclic and fully released after every test.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

__all__ = [
    "lock", "rlock", "condition", "enable", "disable", "enabled",
    "violations", "violation_count", "edges", "held_snapshot",
    "assert_released", "check_acyclic", "forget", "clear", "WatchedLock",
    "Violation",
]

_enabled = False

# the witness's own bookkeeping lock: a PLAIN threading.Lock on purpose
# (watching the watcher would recurse), guarding the edge graph, the
# violation list and the per-thread held-stack registry
_graph_lock = threading.Lock()
_adj: Dict[str, set] = {}              # name -> set of names acquired after
_edge_set: set = set()                 # {(held, acquired)} fast membership
_violations: List["Violation"] = []
# tid -> [per-acquisition entries]; each thread mutates only its own
# list (GIL-safe), the registry itself is mutated under _graph_lock
_held: Dict[int, List["_Held"]] = {}

_tls = threading.local()


class Violation(NamedTuple):
    """One lock-order cycle, recorded at the acquisition that closed it."""

    thread: str
    edge: Tuple[str, str]     # the (held, acquired) pair that closed it
    cycle: Tuple[str, ...]    # acquired -> ... -> held -> acquired
    held: Tuple[str, ...]     # the acquiring thread's full holder stack

    def describe(self) -> str:
        return (f"lock-order cycle on thread {self.thread!r}: acquiring "
                f"{self.edge[1]!r} while holding {self.edge[0]!r} closes "
                f"{' -> '.join(self.cycle)}")


class _Held:
    __slots__ = ("obj_id", "name", "depth")

    def __init__(self, obj_id: int, name: str) -> None:
        self.obj_id = obj_id
        self.name = name
        self.depth = 1


def _my_stack() -> List[_Held]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
        with _graph_lock:
            _held[threading.get_ident()] = stack
    return stack


def _cycle_path(src: str, dst: str) -> Optional[Tuple[str, ...]]:
    """Path src -> ... -> dst along recorded edges (callers hold
    ``_graph_lock``); adding dst -> src would then close a cycle."""
    seen = {src}
    path = [src]

    def dfs(node: str) -> bool:
        for nxt in sorted(_adj.get(node, ())):
            if nxt == dst:
                path.append(dst)
                return True
            if nxt not in seen:
                seen.add(nxt)
                path.append(nxt)
                if dfs(nxt):
                    return True
                path.pop()
        return False

    return tuple(path) if dfs(src) else None


def _record_violation(v: Violation) -> None:
    # lazy, defensive: the Dashboard import is deferred (dashboard.py
    # imports THIS module for its lock factories) and a failure to
    # count must never break the acquiring thread
    try:
        from ..dashboard import Dashboard

        Dashboard.get_or_create_counter("LOCK_ORDER_VIOLATIONS").inc()
    except Exception:       # pragma: no cover - import-order edge cases
        pass
    try:
        from ..log import Log

        Log.error("lockwatch: %s", v.describe())
    except Exception:       # pragma: no cover
        pass


def _on_acquired(wl: "WatchedLock") -> None:
    """Post-acquisition hook (the lock IS held when this runs)."""
    stack = _my_stack()
    for entry in stack:
        if entry.obj_id == id(wl):      # reentrant re-acquire (RLock)
            entry.depth += 1
            return
    entry = _Held(id(wl), wl.name)
    new_violations: List[Violation] = []
    if stack:
        holder_names = tuple(e.name for e in stack)
        for held in stack:
            if held.name == wl.name:    # name-level self-edge: skip
                continue
            edge = (held.name, wl.name)
            if edge in _edge_set:       # optimistic read; GIL-safe
                continue
            with _graph_lock:
                if edge in _edge_set:
                    continue
                cycle = _cycle_path(wl.name, held.name)
                _edge_set.add(edge)
                _adj.setdefault(held.name, set()).add(wl.name)
                if cycle is not None:
                    v = Violation(threading.current_thread().name, edge,
                                  cycle + (wl.name,), holder_names)
                    _violations.append(v)
                    new_violations.append(v)
    stack.append(entry)
    # counter/log OUTSIDE the graph lock: the Dashboard counter has its
    # own (plain) lock and must not nest under the witness's
    for v in new_violations:
        _record_violation(v)


def _on_released(wl: "WatchedLock") -> None:
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    for i in range(len(stack) - 1, -1, -1):
        if stack[i].obj_id == id(wl):
            stack[i].depth -= 1
            if stack[i].depth == 0:
                del stack[i]
            return


class WatchedLock:
    """Lock/RLock wrapper recording acquisition order while enabled.

    Duck-compatible with ``threading.Lock`` (``acquire``/``release``/
    context manager/``locked``) and usable as the underlying lock of a
    ``threading.Condition`` — the Condition's wait/notify machinery goes
    through ``acquire``/``release``, so a ``cv.wait()`` correctly drops
    the lock from the holder stack for its sleep and re-records it on
    wake.
    """

    __slots__ = ("_inner", "name")

    def __init__(self, inner, name: str) -> None:
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok and _enabled:
            _on_acquired(self)
        return ok

    def release(self) -> None:
        # pop UNCONDITIONALLY: gating this on _enabled leaves a stale
        # held-stack entry when the witness is disabled between a
        # lock's acquire and its release — the phantom hold then feeds
        # a bogus (stale -> X) edge into every later acquisition on
        # this thread, and assert_released() reports a lock held
        # forever. The pop is a cheap scan and a no-op when the
        # acquire was never recorded.
        _on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _release_save(self):
        """``threading.Condition`` wait-path hook. Forwarding matters
        for RLock-backed watched locks: Condition's default fallback is
        a SINGLE release(), so a reentrant holder (depth >= 2) would go
        to sleep still holding the underlying RLock — the notifier could
        never acquire it, a permanent deadlock. The witness entry is
        dropped whole (all recursion levels) and its depth rides the
        saved state so the wake restores it exactly."""
        stack = getattr(_tls, "stack", None)
        depth = 0
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i].obj_id == id(self):
                    depth = stack[i].depth
                    del stack[i]
                    break
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return (inner._release_save(), depth)
        inner.release()
        return (None, depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(inner_state)
        else:
            inner.acquire()
        if _enabled:
            _on_acquired(self)
            if depth > 1:
                for entry in _my_stack():
                    if entry.obj_id == id(self):
                        entry.depth = depth
                        break

    def _is_owned(self) -> bool:
        """``threading.Condition`` ownership probe. Delegating (instead
        of the Condition's try-acquire fallback) matters for RLock-backed
        watched locks: a reentrant try-acquire would SUCCEED for the
        owning thread and misreport not-owned."""
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"WatchedLock({self.name!r}, {self._inner!r})"


def lock(name: str) -> WatchedLock:
    """A watched ``threading.Lock`` under canonical ``name``."""
    return WatchedLock(threading.Lock(), name)


def rlock(name: str) -> WatchedLock:
    """A watched ``threading.RLock`` (reentrant re-acquisition bumps a
    depth count instead of recording a new edge)."""
    return WatchedLock(threading.RLock(), name)


def condition(lk: Optional[WatchedLock] = None,
              name: str = "") -> threading.Condition:
    """A ``threading.Condition`` over a watched lock. Pass the
    :class:`WatchedLock` it should share (the engine/batcher pattern:
    one lock, one condition) or a name to mint a fresh one."""
    if lk is None:
        lk = lock(name or "condition")
    return threading.Condition(lk)


# -- lifecycle / introspection ------------------------------------------------

def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def violations() -> List[Violation]:
    with _graph_lock:
        return list(_violations)


def violation_count() -> int:
    return len(_violations)       # list len read is GIL-atomic


def edges() -> set:
    with _graph_lock:
        return set(_edge_set)


def held_snapshot() -> Dict[str, List[str]]:
    """Currently-held watched locks per thread (threads holding none are
    omitted) — the conftest fully-released guard's read."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    with _graph_lock:
        items = [(tid, list(stack)) for tid, stack in _held.items()]
    for tid, stack in items:
        if stack:
            out[names.get(tid, f"tid-{tid}")] = [e.name for e in stack]
    return out


def assert_released(timeout_s: float = 5.0) -> None:
    """Assert no thread holds a watched lock, retrying for ``timeout_s``
    (running daemon threads hold locks transiently; only a hold that
    PERSISTS across the window is a leak/wedge)."""
    import time

    deadline = time.monotonic() + timeout_s
    while True:
        held = held_snapshot()
        if not held:
            return
        if time.monotonic() >= deadline:
            raise AssertionError(
                f"watched locks still held after {timeout_s:g}s: {held}")
        time.sleep(0.02)


def check_acyclic() -> List[Tuple[str, ...]]:
    """Cycles currently present in the recorded order graph (empty =
    DAG). :data:`violations` catches cycles at the edge that closed
    them; this re-derives the property from the graph itself — the
    end-of-test invariant the conftest guard asserts."""
    with _graph_lock:
        adj = {k: sorted(v) for k, v in _adj.items()}
    cycles: List[Tuple[str, ...]] = []
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    for root in sorted(adj):
        if color.get(root, WHITE) != WHITE:
            continue
        stack: List[Tuple[str, int]] = [(root, 0)]
        path = [root]
        color[root] = GREY
        while stack:
            node, i = stack[-1]
            nxts = adj.get(node, [])
            if i < len(nxts):
                stack[-1] = (node, i + 1)
                nxt = nxts[i]
                c = color.get(nxt, WHITE)
                if c == GREY:
                    cycles.append(tuple(path[path.index(nxt):]) + (nxt,))
                elif c == WHITE:
                    color[nxt] = GREY
                    stack.append((nxt, 0))
                    path.append(nxt)
            else:
                color[node] = BLACK
                stack.pop()
                path.pop()
    return cycles


def forget(name_prefix: str) -> None:
    """Drop edges and violations touching locks whose name starts with
    ``name_prefix`` — tests that deliberately seed an inversion clean up
    after themselves without wiping the real tree's recorded order."""
    with _graph_lock:
        _violations[:] = [v for v in _violations
                          if not (v.edge[0].startswith(name_prefix)
                                  or v.edge[1].startswith(name_prefix))]
        _edge_set.difference_update(
            {e for e in _edge_set if e[0].startswith(name_prefix)
             or e[1].startswith(name_prefix)})
        for src in list(_adj):
            if src.startswith(name_prefix):
                del _adj[src]
            else:
                _adj[src] = {d for d in _adj[src]
                             if not d.startswith(name_prefix)}


def clear() -> None:
    """Reset the whole witness (graph, violations, dead-thread stacks).
    Edges re-accumulate from live traffic; per-thread held stacks of
    RUNNING threads are left alone (they reflect real state)."""
    with _graph_lock:
        _adj.clear()
        _edge_set.clear()
        _violations.clear()
        live = {t.ident for t in threading.enumerate()}
        for tid in [t for t in _held if t not in live]:
            del _held[tid]
