"""Async sample readers for the LogisticRegression app.

TPU-native re-build of the reference's background ``SampleReader`` family
(``LR/src/reader.{h,cpp}``): a loader thread parses ahead into a bounded
ring while device steps consume samples, and per-window *keysets* (the set
of feature keys touched by the next ``update_per_sample`` samples) are
published on a queue so a pipelined PS model can prefetch exactly the rows
the next sync window needs (reference keyset queue,
``LR/src/reader.cpp:159-198``; consumed by ``PSModel::GetPipelineTable``,
``LR/src/model/ps_model.cpp:236``).

Reader variants (factory :func:`sample_iterator` mirroring
``SampleReader::Get``, ``LR/src/reader.cpp:212-229``):

* ``default`` — libsvm ``label k:v ...`` (sparse) or ``label v v ...``
  (dense) text (``LR/src/reader.cpp:169-207``)
* ``weight`` — ``label:weight k:v ...``; feature values are scaled by the
  per-sample weight, the bias is not (``LR/src/reader.cpp:233-278``)
* ``bsparse`` — packed binary sparse records
  ``<u64 nkeys> <i32 label> <f64 weight> <u64 keys[nkeys]>`` where every
  feature value equals the record weight (``LR/src/reader.cpp:382-444``);
  :func:`write_bsparse` produces the format.

Unlike the reference readers, none of these append the bias term — the
model classes own the bias key (``LogRegConfig.input_size``) so that every
reader variant and the test path share one convention.
"""

from __future__ import annotations

import os
import queue
import struct
import threading
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..io.stream import TextReader, open_stream
from ..log import Log

#: (label, keys, values) — keys int64, values float64.
Sample = Tuple[float, np.ndarray, np.ndarray]

_BSPARSE_HEAD = struct.Struct("<qid")  # nkeys, label, weight
_NATIVE_BSPARSE_MAX = 512 << 20   # materialization cap for the C++ parser


def _parse_features(parts: List[str], sparse: bool, input_size: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Feature tokens (already split) -> (keys, values)."""
    if sparse:
        keys, vals = [], []
        for tok in parts:
            k, _, v = tok.partition(":")
            keys.append(int(k))
            vals.append(float(v) if v else 1.0)
        return np.asarray(keys, np.int64), np.asarray(vals, np.float64)
    vals = np.zeros(input_size, np.float64)
    dense = [float(t) for t in parts]
    vals[: len(dense)] = dense
    return np.arange(len(dense), dtype=np.int64), vals


def parse_default(line: str, sparse: bool, input_size: int) -> Sample:
    """``label k:v ...`` / ``label v v ...`` (``LR/src/reader.cpp:169``)."""
    parts = line.split()
    keys, vals = _parse_features(parts[1:], sparse, input_size)
    return float(parts[0]), keys, vals


def parse_weighted(line: str, sparse: bool, input_size: int) -> Sample:
    """``label:weight k:v ...`` — values scaled by the sample weight
    (``WeightedSampleReader::ParseLine``, ``LR/src/reader.cpp:233``)."""
    parts = line.split()
    head, _, wtok = parts[0].partition(":")
    weight = float(wtok) if wtok else 1.0
    keys, vals = _parse_features(parts[1:], sparse, input_size)
    return float(head), keys, vals * weight


def write_bsparse(path: str, samples: Iterable[Sample]) -> int:
    """Write packed binary sparse records; returns the record count.

    Layout per record matches ``BSparseSampleReader::ParseSample``
    (``LR/src/reader.cpp:382-444``): ``<u64 nkeys><i32 label><f64 weight>``
    then ``nkeys`` little-endian u64 keys.  The per-record scalar feature
    value is stored as the *weight* (the format carries keys only).
    """
    count = 0
    with open_stream(path, "wb") as stream:
        for label, keys, values in samples:
            keys = np.asarray(keys, np.int64)
            vals = np.asarray(values, np.float64)
            weight = float(vals[0]) if vals.size else 1.0
            stream.write(_BSPARSE_HEAD.pack(keys.size, int(label), weight))
            stream.write(keys.astype("<i8").tobytes())
            count += 1
    return count


def iter_bsparse(path: str, chunk_size: int = 1 << 20) -> Iterator[Sample]:
    """Stream bsparse records (``BSparseSampleReader``, chunked reads
    mirroring ``LoadDataChunk``, ``LR/src/reader.cpp:367-379``)."""
    with open_stream(path, "rb") as stream:
        buf = b""
        offset = 0
        while True:
            if len(buf) - offset < _BSPARSE_HEAD.size:
                buf = buf[offset:] + stream.read(chunk_size)
                offset = 0
                if len(buf) < _BSPARSE_HEAD.size:
                    if buf:
                        raise EOFError(
                            f"truncated bsparse record header in {path}")
                    return
            nkeys, label, weight = _BSPARSE_HEAD.unpack_from(buf, offset)
            offset += _BSPARSE_HEAD.size
            nbytes = 8 * nkeys
            while len(buf) - offset < nbytes:
                more = stream.read(max(chunk_size, nbytes))
                if not more:
                    raise EOFError(f"truncated bsparse record in {path}")
                buf = buf[offset:] + more
                offset = 0
            keys = np.frombuffer(buf, "<i8", nkeys, offset).astype(np.int64)
            offset += nbytes
            yield float(label), keys, np.full(nkeys, weight, np.float64)


def sample_iterator(reader_type: str, files: str, sparse: bool,
                    input_size: int) -> Iterator[Sample]:
    """Reader factory (``SampleReader::Get``, ``LR/src/reader.cpp:212``).

    ``files`` is a comma-separated list read in order, like the reference's
    multi-file ``files_`` vector (``LR/src/reader.cpp:150-155``).
    """
    paths = [p for p in (s.strip() for s in files.split(",")) if p]
    if reader_type == "bsparse":
        if not sparse:
            Log.fatal("bsparse reader requires sparse=true "
                      "(LR/src/reader.cpp:296 LR_CHECK(sparse))")
        from .. import native

        for path in paths:
            # C++ record parser (cpp/mvtpu/reader.cc) for files small enough
            # to materialize (it returns whole arrays; the Python reader
            # streams in bounded chunks, so big files stay on it). Values
            # are f64 end-to-end, matching the Python reader exactly;
            # keys >= 2^31 make the native parser refuse, falling back to
            # the i64-capable Python reader.
            use_native = (native.available()
                          and os.path.getsize(path) <= _NATIVE_BSPARSE_MAX)
            if use_native:
                try:
                    labels, indptr, keys, values = native.parse_bsparse(path)
                except IOError:
                    Log.debug("native bsparse parse refused %s; using the "
                              "Python reader", path)
                    use_native = False
            if use_native:
                for i in range(labels.shape[0]):
                    lo, hi = int(indptr[i]), int(indptr[i + 1])
                    yield (float(labels[i]), keys[lo:hi].astype(np.int64),
                           values[lo:hi])
            else:
                yield from iter_bsparse(path)
        return
    parse = parse_weighted if reader_type == "weight" else parse_default
    if reader_type not in ("default", "weight"):
        Log.fatal(f"unknown reader_type {reader_type!r} "
                  "(expected default|weight|bsparse)")
    for path in paths:
        with TextReader(path) as reader:
            for line in reader:
                if line.strip():
                    yield parse(line, sparse, input_size)


class AsyncSampleReader:
    """Background-thread sample pipeline with per-window keyset publication.

    The loader thread parses ahead into a bounded queue (the reference's
    ring of ``max_row_buffer_count`` samples, ``LR/src/reader.cpp:128``)
    while the trainer consumes; every ``window_size`` samples the set of
    keys they touch is published so :meth:`next_keyset` can drive a
    pipelined pull of exactly the rows the *next* sync window needs
    (reference ``keys_`` queue + ``GetKeys``).

    Keysets always include ``bias_key`` when given, matching the reference
    appending the bias row to every keyset (``LR/src/reader.cpp:186-194``).
    """

    _DONE = object()

    def __init__(self, samples: Iterable[Sample], window_size: int,
                 bias_key: Optional[int] = None,
                 buffer_samples: int = 4096) -> None:
        self._samples = samples
        self._window = max(int(window_size), 1)
        self._bias_key = bias_key
        self._queue: "queue.Queue" = queue.Queue(max(buffer_samples, 1))
        self._keysets: "queue.Queue" = queue.Queue()
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._main, name="mv-sample-reader", daemon=True)
        self._thread.start()

    def _main(self) -> None:
        touched: set = set()
        count = 0
        try:
            for sample in self._samples:
                if self._stop.is_set():
                    return
                touched.update(int(k) for k in sample[1])
                count += 1
                if count == self._window:
                    self._publish_keyset(touched)
                    touched, count = set(), 0
                while not self._stop.is_set():
                    try:
                        self._queue.put(sample, timeout=0.1)
                        break
                    except queue.Full:
                        continue
            if touched:
                self._publish_keyset(touched)
        except BaseException as exc:  # surfaced on the consumer side
            self._error = exc
        finally:
            self._queue.put(self._DONE)

    def _publish_keyset(self, touched: set) -> None:
        if self._bias_key is not None:
            touched.add(int(self._bias_key))
        self._keysets.put(np.asarray(sorted(touched), np.int64))

    def __iter__(self) -> Iterator[Sample]:
        while True:
            item = self._queue.get()
            if item is self._DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def next_keyset(self, timeout: Optional[float] = None
                    ) -> Optional[np.ndarray]:
        """Keyset for the next window; None only once the stream is drained.

        Blocks while the producer is still parsing (by default without
        limit — a slow source delays the pull, it doesn't disable it). With
        ``timeout`` set, expiry raises :class:`TimeoutError` so a slow
        producer is never mistaken for end-of-stream.
        """
        while True:
            if self._error is not None:
                raise self._error
            try:
                return self._keysets.get(timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive() and self._keysets.empty():
                    return None
                if timeout is not None:
                    timeout -= 0.1
                    if timeout <= 0:
                        raise TimeoutError(
                            "next_keyset: producer still running after "
                            "timeout")

    def close(self) -> None:
        self._stop.set()
        # drain so the producer can observe the stop flag promptly
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass


def batched(samples: Iterable[Sample], batch_size: int
            ) -> Iterator[List[Sample]]:
    """Group a sample stream into minibatches (trailing partial included)."""
    batch: List[Sample] = []
    for sample in samples:
        batch.append(sample)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch:
        yield batch
