"""Distributed word2vec application.

TPU-native re-build of the reference WordEmbedding app
(``Applications/WordEmbedding/src/distributed_wordembedding.cpp`` in the
Multiverso reference): dictionary build, subsampling, skip-gram/CBOW pair
generation, epoch loop with the words/sec throughput log (the north-star
metric, ``WE/src/trainer.cpp:45-48``), and embedding save. The reference's
block data pipeline (loader thread -> BlockQueue -> per-block row pulls,
``distributed_wordembedding.cpp:33-62``) maps to a host-side batch generator
feeding fixed-shape device batches, run ahead on a loader thread
(``parallel.prefetch_iterator``) so pair generation overlaps device steps —
and for maximum throughput the corpus can live in HBM entirely
(``Word2Vec.load_corpus_chunk`` + ``train_device_steps``).

CLI mirrors the reference options (``WE/src/util.cpp``):
``python -m multiverso_tpu.apps.wordembedding -train_file corpus.txt
-output vec.txt -size 100 -window 5 -negative 5 -epoch 1 ...``
"""

from __future__ import annotations

import sys
import time
from collections import Counter
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional, Tuple

import numpy as np

from ..dashboard import Dashboard
from ..io.stream import TextReader
from ..log import Log
from ..models.word2vec import (HuffmanCodes, Word2Vec, Word2VecConfig,
                               build_huffman)


_INFREQUENT_BUCKET = "WE_ARE_THE_INFREQUENT_WORDS"


class Dictionary:
    """Vocab with counts + id mapping (reference ``WE/src/dictionary.cpp``)."""

    def __init__(self, min_count: int = 5) -> None:
        self.min_count = min_count
        self.word2id = {}
        self.words: List[str] = []
        self.counts: List[int] = []
        self._whitelist: set = set()

    # -- reference dictionary extras (dictionary.h:42-62) ------------------
    def set_whitelist(self, words) -> None:
        """Words exempt from frequency pruning/merging (``SetWhiteList``)."""
        self._whitelist = set(words)

    def insert(self, word: str, count: int = 1) -> None:
        """``Insert``: accumulate a word-count pair."""
        idx = self.word2id.get(word)
        if idx is None:
            self.word2id[word] = len(self.words)
            self.words.append(word)
            self.counts.append(int(count))
        else:
            self.counts[idx] += int(count)

    def remove_words_less_than(self, min_count: int) -> None:
        """Drop sub-threshold words (``RemoveWordsLessThan``); whitelisted
        and zero-freq entries survive, like the reference."""
        kept = [(w, c) for w, c in zip(self.words, self.counts)
                if c >= min_count or c == 0 or w in self._whitelist]
        self.word2id = {w: i for i, (w, _) in enumerate(kept)}
        self.words = [w for w, _ in kept]
        self.counts = [c for _, c in kept]

    def merge_infrequent_words(self, threshold: int) -> None:
        """Collapse sub-threshold words into ONE shared bucket id
        (``MergeInfrequentWords``, ``dictionary.cpp:26-51``): rare words
        keep training signal through a shared embedding row instead of
        being dropped."""
        new_words: List[str] = []
        new_counts: List[int] = []
        new_map: dict = {}
        infreq_idx = -1
        for word, count in zip(self.words, self.counts):
            if count >= threshold or count == 0 or word in self._whitelist:
                new_map[word] = len(new_words)
                new_words.append(word)
                new_counts.append(count)
            else:
                if infreq_idx < 0:
                    infreq_idx = len(new_words)
                    new_map[_INFREQUENT_BUCKET] = infreq_idx
                    new_words.append(_INFREQUENT_BUCKET)
                    new_counts.append(0)
                new_map[word] = infreq_idx
                new_counts[infreq_idx] += count
        self.words, self.counts, self.word2id = new_words, new_counts, new_map

    def load_tri_letter(self, path: str, min_count: int = 1,
                        letter_count: int = 3, combine: bool = False) -> None:
        """Tri-letter-gram vocabulary from a word-count file
        (``LoadTriLetterFromFile``, ``dictionary.cpp:95-140``): each word
        becomes ``#word#`` character n-grams (the DSSM trick); ``combine``
        also inserts the surface word."""
        with TextReader(path) as reader:
            for line in reader:
                parts = line.split()
                if len(parts) != 2:
                    continue
                try:
                    word, count = parts[0], int(parts[1])
                except ValueError:
                    continue
                if count < min_count:
                    continue
                if combine:
                    self.insert(word, count)
                hashed = f"#{word}#"
                if len(hashed) <= letter_count:
                    self.insert(hashed, count)
                else:
                    for i in range(len(hashed) - letter_count + 1):
                        self.insert(hashed[i:i + letter_count], count)

    @classmethod
    def build(cls, corpus_path: str, min_count: int = 5) -> "Dictionary":
        from .. import native

        if native.available():  # C++ tokeniser/counter (cpp/mvtpu/reader.cc)
            vocab = native.build_vocab(corpus_path, min_count)
            d = cls(min_count)
            d.words = vocab.words()
            d.counts = [int(c) for c in vocab.counts()]
            d.word2id = {w: i for i, w in enumerate(d.words)}
            d._native_vocab = vocab
            return d
        counter: Counter = Counter()
        with TextReader(corpus_path) as reader:
            for line in reader:
                counter.update(line.split())
        d = cls(min_count)
        for word, count in counter.most_common():
            if count < min_count:
                break
            d.word2id[word] = len(d.words)
            d.words.append(word)
            d.counts.append(count)
        return d

    def save(self, path: str) -> None:
        """Write ``word count`` lines (the ``mv_word_count`` tool's format,
        reference ``WE/preprocess/word_count.cpp`` output consumed via
        ``-read_vocab``)."""
        with open(path, "w") as f:
            for word, count in zip(self.words, self.counts):
                f.write(f"{word} {count}\n")

    @classmethod
    def load(cls, path: str, min_count: int = 5) -> "Dictionary":
        """Load a saved/preprocessed vocab file instead of re-counting the
        corpus (reference ``-read_vocab``).

        Note: a loaded dictionary has no native (C++) vocab handle, so
        ``encode_corpus`` uses the Python encoder; ``Dictionary.build``
        attaches the native tokeniser when the shared library is present.
        """
        d = cls(min_count)
        with TextReader(path) as reader:
            for line in reader:
                parts = line.split()
                if len(parts) != 2:
                    continue
                try:
                    word, count = parts[0], int(parts[1])
                except ValueError:   # tolerate headers/foreign formats
                    continue
                if count < min_count:
                    continue
                d.word2id[word] = len(d.words)
                d.words.append(word)
                d.counts.append(count)
        return d

    @property
    def vocab_size(self) -> int:
        return len(self.words)

    @property
    def train_words(self) -> int:
        return int(sum(self.counts))

    def encode(self, tokens: List[str]) -> List[int]:
        w2i = self.word2id
        return [w2i[t] for t in tokens if t in w2i]


def subsample_probs(counts: np.ndarray, sample: float) -> np.ndarray:
    """Word-discard probabilities (reference sub-sampling formula)."""
    if sample <= 0:
        return np.zeros(counts.shape[0], np.float64)
    total = counts.sum()
    freq = counts / total
    keep = (np.sqrt(freq / sample) + 1) * (sample / np.maximum(freq, 1e-12))
    return np.clip(1.0 - keep, 0.0, 1.0)


def _pairs_from_chunk(ids: np.ndarray, sent_ids: np.ndarray, window: int,
                      rng) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorised skip-gram pair generation over a word chunk.

    ``ids`` is the concatenation of (subsampled) sentences, ``sent_ids``
    marks sentence membership so windows never cross boundaries. Per-center
    random window shrink matches the reference trainer's
    ``rand % window + 1`` behavior. Returns (centers, contexts, mask).
    """
    n = ids.shape[0]
    if n < 2:
        return (np.empty(0, np.int32), np.empty(0, np.int32),
                np.empty(0, np.float32))
    shrink = rng.integers(1, window + 1, size=n)
    centers_parts, contexts_parts = [], []
    for d in range(1, window + 1):
        same_sent = sent_ids[:-d] == sent_ids[d:]
        # forward pairs: center i, context i+d (center's window covers d)
        fwd = same_sent & (shrink[:-d] >= d)
        centers_parts.append(ids[:-d][fwd])
        contexts_parts.append(ids[d:][fwd])
        # backward pairs: center i+d, context i
        bwd = same_sent & (shrink[d:] >= d)
        centers_parts.append(ids[d:][bwd])
        contexts_parts.append(ids[:-d][bwd])
    centers = np.concatenate(centers_parts).astype(np.int32)
    contexts = np.concatenate(contexts_parts).astype(np.int32)
    perm = rng.permutation(centers.shape[0])
    return (centers[perm], contexts[perm],
            np.ones(centers.shape[0], np.float32))


def _cbow_from_chunk(ids: np.ndarray, sent_ids: np.ndarray, window: int,
                     rng) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorised CBOW example generation: one example per center word with
    its (shrunk) window as context slots. Returns
    (centers [N], contexts [N, 2W], cmask [N, 2W])."""
    n = ids.shape[0]
    W = window
    if n < 2:
        return (np.empty(0, np.int32), np.empty((0, 2 * W), np.int32),
                np.empty((0, 2 * W), np.float32))
    shrink = rng.integers(1, W + 1, size=n)
    offsets = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
    pos = np.arange(n)
    ctx = pos[:, None] + offsets[None, :]
    in_range = (ctx >= 0) & (ctx < n)
    ctx_c = np.clip(ctx, 0, n - 1)
    in_window = np.abs(offsets)[None, :] <= shrink[:, None]
    valid = in_range & in_window & (sent_ids[ctx_c] == sent_ids[pos][:, None])
    keep_rows = valid.any(axis=1)
    centers = ids[pos[keep_rows]].astype(np.int32)
    contexts = ids[ctx_c[keep_rows]].astype(np.int32)
    cmask = valid[keep_rows].astype(np.float32)
    perm = rng.permutation(centers.shape[0])
    return centers[perm], contexts[perm], cmask[perm]


def iter_pair_batches(
    corpus_path: str,
    dictionary: Dictionary,
    window: int,
    batch_size: int,
    sample: float = 1e-3,
    seed: int = 11,
    cbow: bool = False,
    chunk_words: int = 1 << 20,
    progress: Optional[dict] = None,
    shard: Tuple[int, int] = (0, 1),
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Yield fixed-size (centers, contexts, mask) batches.

    Skip-gram: contexts/mask are [B]; CBOW: [B, 2*window] with per-slot
    validity. Replaces the reference's loader-thread/BlockQueue pipeline
    (``distributed_wordembedding.cpp:33-62``) with chunked vectorised numpy
    generation: sentences accumulate into ~``chunk_words`` word chunks,
    examples for a whole chunk are produced by array ops (no per-word Python
    loop), then sliced into fixed-size device batches.

    ``progress``, if given, is updated in place: ``progress["words"]`` counts
    corpus words consumed so far (pre-subsampling — the reference's
    ``word_count`` semantics) for exact lr-decay tracking.

    ``shard=(i, n)`` keeps only every n-th input line starting at line i —
    the multi-process data partition (the reference hands each process its
    own data blocks, ``distributed_wordembedding.cpp:146-178``). Sharding is
    by RAW line number, before subsampling, so the partition is disjoint and
    deterministic regardless of each rank's RNG.
    """
    shard_i, shard_n = shard
    rng = np.random.default_rng(seed)
    discard = subsample_probs(np.asarray(dictionary.counts, np.float64), sample)
    vocab_lookup = dictionary.word2id
    from_chunk = _cbow_from_chunk if cbow else _pairs_from_chunk
    chunk_ids: List[np.ndarray] = []
    chunk_sents: List[np.ndarray] = []
    chunk_len = 0
    sent_counter = 0
    leftovers: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    leftover_len = 0

    def flush_chunk():
        nonlocal chunk_ids, chunk_sents, chunk_len, leftover_len
        if not chunk_ids:
            return
        ids = np.concatenate(chunk_ids)
        sents = np.concatenate(chunk_sents)
        chunk_ids, chunk_sents, chunk_len = [], [], 0
        example = from_chunk(ids, sents, window, rng)
        leftovers.append(example)
        leftover_len += example[0].shape[0]

    def drain(final: bool):
        nonlocal leftovers, leftover_len
        if leftover_len == 0:
            return
        if not final and leftover_len < batch_size:
            return
        centers = np.concatenate([e[0] for e in leftovers])
        contexts = np.concatenate([e[1] for e in leftovers])
        masks = np.concatenate([e[2] for e in leftovers])
        full = (centers.shape[0] // batch_size) * batch_size
        for i in range(0, full, batch_size):
            yield (centers[i:i + batch_size], contexts[i:i + batch_size],
                   masks[i:i + batch_size])
        rest = (centers[full:], contexts[full:], masks[full:])
        if final and rest[0].shape[0]:
            n_rest = rest[0].shape[0]
            pad = batch_size - n_rest
            yield (
                np.concatenate([rest[0], np.zeros(pad, np.int32)]),
                np.concatenate(
                    [rest[1],
                     np.zeros((pad,) + rest[1].shape[1:], np.int32)]),
                np.concatenate(
                    [rest[2],
                     np.zeros((pad,) + rest[2].shape[1:], np.float32)]),
            )
            leftovers, leftover_len = [], 0
        else:
            leftovers = [rest]
            leftover_len = rest[0].shape[0]

    with TextReader(corpus_path) as reader:
        for line_no, line in enumerate(reader):
            if shard_n > 1 and line_no % shard_n != shard_i:
                continue
            tokens = line.split()
            arr = np.asarray([vocab_lookup[t] for t in tokens
                              if t in vocab_lookup], dtype=np.int32)
            if progress is not None:
                progress["words"] = progress.get("words", 0) + int(arr.size)
            if sample > 0 and arr.size:
                keep = rng.random(arr.shape[0]) >= discard[arr]
                arr = arr[keep]
            if arr.size < 2:
                continue
            chunk_ids.append(arr)
            chunk_sents.append(np.full(arr.shape[0], sent_counter, np.int32))
            sent_counter += 1
            chunk_len += arr.shape[0]
            if chunk_len >= chunk_words:
                flush_chunk()
                yield from drain(final=False)
    flush_chunk()
    yield from drain(final=True)


def encode_corpus(corpus_path: str, dictionary: Dictionary
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Encode a corpus to (word ids, sentence ids) arrays for upload to HBM
    (the device-resident fast path, ``Word2Vec.load_corpus_chunk``)."""
    vocab = getattr(dictionary, "_native_vocab", None)
    if vocab is not None:  # native encoder
        ids, sents, _ = vocab.encode(corpus_path)
        return ids, sents
    ids_parts: List[np.ndarray] = []
    sent_parts: List[np.ndarray] = []
    lookup = dictionary.word2id
    with TextReader(corpus_path) as reader:
        for si, line in enumerate(reader):
            arr = np.asarray([lookup[t] for t in line.split() if t in lookup],
                             dtype=np.int32)
            if arr.size < 2:
                continue
            ids_parts.append(arr)
            sent_parts.append(np.full(arr.shape[0], si, np.int32))
    if not ids_parts:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    return np.concatenate(ids_parts), np.concatenate(sent_parts)


@dataclass
class TrainResult:
    words_trained: int        # corpus words seen (reference word_count_actual)
    pairs_trained: int        # (center, context) training pairs
    elapsed_s: float
    words_per_sec: float
    pairs_per_sec: float
    final_loss: float


_DEVICE_CORPUS_MAX_TOKENS = 1 << 27   # 128M tokens ≈ 1 GB of ids in HBM


class _AsyncDeltaPusher:
    """``AddDeltaParameter`` over the async bus (``WE/src/communicator.cpp:194``).

    The fused training steps mutate the LOCAL table replica directly, so in
    async multi-process mode nothing would cross processes; this periodically
    publishes each table's OWN-training movement — (current − snapshot)
    minus the peer deltas the drain thread folded in meanwhile (tracked by
    ``TableBase._remote_accum``) — and peers apply it like any other Add.
    Per-worker AdaGrad state stays local, matching the framework's
    per-worker accumulator semantics.
    """

    def __init__(self, tables, every_calls: int = 1) -> None:
        import multiverso_tpu as mv
        from ..updaters import AddOption

        self.bus = mv.session().async_bus
        self.active = self.bus is not None
        if not self.active:
            return
        self._option = AddOption(worker_id=max(mv.worker_id(), 0))
        self.every = max(1, int(every_calls))
        self.calls = 0
        self.tables = list(tables)
        self._snaps = []
        for t in self.tables:
            if t.updater.name != "default":
                Log.fatal("async delta pusher requires the default "
                          "(accumulate) updater on its tables; "
                          f"{t.name!r} has {t.updater.name!r}")
            with t._lock:
                self._snaps.append(np.asarray(t.get(), np.float32).copy())
                t._remote_accum = np.zeros(t.shape, np.float32)

    def tick(self, force: bool = False) -> None:
        if not self.active:
            return
        self.calls += 1
        if not force and self.calls % self.every:
            return
        for i, t in enumerate(self.tables):
            with t._lock:   # atomic vs the drain thread (RLock: get() nests)
                cur = np.asarray(t.get(), np.float32)
                own = cur - self._snaps[i] - t._remote_accum
                t._remote_accum[...] = 0.0
                self._snaps[i] = cur
            # keyed touched-row publication when movement is sparse (the
            # bus picks; a -sync_frequency=1 w2v epoch touches most rows,
            # larger cadences and sparse models publish only what moved)
            self.bus.publish_delta(t, own.astype(t.dtype), self._option)

    def close(self) -> None:
        if not self.active:
            return
        self.tick(force=True)
        for t in self.tables:
            with t._lock:
                t._remote_accum = None


def train(
    corpus_path: str,
    output_path: Optional[str] = None,
    cfg: Optional[Word2VecConfig] = None,
    epochs: int = 1,
    min_count: int = 5,
    sample: float = 1e-3,
    dictionary: Optional[Dictionary] = None,
    log_every: int = 200,
    device_corpus: Optional[bool] = None,
    table_dtype: Optional[Any] = None,
    steps_per_call: Optional[int] = None,
    oversample: Optional[float] = None,
    output_path_ctx: Optional[str] = None,
) -> TrainResult:
    """Full training driver (reference ``TrainNeuralNetwork``,
    ``distributed_wordembedding.cpp:146``).

    ``device_corpus`` selects the fast path: upload the encoded corpus to
    HBM and sample + train entirely on device (``train_device_steps`` —
    the mode ``bench.py`` measures). Corpora over the HBM budget rotate
    through equal-length chunks, so the path scales to the 1B-token
    north-star corpus (~8 uploads/epoch at the default budget). Default
    (None) auto-enables it when the corpus fits one chunk; False streams
    host-generated pair batches (the reference's loader-thread shape).

    ``steps_per_call`` / ``oversample`` override the matching cfg fields;
    left as None, cfg values at their dataclass defaults are resolved to
    the chosen path's tuned values (device: 32 / 2.5; host: unchanged).
    The caller's ``cfg`` object is never mutated.
    """
    import dataclasses

    import multiverso_tpu as mv

    # Private copy: the fast-path default resolution below tunes fields on
    # it, and the caller's cfg must never inherit those values.
    cfg = dataclasses.replace(cfg) if cfg is not None else Word2VecConfig()
    explicit_spc = steps_per_call is not None
    explicit_ovs = oversample is not None
    if explicit_spc:
        cfg.steps_per_call = int(steps_per_call)
    if explicit_ovs:
        cfg.oversample = float(oversample)
    if dictionary is None:
        Log.info("building dictionary from %s ...", corpus_path)
        dictionary = Dictionary.build(corpus_path, min_count=min_count)
    vocab = dictionary.vocab_size
    if vocab == 0:
        Log.fatal(f"empty vocabulary from {corpus_path}")
    cfg.vocab_size = vocab
    counts = np.asarray(dictionary.counts, np.float64)
    Log.info("vocab %d, train words %d", vocab, dictionary.train_words)
    if cfg.row_mean_updates is None:
        # Auto: batched scatter-sum matches the reference's sequential
        # updates until the HOTTEST row collects enough colliding pair
        # grads per step to blow past the sequential loop's sigmoid
        # self-limiting (zipf corpora concentrate mass: a 71k-vocab corpus
        # at 64k batch puts thousands of colliding grads on the head words
        # and summed training NaNs within one dispatch). Estimate the
        # hot-row hits from the KNOWN sampling laws — centers/contexts
        # from the unigram counts, negatives from unigram^0.75 — and
        # switch to capped row-mean past the empirically safe region
        # (stable at ~150 hits, divergent at ~2300+; threshold 512; see
        # docs/EMBEDDING_QUALITY.md for quality parity of cap=8).
        total = max(counts.sum(), 1.0)
        p_center = float(counts.max() / total)
        w75 = counts ** 0.75
        p_neg = float(w75.max() / max(w75.sum(), 1e-12))
        est_hot = cfg.batch_size * (2 * p_center + cfg.negative * p_neg)
        cfg.row_mean_updates = est_hot > 512

    # The same two tables the reference allocates (WE/src/communicator.cpp:17-33);
    # AdaGrad G state lives model-side when cfg.use_adagrad.
    dtype_kw = {} if table_dtype is None else {"dtype": table_dtype}
    input_table = mv.create_table(
        "matrix", vocab, cfg.embedding_size, init_value="random",
        seed=cfg.seed, name="word2vec_input", **dtype_kw)
    output_table = mv.create_table(
        "matrix", vocab, cfg.embedding_size, name="word2vec_output",
        **dtype_kw)
    # word-count bookkeeping table (reference KV wordcount table)
    wordcount_table = mv.create_table("kv", name="word2vec_wordcount")

    ids = sent_ids = None
    if device_corpus is None or device_corpus:
        ids, sent_ids = encode_corpus(corpus_path, dictionary)
        n_enc = int(ids.shape[0])
        # auto-enable when the corpus fits the HBM budget AND is big enough
        # that the fast-path defaults pay off (the fused sampler also needs
        # batch + 2*window positions per step); small corpora fall back to
        # host streaming, where per-batch dispatch cost doesn't matter
        min_positions = cfg.batch_size + 2 * cfg.window + 2
        if device_corpus is None:
            device_corpus = (n_enc <= _DEVICE_CORPUS_MAX_TOKENS
                             and n_enc >= max(min_positions, 1 << 16))
        elif n_enc < min_positions:
            Log.fatal(f"device_corpus needs at least batch_size + 2*window "
                      f"positions; corpus has {n_enc}")
        # corpora over the HBM budget run the device path in rotating
        # equal-length chunks (handled below); nothing to refuse
    if device_corpus:
        # fast-path defaults: fuse many steps per dispatch and oversample
        # candidates unless the caller chose otherwise. Resolved BEFORE
        # model construction — Word2Vec validates the static-stabiliser
        # oversample prerequisite at __init__.
        if cfg.steps_per_call <= 1 and not explicit_spc:
            cfg.steps_per_call = 32
        if cfg.oversample <= 1 and not explicit_ovs:
            cfg.oversample = 2.5

    # Multi-process data parallelism: every process must train DIFFERENT
    # data, like the reference's per-process data-block partition
    # (``distributed_wordembedding.cpp:146-178``). The partition unit is
    # the PROCESS (worker lanes inside a process already split each batch
    # via the mesh worker axis — they share one data stream). Tables above
    # are seeded with the SHARED cfg.seed (identical init everywhere); the
    # model's *sampling* seed folds in the rank so subsampling, window
    # shrink and negative draws decorrelate, and the corpus itself is
    # partitioned per process below (stream offset + chunk rotation on the
    # device path, sentence sharding on the host path).
    part_i = mv.rank()
    part_n = max(mv.size(), 1)
    model_cfg = (cfg if part_n == 1
                 else dataclasses.replace(cfg, seed=cfg.seed + 100003 * part_i))
    huffman = build_huffman(counts, cfg.max_code_length) if cfg.hs else None
    model = Word2Vec(model_cfg, input_table, output_table, counts=counts,
                     huffman=huffman)
    # lr decays over the GLOBAL word count (the reference syncs word_count
    # through the server's wordcount table); with the corpus partitioned
    # part_n ways, each process's local counter advances 1/n as fast, so
    # its decay horizon is the partition's share.
    words_share = -(-dictionary.train_words // part_n)   # per epoch
    model.total_words = words_share * max(epochs, 1)

    def batch_examples(mask: np.ndarray) -> int:
        if cfg.cbow:
            return int((mask.sum(axis=-1) > 0).sum())
        return int(mask.sum())

    pairs = 0
    loss = 0.0
    t0 = time.perf_counter()
    mon = Dashboard.get_or_create("W2V_TRAIN_BATCH")

    # async multi-process: publish own-training deltas every
    # -sync_frequency calls (reference AddDeltaParameter cadence); inactive
    # single-process / sync / ma
    pusher = _AsyncDeltaPusher(
        [input_table, output_table],
        every_calls=max(1, int(mv.get_flag("sync_frequency"))))
    # -ssp_staleness=N bounds worker drift: each training call is one SSP
    # round, and the fastest worker blocks once it is > N rounds ahead.
    # The clock is per-EPOCH: shard sizes differ by a few batches, so a
    # process that exhausts its shard first releases laggards with
    # finish() (the reference FinishTrain clock -> INT_MAX,
    # ``src/server.cpp:82-139``) instead of deadlocking them against the
    # epoch barrier; the next epoch starts a fresh generation after the
    # barrier, restoring the bound.
    use_ssp = int(mv.get_flag("ssp_staleness")) >= 0 and pusher.active
    ssp_clock = None   # the CURRENT epoch's clock (released in finally)

    def _epoch_clock():
        nonlocal ssp_clock
        if use_ssp:
            from ..parallel import SSPClock

            ssp_clock = SSPClock(staleness=int(mv.get_flag("ssp_staleness")))
        return ssp_clock

    def _epoch_clock_done():
        nonlocal ssp_clock
        if ssp_clock is not None:
            ssp_clock.finish()
            ssp_clock = None

    words_done = 0   # host path: exact words this process consumed
    try:
        if device_corpus:
            # -- device-resident fast path: corpus in HBM, sampling + training
            #    fused into multi-step dispatches (defaults resolved above) --
            discard = subsample_probs(counts, sample).astype(np.float32)
            n_enc = int(ids.shape[0])
            # Corpora over the HBM budget rotate through EQUAL-length chunks
            # (equal so the fused program compiles once); the tail chunk
            # wraps to the front, mirroring the in-chunk stream's own
            # wrap-around. One chunk upload amortises over that chunk's
            # whole slice of the epoch — the 1B-token north-star corpus
            # (~8x the budget) pays 8 uploads per epoch.
            n_chunks = -(-n_enc // _DEVICE_CORPUS_MAX_TOKENS)
            # equal split (not budget-sized chunks): the tail chunk's wrap
            # overlap stays < n_chunks tokens instead of retraining up to
            # a whole budget's worth of front tokens per epoch
            chunk_len = -(-n_enc // n_chunks)
            if n_chunks > 1:
                Log.info("device corpus: %d tokens in %d chunk(s) of %d",
                         n_enc, n_chunks, chunk_len)

            def chunk_arrays(c):
                # processes rotate through chunks with a per-rank phase so
                # concurrent processes hold DIFFERENT chunks (data partition)
                lo = ((c + part_i) % n_chunks) * chunk_len
                if lo + chunk_len <= n_enc:
                    sl = slice(lo, lo + chunk_len)
                    return ids[sl], sent_ids[sl]
                wrap = lo + chunk_len - n_enc
                return (np.concatenate([ids[lo:], ids[:wrap]]),
                        np.concatenate([sent_ids[lo:], sent_ids[:wrap]]))

            model.load_corpus_chunk(*chunk_arrays(0), discard)
            # each process streams its own arc of the (cyclic) chunk
            model.set_stream_pos((part_i * chunk_len) // part_n)
            spc = cfg.steps_per_call
            m_per_step = model._candidate_batch(chunk_len)
            # The device sampler draws ONE (center, context) pair per corpus
            # position per pass; the reference trains every word in the shrunk
            # window (expected window+1 pairs per center,
            # ``wordembedding.cpp:214``). Scale passes so one "epoch" trains
            # the reference's pair count. CBOW is one example per center.
            # The pair budget is split across processes (reference data
            # blocks): an epoch is the corpus covered once IN AGGREGATE.
            pair_factor = 1 if cfg.cbow else cfg.window + 1
            calls_per_chunk = max(
                1, -(-(chunk_len * pair_factor)
                     // (spc * m_per_step * part_n)))
            for epoch in range(epochs):
                _epoch_clock()
                done = 0.0   # running pair count, synced once per log point
                pending_counts = []
                call_no = 0
                for c in range(n_chunks):
                    if n_chunks > 1 and (epoch > 0 or c > 0):
                        model.load_corpus_chunk(*chunk_arrays(c), discard)
                    for _ in range(calls_per_chunk):
                        call_no += 1
                        if ssp_clock is not None:
                            ssp_clock.wait()
                        mon.begin()
                        loss, count = model.train_device_steps(spc)
                        mon.end()
                        if ssp_clock is not None:
                            # a round must END with its deltas visible,
                            # or the SSP bound silently widens by the
                            # publish cadence
                            pusher.tick(force=True)
                            ssp_clock.tick()
                        else:
                            pusher.tick()
                        pending_counts.append(count)
                        if log_every and call_no % log_every == 0:
                            done += float(np.sum(
                                [float(x) for x in pending_counts]))
                            pending_counts = []
                            elapsed = time.perf_counter() - t0
                            Log.info(
                                "epoch %d call %d: %.0f pairs/sec, lr %.5f, "
                                "loss %.4f", epoch, call_no,
                                (pairs + done) / elapsed, model.current_lr(),
                                float(loss))
                done += float(np.sum([float(c) for c in pending_counts]))
                pairs += int(done)
                # each process reports ITS share of the epoch's words (the
                # reference adds the per-process word_count)
                wordcount_table.add([0], [words_share])
                _epoch_clock_done()
                pusher.tick(force=True)
                mv.barrier()   # quiesces the bus: all epoch deltas land
            mode = " [device corpus]"
        else:
            group = max(1, cfg.steps_per_call)
            from ..parallel import prefetch_iterator

            for epoch in range(epochs):
                _epoch_clock()
                progress = {"words": 0}
                # loader-thread overlap: batch generation runs ahead on a thread
                batches = prefetch_iterator(
                    iter_pair_batches(corpus_path, dictionary, cfg.window,
                                      cfg.batch_size, sample=sample,
                                      cbow=cfg.cbow,
                                      seed=model_cfg.seed + epoch,
                                      progress=progress,
                                      shard=(part_i, part_n)),
                    depth=2 * group)
                pending = []
                for step_idx, batch in enumerate(batches):
                    pending.append(batch)
                    if len(pending) < group:
                        continue
                    mon.begin()
                    if group == 1:
                        loss = model.train_batch(*pending[0])
                    else:
                        loss = model.train_batches(
                            np.stack([b[0] for b in pending]),
                            np.stack([b[1] for b in pending]),
                            np.stack([b[2] for b in pending]))
                    pairs += sum(batch_examples(b[2]) for b in pending)
                    pending = []
                    mon.end()
                    if ssp_clock is not None:
                        pusher.tick(force=True)
                        ssp_clock.tick()
                        ssp_clock.wait()
                    else:
                        pusher.tick()
                    # exact lr-decay progress in word units (reference
                    # word_count); progress counts this process's shard, and
                    # finished epochs contribute their EXACT word counts so
                    # the counter is monotonic across epoch boundaries
                    model.set_words_trained(words_done + progress["words"])
                    if log_every and (step_idx + 1) % log_every == 0:
                        elapsed = time.perf_counter() - t0
                        Log.info(
                            "epoch %d step %d: %.0f pairs/sec, lr %.5f, "
                            "loss %.4f", epoch, step_idx + 1, pairs / elapsed,
                            model.current_lr(), float(loss))
                for centers, contexts, mask in pending:  # tail, one dispatch each
                    loss = model.train_batch(centers, contexts, mask)
                    pairs += batch_examples(mask)
                words_done += progress["words"]
                # the reference adds each process's ACTUAL word_count
                wordcount_table.add([0], [progress["words"]])
                _epoch_clock_done()
                pusher.tick(force=True)
                mv.barrier()   # quiesces the bus: all epoch deltas land
            mode = ""
    finally:
        # always detach the remote accumulators (unbounded growth if
        # left installed after a failed run)
        _epoch_clock_done()
        pusher.close()

    final_loss = float(loss)
    elapsed = time.perf_counter() - t0

    if output_path and mv.rank() == 0:
        save_embeddings(output_path, dictionary, input_table.get())
    if output_path_ctx and mv.rank() == 0:
        # context (output-table) embeddings: the reference never saves
        # these, but held-out NS likelihood needs u_o . v_c — the
        # evaluation hook behind tools/embedding_quality.py --heldout
        save_embeddings(output_path_ctx, dictionary, output_table.get())
    # words/sec counts corpus words (reference word_count_actual semantics,
    # WE/src/trainer.cpp:45-48); pairs/sec counts device training examples.
    # Multi-process: this process trained its 1/n partition of each epoch —
    # exact on the host path, the partition share on the device path.
    words = words_share * epochs if device_corpus else words_done
    result = TrainResult(words_trained=words, pairs_trained=pairs,
                         elapsed_s=elapsed,
                         words_per_sec=words / max(elapsed, 1e-9),
                         pairs_per_sec=pairs / max(elapsed, 1e-9),
                         final_loss=final_loss)
    Log.info("trained %d words (%d pairs) in %.1fs: %.0f words/sec, "
             "%.0f pairs/sec%s",
             words, pairs, result.elapsed_s, result.words_per_sec,
             result.pairs_per_sec, mode)
    return result


def save_embeddings(path: str, dictionary: Dictionary,
                    vectors: np.ndarray) -> None:
    """word2vec text format (reference SaveEmbedding,
    ``distributed_wordembedding.cpp:260-328``)."""
    # bf16 table dumps come back as ml_dtypes scalars with no float
    # formatting support; write f32 text regardless of table dtype
    vectors = np.asarray(vectors, np.float32)
    with open(path, "w") as f:
        f.write(f"{dictionary.vocab_size} {vectors.shape[1]}\n")
        for i, word in enumerate(dictionary.words):
            vec = " ".join(f"{x:.6f}" for x in vectors[i])
            f.write(f"{word} {vec}\n")


def main(argv: Optional[List[str]] = None) -> int:
    import multiverso_tpu as mv

    argv = list(sys.argv[1:] if argv is None else argv)

    def opt(name, default, cast=str):
        flag = f"-{name}"
        if flag in argv:
            i = argv.index(flag)
            val = cast(argv[i + 1])
            del argv[i:i + 2]
            return val
        return default

    train_file = opt("train_file", "")
    output = opt("output", "embeddings.txt")
    size = opt("size", 100, int)
    window = opt("window", 5, int)
    negative = opt("negative", 5, int)
    hs = bool(opt("hs", 0, int))
    cbow = bool(opt("cbow", 0, int))
    epochs = opt("epoch", 1, int)
    min_count = opt("min_count", 5, int)
    sample = opt("sample", 1e-3, float)
    lr = opt("lr", 0.025, float)
    batch = opt("batch_size", 1024, int)
    adagrad = bool(opt("use_adagrad", 0, int))
    read_vocab = opt("read_vocab", "")
    save_vocab = opt("save_vocab", "")
    device_corpus = opt("device_corpus", -1, int)  # -1 auto, 0 off, 1 on
    # fast-path knobs. steps_per_call / oversample default to the device
    # path's tuned values INSIDE train() (the host streaming path keeps its
    # reference-shaped defaults); -1 = unset
    steps_per_call = opt("steps_per_call", -1, int)
    oversample = opt("oversample", -1.0, float)
    neg_pool = opt("neg_pool", 1 << 22, int)
    # -1 auto: reference summed-update semantics at small batch, row-mean
    # divergence guard only once batches are large enough to need it (see
    # docs/EMBEDDING_QUALITY.md for the quality comparison behind this)
    row_mean = opt("row_mean", -1, int)
    shared_negatives = opt("shared_negatives", 0, int)
    bf16 = bool(opt("bf16", 0, int))
    if not train_file:
        print("usage: wordembedding -train_file FILE [-output F] [-size N] "
              "[-window N] [-negative N] [-hs 0|1] [-cbow 0|1] [-epoch N] "
              "[-min_count N] [-sample F] [-lr F] [-batch_size N] "
              "[-use_adagrad 0|1] [-read_vocab F] [-save_vocab F] "
              "[-row_mean -1|0|1]\n"
              "  -row_mean: 0 = reference summed-update semantics "
              "(wordembedding.cpp:120-168); 1 = capped row-mean updates "
              "(large-batch divergence guard); -1 (default) = auto, on only "
              "when batch_size is large relative to the vocabulary")
        return 2
    mv.init(argv)
    cfg = Word2VecConfig(embedding_size=size, window=window, negative=negative,
                         hs=hs, cbow=cbow, init_lr=lr, batch_size=batch,
                         use_adagrad=adagrad,
                         neg_pool_size=neg_pool,
                         row_mean_updates=None if row_mean < 0 else bool(row_mean),
                         shared_negatives=shared_negatives)
    dictionary = (Dictionary.load(read_vocab, min_count=min_count)
                  if read_vocab else None)
    if save_vocab:
        if dictionary is None:
            dictionary = Dictionary.build(train_file, min_count=min_count)
        if mv.rank() == 0:   # same single-writer convention as save_embeddings
            dictionary.save(save_vocab)
    table_dtype = None
    if bf16:
        import jax.numpy as jnp

        table_dtype = jnp.bfloat16
    train(train_file, output, cfg, epochs=epochs, min_count=min_count,
          sample=sample, dictionary=dictionary,
          device_corpus=None if device_corpus < 0 else bool(device_corpus),
          table_dtype=table_dtype,
          steps_per_call=steps_per_call if steps_per_call > 0 else None,
          oversample=oversample if oversample >= 0 else None)
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
