"""Transformer language-model training CLI (beyond the reference scope).

The reference framework predates transformers (SURVEY §5.7); this app is
the modern flagship the reference's WordEmbedding would be today: a causal
LM trained data-parallel over the ``worker`` axis with Megatron-style
tensor parallelism over the ``server`` axis (``models/transformer.py``),
optional Pallas flash attention, checkpoint autosave/resume, and
byte-level tokens so no external tokenizer is needed.

Usage::

    python -m multiverso_tpu.apps.lm -train_file corpus.txt \
        [-d_model 256] [-n_layers 4] [-n_heads 4] [-seq 256] [-batch 32]
        [-steps 1000] [-lr 0.1] [-attention flash|reference|flash_force]
        [-ckpt DIR] [-ckpt_every 200] [-sample 128]
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional

import numpy as np

from ..log import Log

_VOCAB = 256   # byte-level


def load_bytes(path: str) -> np.ndarray:
    from ..io.stream import open_stream

    with open_stream(path, "rb") as f:
        data = f.read()
    if len(data) < 2:
        Log.fatal(f"corpus too small: {path}")
    return np.frombuffer(data, np.uint8).astype(np.int32)


def batches(data: np.ndarray, batch: int, seq: int, seed: int = 0):
    """Random [batch, seq+1] windows forever (next-token targets)."""
    rng = np.random.default_rng(seed)
    n = data.shape[0] - seq - 1
    while True:
        starts = rng.integers(0, n, batch)
        yield np.stack([data[s:s + seq + 1] for s in starts])


def sample(lm, prompt: np.ndarray, n_tokens: int, temperature: float = 1.0,
           seed: int = 0) -> np.ndarray:
    """Greedy/temperature sampling (host loop; generation is not the hot
    path here — the training step is)."""
    rng = np.random.default_rng(seed)
    toks = list(prompt)
    max_seq = lm.config.max_seq
    for _ in range(n_tokens):
        ctx = np.asarray(toks[-max_seq:], np.int32)[None, :]
        logits = np.asarray(lm.logits(ctx))[0, -1]
        if temperature <= 0:
            nxt = int(logits.argmax())
        else:
            p = np.exp((logits - logits.max()) / temperature)
            p /= p.sum()
            nxt = int(rng.choice(_VOCAB, p=p))
        toks.append(nxt)
    return np.asarray(toks, np.int32)


def main(argv: Optional[List[str]] = None) -> int:
    import multiverso_tpu as mv
    from ..models.transformer import TransformerConfig, TransformerLM

    argv = list(sys.argv[1:] if argv is None else argv)

    def opt(name, default, cast=str):
        flag = f"-{name}"
        if flag in argv:
            i = argv.index(flag)
            val = cast(argv[i + 1])
            del argv[i:i + 2]
            return val
        return default

    train_file = opt("train_file", "")
    d_model = opt("d_model", 256, int)
    n_layers = opt("n_layers", 4, int)
    n_heads = opt("n_heads", 4, int)
    d_ff = opt("d_ff", 0, int) or 4 * d_model
    seq = opt("seq", 256, int)
    batch = opt("batch", 32, int)
    steps = opt("steps", 1000, int)
    lr = opt("lr", 0.1, float)
    # flash = crossover dispatch, never slower than reference at any
    # shape (docs/LM_MFU.md: 1.5-2x faster at seq >= 1024 in-model)
    attention = opt("attention", "flash")
    ckpt = opt("ckpt", "")
    ckpt_every = opt("ckpt_every", 200, int)
    n_sample = opt("sample", 0, int)
    log_every = opt("log_every", 50, int)
    if not train_file:
        print("usage: lm -train_file FILE [-d_model N] [-n_layers N] "
              "[-n_heads N] [-seq N] [-batch N] [-steps N] [-lr F] "
              "[-attention flash|reference|flash_force] [-ckpt DIR] [-ckpt_every N] "
              "[-sample N]")
        return 2

    mv.init(argv)
    cfg = TransformerConfig(vocab_size=_VOCAB, d_model=d_model,
                            n_heads=n_heads, n_layers=n_layers, d_ff=d_ff,
                            max_seq=seq, learning_rate=lr,
                            attention=attention)
    lm = TransformerLM(cfg)
    data = load_bytes(train_file)
    if data.shape[0] < seq + 2:
        Log.fatal(f"corpus has {data.shape[0]} bytes; needs >= seq+2 "
                  f"({seq + 2}) for [batch, seq+1] windows")
    Log.info("LM: %d bytes corpus, d_model %d, %d layers, %d heads, "
             "attention=%s, mesh %s", data.shape[0], d_model, n_layers,
             n_heads, attention, dict(mv.session().mesh.shape))

    # resume + autosave through the table registry: LM params live in the
    # model, so expose them to the checkpoint layer via a matrix table
    # holding the flattened params (simple + uses the PS machinery)
    saver = None
    start_step = 0
    flat_table = None
    if ckpt:
        import jax

        from ..io import checkpoint

        leaves = jax.tree_util.tree_leaves(lm.params)
        total = int(sum(np.prod(np.shape(l)) for l in leaves))
        flat_table = mv.create_table("array", total, name="lm_params")
        latest = checkpoint.restore_latest(ckpt)
        if latest is not None:
            flat = flat_table.get()
            offset = 0
            new_leaves = []
            for leaf in leaves:
                size = int(np.prod(np.shape(leaf)))
                new_leaves.append(
                    flat[offset:offset + size].reshape(np.shape(leaf))
                    .astype(np.asarray(leaf).dtype))
                offset += size
            lm.params = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(lm.params), new_leaves)
            start_step = latest
            Log.info("resumed from step %d", latest)
        saver = checkpoint.Autosaver(ckpt, every_steps=ckpt_every)

    def snapshot_params():
        import jax

        leaves = jax.tree_util.tree_leaves(lm.params)
        flat = np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves])
        current = flat_table.get()
        # scale by the add's actual fan-out: under sync aggregation (sum
        # over processes) and the async bus (every peer applies every add)
        # each replica receives size copies of the delta; in ma mode or a
        # bus-less run the add stays local and must not be scaled down
        sess = mv.session()
        fanout = (mv.size() if (mv.get_flag("sync")
                                or sess.async_bus is not None) else 1)
        flat_table.add((flat - current) / fanout)

    t0 = time.perf_counter()
    gen = batches(data, batch, seq, seed=mv.rank())
    loss = None
    for step in range(start_step + 1, steps + 1):
        loss = lm.train_batch(next(gen))
        if log_every and step % log_every == 0:
            elapsed = time.perf_counter() - t0
            tps = (step - start_step) * batch * seq / elapsed
            Log.info("step %d: loss %.4f, ppl %.1f, %.0f tok/s",
                     step, float(loss), float(np.exp(float(loss))), tps)
        if saver is not None and step % ckpt_every == 0:
            snapshot_params()
            saver.step(step)
    if saver is not None and steps % ckpt_every != 0 and steps > start_step:
        snapshot_params()
        saver.save_now(steps)   # the final state is the app's artifact
    if loss is not None:
        Log.info("final loss %.4f (ppl %.1f)", float(loss),
                 float(np.exp(float(loss))))

    if n_sample > 0:
        # the forward pass computes over mesh-sharded params: every
        # process must participate; only rank 0 prints
        out = sample(lm, data[:16], n_sample)
        if mv.rank() == 0:
            text = bytes(out.astype(np.uint8)).decode("utf-8",
                                                      errors="replace")
            print("--- sample ---")
            print(text)

    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
