"""Reference applications: distributed word2vec + logistic regression."""
