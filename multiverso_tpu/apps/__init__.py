"""Applications: distributed word2vec + logistic regression (reference
parity) and a transformer LM (beyond reference — apps/lm.py)."""
