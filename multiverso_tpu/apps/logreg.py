"""Logistic-regression application: config-file driven train/test.

TPU-native re-build of the reference LogisticRegression app driver
(``Applications/LogisticRegression/src/logreg.cpp`` in the Multiverso
reference): key=value config file (``LR/src/configure.h:9-93``), libsvm /
dense text readers (``LR/src/reader.cpp:169``), epoch loop with minibatch
updates, test accuracy, model save/load. With ``pipeline=true`` the
reference's background ``SampleReader`` thread (``LR/src/reader.cpp:128``)
maps to ``parallel.prefetch_iterator``: parsing runs ahead on a loader
thread, overlapping device steps; ``sync_frequency=N`` makes the sparse
model refresh its pulled weights every N minibatches
(``PSModel::DoesNeedSync``, ``ps_model.cpp:172``).

Usage: ``python -m multiverso_tpu.apps.logreg train.config``
"""

from __future__ import annotations

import sys
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..io.stream import TextReader, open_stream
from ..log import Log
from ..models.logreg import FTRLLogReg, LogReg, LogRegConfig, SparseLogReg
from ..parallel import PipelinedGetter, prefetch_iterator
from .lr_reader import AsyncSampleReader, batched, parse_default, sample_iterator


def parse_config(path: str) -> dict:
    """key=value config file (reference ``Configure``, ``LR/src/configure.cpp``)."""
    out = {}
    with TextReader(path) as reader:
        for line in reader:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            key, _, value = line.partition("=")
            out[key.strip()] = value.strip()
    return out


def config_from_dict(d: dict) -> LogRegConfig:
    cfg = LogRegConfig()
    casts = {
        "input_size": int, "output_size": int, "minibatch_size": int,
        "sync_frequency": int, "read_buffer_size": int,
        "learning_rate": float,
        "learning_rate_coef": float, "regular_coef": float,
        "ftrl_alpha": float, "ftrl_beta": float,
        "ftrl_lambda1": float, "ftrl_lambda2": float,
    }
    for key, value in d.items():
        if key in ("objective_type", "regular_type", "reader_type"):
            setattr(cfg, key, value)
        elif key in ("sparse", "pipeline"):
            setattr(cfg, key, value.lower() in ("1", "true", "yes"))
        elif key in casts:
            setattr(cfg, key, casts[key](value))
    return cfg


# libsvm/dense text parsing lives in lr_reader; kept under the old name.
parse_sample = parse_default


def iter_samples(path: str, sparse: bool, input_size: int,
                 reader_type: str = "default"):
    """Reader-factory front door (``SampleReader::Get``,
    ``LR/src/reader.cpp:212``); see :mod:`.lr_reader` for the variants."""
    yield from sample_iterator(reader_type, path, sparse, input_size)


def iter_dense_minibatches(path: str, cfg: LogRegConfig
                           ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Fixed-size [B, input] / [B, output] batches for the dense jitted path."""
    xs, ys = [], []
    for label, _, values in iter_samples(path, False, cfg.input_size,
                                         cfg.reader_type):
        xs.append(values)
        if cfg.output_size == 1:
            ys.append([label])
        else:
            onehot = np.zeros(cfg.output_size, np.float32)
            onehot[int(label)] = 1.0
            ys.append(onehot)
        if len(xs) == cfg.minibatch_size:
            yield np.stack(xs), np.asarray(ys, np.float32)
            xs, ys = [], []
    if xs:
        yield np.stack(xs), np.asarray(ys, np.float32)


def build_model(cfg: LogRegConfig):
    """Table + model factory (reference ``Model::Get``/``PSModel`` ctor,
    ``LR/src/model/model.cpp:212``, ``ps_model.cpp:13-67``)."""
    import multiverso_tpu as mv

    if cfg.objective_type == "ftrl":
        table = mv.create_table("ftrl", cfg.input_size + 1, name="logreg_ftrl")
        return FTRLLogReg(cfg, table)
    if cfg.sparse:
        table = mv.create_table("sparse", cfg.input_size + 1, updater="sgd",
                                name="logreg_sparse")
        return SparseLogReg(cfg, table)
    table = mv.create_table("matrix", cfg.output_size, cfg.input_size + 1,
                            updater="sgd", name="logreg_weights")
    return LogReg(cfg, table)


def train_file(model, cfg: LogRegConfig, path: str, epochs: int = 1,
               log_every: int = 100) -> float:
    """Epoch loop (reference ``LogReg::Train``, ``LR/src/logreg.cpp:40``)."""
    loss = 0.0
    for epoch in range(epochs):
        if isinstance(model, LogReg):
            batches = iter_dense_minibatches(path, cfg)
            if cfg.pipeline:
                batches = prefetch_iterator(batches, depth=4)
            for i, (x, y) in enumerate(batches):
                loss = model.train_minibatch(x, y)
                if log_every and (i + 1) % log_every == 0:
                    Log.info("epoch %d batch %d loss %.4f", epoch, i + 1,
                             float(loss))
            loss = float(loss)
        elif isinstance(model, SparseLogReg):
            loss = _train_sparse_epoch(model, cfg, path)
        else:  # FTRL: per-sample proximal updates
            for label, keys, values in iter_samples(
                    path, True, cfg.input_size, cfg.reader_type):
                loss = model.train_sample(keys, values, label)
    return float(loss)


def _train_sparse_epoch(model: SparseLogReg, cfg: LogRegConfig, path: str
                        ) -> float:
    """One epoch of the sparse PS path.

    ``pipeline=true`` runs the reference's double-buffered pull
    (``PSModel::GetPipelineTable``, ``LR/src/model/ps_model.cpp:236``): the
    async reader publishes each sync window's keyset ahead of time, a
    background getter pulls those rows while the current window trains, and
    the result lands in the model cache at the window boundary.
    """
    loss = 0.0
    samples = sample_iterator(cfg.reader_type, path, True, cfg.input_size)
    sync_every = max(cfg.sync_frequency, 1)
    if not cfg.pipeline:
        for batch in batched(samples, cfg.minibatch_size):
            loss = model.train_minibatch(
                [(keys, values, label) for label, keys, values in batch])
        return loss
    window = cfg.minibatch_size * sync_every
    reader = AsyncSampleReader(
        samples, window_size=window, bias_key=model.bias_key,
        # At the start of window j the consumer blocks on keyset j+1, which
        # the loader publishes only after parsing 2*window samples past the
        # consumer's position — the ring must hold that much.
        buffer_samples=max(cfg.read_buffer_size, 2 * window))
    getter = PipelinedGetter(lambda ks: (ks, model.table.get_keys(ks)))
    in_flight = False
    first = reader.next_keyset()
    if first is not None:
        getter.prime(first)
        in_flight = True
    try:
        for batch_idx, batch in enumerate(batched(reader, cfg.minibatch_size)):
            # Align on the per-epoch batch index: the reader's keyset windows
            # restart at sample 0 each epoch, so the boundary phase must
            # restart with them (model.steps carries phase across epochs
            # whenever an epoch's batch count is not a multiple of
            # sync_frequency, which would misalign every later window).
            if in_flight and batch_idx % sync_every == 0:
                nxt = reader.next_keyset()
                pulled = getter.get(nxt)
                in_flight = nxt is not None
                model.load_cache(*pulled)
            loss = model.train_minibatch(
                [(keys, values, label) for label, keys, values in batch])
    finally:
        reader.close()
    return loss


def test_file(model, cfg: LogRegConfig, path: str) -> float:
    """Accuracy over a test file (reference ``LogReg::Test``)."""
    if isinstance(model, LogReg):
        correct = total = 0
        for x, y in iter_dense_minibatches(path, cfg):
            preds = model.predict(x)
            if cfg.output_size == 1:
                correct += int((((preds[:, 0] > 0.5) == (y[:, 0] > 0.5))).sum())
            else:
                correct += int((preds.argmax(-1) == y.argmax(-1)).sum())
            total += x.shape[0]
        return correct / max(total, 1)
    correct = total = 0
    for label, keys, values in iter_samples(path, True, cfg.input_size,
                                            cfg.reader_type):
        pred = model.predict_sample(keys, values)
        correct += int((pred > 0.5) == (label > 0.5))
        total += 1
    return correct / max(total, 1)


def save_model(model, path: str) -> None:
    with open_stream(path, "wb") as stream:
        model.table.store(stream)


def load_model(model, path: str) -> None:
    with open_stream(path, "rb") as stream:
        model.table.load(stream)


def main(argv: Optional[List[str]] = None) -> int:
    import multiverso_tpu as mv

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: logreg <config-file>")
        return 2
    import os

    if not os.path.exists(argv[0]):
        print(f"logreg: config file not found: {argv[0]}")
        return 2
    conf = parse_config(argv[0])
    mv.init(argv[1:])
    cfg = config_from_dict(conf)
    model = build_model(cfg)
    if conf.get("init_model_file"):
        load_model(model, conf["init_model_file"])
    if conf.get("train_file"):
        epochs = int(conf.get("train_epoch", "1"))
        loss = train_file(model, cfg, conf["train_file"], epochs=epochs)
        Log.info("final train loss: %.4f", loss)
    if conf.get("test_file"):
        acc = test_file(model, cfg, conf["test_file"])
        Log.info("test accuracy: %.4f", acc)
    if conf.get("output_model_file"):
        save_model(model, conf["output_model_file"])
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
