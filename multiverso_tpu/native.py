"""ctypes interface to the native runtime (``cpp/libmultiverso_tpu.so``).

Two directions of integration:

* **Loaders** — the native corpus/libsvm readers (``cpp/mvtpu/reader.cc``)
  are the fast host path for the data pipeline; ``build_vocab`` /
  ``encode_corpus`` / ``parse_libsvm`` wrap them with numpy outputs.
* **Bridge** — ``install_bridge()`` points the C ABI's function-pointer
  table (``cpp/c_api.h`` MV_Bridge) at this process's JAX session, so C and
  Lua callers of ``MV_GetArrayTable``/... operate on TPU-resident sharded
  tables instead of the library's local store.

The library is optional: every caller falls back to pure Python when it is
absent (``available()``).
"""

from __future__ import annotations

import ctypes
import os
import threading
from .analysis import lockwatch
from typing import List, Optional, Tuple

import numpy as np

from .log import Log

_LIB_ENV = "MV_NATIVE_LIB"
_lock = lockwatch.lock("native._lock")
_lib: Optional[ctypes.CDLL] = None
# Must match MV_EXT_ABI_VERSION in cpp/c_api.h (rev 2: f64 SvmData values).
_EXT_ABI_VERSION = 2


def _lib_candidates() -> List[str]:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [
        os.environ.get(_LIB_ENV, ""),
        os.path.join(here, "cpp", "libmultiverso_tpu.so"),
        "libmultiverso_tpu.so",
    ]


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.MV_Init.argtypes = [c.POINTER(c.c_int), c.POINTER(c.c_char_p)]
    lib.MV_SetFlag.argtypes = [c.c_char_p, c.c_char_p]
    lib.MV_SetFlag.restype = c.c_int
    lib.MV_NewArrayTable.argtypes = [c.c_int, c.POINTER(c.c_void_p)]
    lib.MV_GetArrayTable.argtypes = [c.c_void_p, c.POINTER(c.c_float), c.c_int]
    lib.MV_AddArrayTable.argtypes = [c.c_void_p, c.POINTER(c.c_float), c.c_int]
    lib.MV_AddAsyncArrayTable.argtypes = lib.MV_AddArrayTable.argtypes
    lib.MV_NewMatrixTable.argtypes = [c.c_int, c.c_int, c.POINTER(c.c_void_p)]
    lib.MV_GetMatrixTableAll.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                         c.c_int]
    lib.MV_AddMatrixTableAll.argtypes = lib.MV_GetMatrixTableAll.argtypes
    lib.MV_AddAsyncMatrixTableAll.argtypes = lib.MV_GetMatrixTableAll.argtypes
    rows_sig = [c.c_void_p, c.POINTER(c.c_float), c.c_int,
                c.POINTER(c.c_int), c.c_int]
    lib.MV_GetMatrixTableByRows.argtypes = rows_sig
    lib.MV_AddMatrixTableByRows.argtypes = rows_sig
    lib.MV_AddAsyncMatrixTableByRows.argtypes = rows_sig
    lib.MV_StoreTable.argtypes = [c.c_void_p, c.c_char_p]
    lib.MV_StoreTable.restype = c.c_int
    lib.MV_LoadTable.argtypes = [c.c_void_p, c.c_char_p]
    lib.MV_LoadTable.restype = c.c_int
    lib.MV_VocabBuild.argtypes = [c.c_char_p, c.c_int]
    lib.MV_VocabBuild.restype = c.c_void_p
    lib.MV_VocabSize.argtypes = [c.c_void_p]
    lib.MV_VocabSize.restype = c.c_int
    lib.MV_VocabTrainWords.argtypes = [c.c_void_p]
    lib.MV_VocabTrainWords.restype = c.c_longlong
    lib.MV_VocabCounts.argtypes = [c.c_void_p, c.POINTER(c.c_longlong)]
    lib.MV_VocabWord.argtypes = [c.c_void_p, c.c_int]
    lib.MV_VocabWord.restype = c.c_char_p
    lib.MV_VocabFree.argtypes = [c.c_void_p]
    lib.MV_CorpusEncode.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.POINTER(c.c_int32)),
        c.POINTER(c.POINTER(c.c_int32)), c.POINTER(c.c_longlong)]
    lib.MV_CorpusEncode.restype = c.c_longlong
    lib.MV_BufferFree.argtypes = [c.c_void_p]
    lib.MV_SvmParse.argtypes = [c.c_char_p]
    lib.MV_SvmParse.restype = c.c_void_p
    lib.MV_BsparseParse.argtypes = [c.c_char_p]
    lib.MV_BsparseParse.restype = c.c_void_p
    lib.MV_SvmNumSamples.argtypes = [c.c_void_p]
    lib.MV_SvmNumSamples.restype = c.c_longlong
    lib.MV_SvmNumEntries.argtypes = [c.c_void_p]
    lib.MV_SvmNumEntries.restype = c.c_longlong
    lib.MV_SvmCopy.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                               c.POINTER(c.c_int64), c.POINTER(c.c_int32),
                               c.POINTER(c.c_double)]
    lib.MV_SvmFree.argtypes = [c.c_void_p]


def load() -> Optional[ctypes.CDLL]:
    """Load and return the native library, or None if unavailable.

    A failed load is retried on the next call (the library may be built or
    ``MV_NATIVE_LIB`` set later in the process); a successful load sticks.
    """
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        for path in _lib_candidates():
            if not path:
                continue
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            # Refuse ABI-skewed builds: a stale .so with a different ext
            # signature set would silently exchange mis-sized buffers
            # (e.g. f32 SvmData values into an f64 array).
            try:
                got = int(lib.MV_ExtAbiVersion())
            except AttributeError:
                got = 1   # pre-versioning builds
            if got != _EXT_ABI_VERSION:
                Log.error(
                    "native library %s has ext ABI rev %d, need %d — "
                    "rebuild cpp/ (make); falling back to Python paths",
                    path, got, _EXT_ABI_VERSION)
                continue
            _declare(lib)
            _lib = lib
            Log.debug("native runtime loaded: %s", path)
            break
        return _lib


def available() -> bool:
    return load() is not None


# -- native loaders ----------------------------------------------------------

class NativeVocab:
    """Wrapper over the native corpus vocab (reference Dictionary)."""

    def __init__(self, handle: int, lib: ctypes.CDLL) -> None:
        self._h = handle
        self._lib = lib
        self.size = int(lib.MV_VocabSize(handle))
        self.train_words = int(lib.MV_VocabTrainWords(handle))

    def counts(self) -> np.ndarray:
        out = np.zeros(self.size, np.int64)
        self._lib.MV_VocabCounts(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)))
        return out

    def words(self) -> List[str]:
        # errors="replace" matches the pure-Python TextReader path, so a
        # non-UTF-8 corpus degrades identically instead of crashing here.
        return [self._lib.MV_VocabWord(self._h, i).decode("utf-8",
                                                          errors="replace")
                for i in range(self.size)]

    def encode(self, path: str) -> Tuple[np.ndarray, np.ndarray, int]:
        """Returns (ids, sentence_ids, words_read)."""
        lib = self._lib
        ids_p = ctypes.POINTER(ctypes.c_int32)()
        sents_p = ctypes.POINTER(ctypes.c_int32)()
        n = ctypes.c_longlong()
        words_read = lib.MV_CorpusEncode(
            self._h, path.encode(), ctypes.byref(ids_p), ctypes.byref(sents_p),
            ctypes.byref(n))
        if words_read < 0:
            raise IOError(f"native corpus encode failed: {path}")
        count = int(n.value)
        ids = np.ctypeslib.as_array(ids_p, shape=(count,)).copy()
        sents = np.ctypeslib.as_array(sents_p, shape=(count,)).copy()
        lib.MV_BufferFree(ids_p)
        lib.MV_BufferFree(sents_p)
        return ids, sents, int(words_read)

    def free(self) -> None:
        if self._h:
            self._lib.MV_VocabFree(self._h)
            self._h = 0

    def __del__(self):  # pragma: no cover
        try:
            self.free()
        except Exception:
            pass


def build_vocab(path: str, min_count: int = 5) -> Optional[NativeVocab]:
    lib = load()
    if lib is None:
        return None
    handle = lib.MV_VocabBuild(path.encode(), min_count)
    if not handle:
        raise IOError(f"native vocab build failed: {path}")
    return NativeVocab(handle, lib)


def _copy_svm_handle(lib, handle):
    n = int(lib.MV_SvmNumSamples(handle))
    entries = int(lib.MV_SvmNumEntries(handle))
    labels = np.zeros(n, np.float32)
    indptr = np.zeros(n + 1, np.int64)
    keys = np.zeros(entries, np.int32)
    values = np.zeros(entries, np.float64)
    c = ctypes
    lib.MV_SvmCopy(handle,
                   labels.ctypes.data_as(c.POINTER(c.c_float)),
                   indptr.ctypes.data_as(c.POINTER(c.c_int64)),
                   keys.ctypes.data_as(c.POINTER(c.c_int32)),
                   values.ctypes.data_as(c.POINTER(c.c_double)))
    lib.MV_SvmFree(handle)
    return labels, indptr, keys, values


def parse_libsvm(path: str):
    """Returns (labels, indptr, keys, values) numpy arrays, or None."""
    lib = load()
    if lib is None:
        return None
    handle = lib.MV_SvmParse(path.encode())
    if not handle:
        raise IOError(f"native libsvm parse failed: {path}")
    return _copy_svm_handle(lib, handle)


def parse_bsparse(path: str):
    """Native bsparse reader (LogReg binary records); None without the lib.

    Raises IOError on open failure or a truncated record (matching the
    Python reader's EOFError stance on corrupt files).
    """
    lib = load()
    if lib is None:
        return None
    handle = lib.MV_BsparseParse(path.encode())
    if not handle:
        raise IOError(f"native bsparse parse failed (missing or truncated): "
                      f"{path}")
    return _copy_svm_handle(lib, handle)


# -- bridge ------------------------------------------------------------------

class _BridgeStruct(ctypes.Structure):
    _void = ctypes.CFUNCTYPE(None)
    _fields_ = [
        ("init", ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_char_p))),
        ("shutdown", ctypes.CFUNCTYPE(None)),
        ("barrier", ctypes.CFUNCTYPE(None)),
        ("num_workers", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("worker_id", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("server_id", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("rank", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("size", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("num_servers", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("new_array", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int)),
        ("get_array", ctypes.CFUNCTYPE(None, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int)),
        ("add_array", ctypes.CFUNCTYPE(None, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int, ctypes.c_int)),
        ("new_matrix", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int)),
        ("get_matrix", ctypes.CFUNCTYPE(None, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_float),
                                        ctypes.c_int)),
        ("add_matrix", ctypes.CFUNCTYPE(None, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_float),
                                        ctypes.c_int, ctypes.c_int)),
        ("get_rows", ctypes.CFUNCTYPE(None, ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_float),
                                      ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_int),
                                      ctypes.c_int)),
        ("add_rows", ctypes.CFUNCTYPE(None, ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_float),
                                      ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_int),
                                      ctypes.c_int, ctypes.c_int)),
        ("store_table", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                                         ctypes.c_char_p)),
        ("load_table", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                                        ctypes.c_char_p)),
    ]


_bridge_refs: List[object] = []  # keep callbacks alive for the library


def install_bridge() -> bool:
    """Route the C ABI at this process's JAX session. Returns False if the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return False
    import multiverso_tpu as mv

    def table(tid):
        return mv.session().table(tid)

    F = dict(_BridgeStruct._fields_)

    def cb(name, fn):
        wrapped = F[name](fn)
        _bridge_refs.append(wrapped)
        return wrapped

    def _init(argc, argv):
        mv.init()

    def _get(tid, data, size):
        out = np.ascontiguousarray(
            np.asarray(table(tid).get(), np.float32).ravel()[:size])
        ctypes.memmove(data, out.ctypes.data, min(size, out.size) * 4)

    def _add(tid, data, size, async_hint):
        arr = np.ctypeslib.as_array(data, shape=(size,)).copy()
        t = table(tid)
        delta = arr.reshape(t.shape)
        if async_hint:
            t.add_async(delta)
        else:
            t.add(delta)

    def _get_rows(tid, data, size, row_ids, n):
        ids = np.ctypeslib.as_array(row_ids, shape=(n,)).copy()
        rows = np.asarray(table(tid).get_rows(ids), np.float32)
        ctypes.memmove(data, rows.ctypes.data, min(size, rows.size) * 4)

    def _add_rows(tid, data, size, row_ids, n, async_hint):
        ids = np.ctypeslib.as_array(row_ids, shape=(n,)).copy()
        t = table(tid)
        vals = np.ctypeslib.as_array(data, shape=(n, t.num_col)).copy()
        if async_hint:
            t.add_rows_async(ids, vals)
        else:
            t.add_rows(ids, vals)

    def _store(tid, path):
        from .io.stream import open_stream

        with open_stream(path.decode(), "wb") as stream:
            table(tid).store(stream)
        return 0

    def _load(tid, path):
        from .io.stream import open_stream

        with open_stream(path.decode(), "rb") as stream:
            table(tid).load(stream)
        return 0

    bridge = _BridgeStruct(
        init=cb("init", _init),
        shutdown=cb("shutdown", lambda: mv.shutdown()),
        barrier=cb("barrier", lambda: mv.barrier()),
        num_workers=cb("num_workers", lambda: mv.num_workers()),
        worker_id=cb("worker_id", lambda: max(mv.worker_id(), 0)),
        server_id=cb("server_id", lambda: max(mv.server_id(), 0)),
        rank=cb("rank", lambda: mv.rank()),
        size=cb("size", lambda: mv.size()),
        num_servers=cb("num_servers", lambda: mv.num_servers()),
        new_array=cb("new_array",
                     lambda size: mv.create_table("array", size).table_id),
        get_array=cb("get_array", _get),
        add_array=cb("add_array", _add),
        new_matrix=cb("new_matrix",
                      lambda r, c: mv.create_table("matrix", r, c).table_id),
        get_matrix=cb("get_matrix", _get),
        add_matrix=cb("add_matrix", _add),
        get_rows=cb("get_rows", _get_rows),
        add_rows=cb("add_rows", _add_rows),
        store_table=cb("store_table", _store),
        load_table=cb("load_table", _load),
    )
    _bridge_refs.append(bridge)
    lib.MV_InstallBridge(ctypes.byref(bridge))
    return True


def clear_bridge() -> None:
    lib = load()
    if lib is not None:
        lib.MV_ClearBridge()
