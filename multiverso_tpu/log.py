"""Logging with Debug/Info/Error/Fatal levels + CHECK helpers.

TPU-native equivalent of the reference logger
(``include/multiverso/util/log.h:9-18,110-142`` in the Multiverso reference):
timestamped ``[LEVEL] [ts] [rank]`` lines to stdout plus an optional file sink,
a ``Fatal`` level that (by default) raises instead of killing the process, and
``CHECK`` / ``CHECK_NOTNULL`` assertion helpers that route through ``Fatal``.

Built on the stdlib ``logging`` module rather than a hand-rolled sink so user
code can attach handlers; the reference-facing API surface is preserved.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from enum import IntEnum
from typing import Any, Optional


class LogLevel(IntEnum):
    DEBUG = 0
    INFO = 1
    ERROR = 2
    FATAL = 3


_LEVEL_MAP = {
    LogLevel.DEBUG: logging.DEBUG,
    LogLevel.INFO: logging.INFO,
    LogLevel.ERROR: logging.ERROR,
    LogLevel.FATAL: logging.CRITICAL,
}

_LEVEL_NAMES = {"debug": LogLevel.DEBUG, "info": LogLevel.INFO,
                "error": LogLevel.ERROR, "fatal": LogLevel.FATAL}


class FatalError(RuntimeError):
    """Raised by Log.fatal / failed CHECKs when kill-on-fatal is off."""


class Logger:
    """Instance logger; static facade below mirrors the reference's ``Log``."""

    def __init__(self, name: str = "multiverso", level: LogLevel = LogLevel.INFO) -> None:
        self._logger = logging.getLogger(name)
        self._logger.propagate = False
        if not self._logger.handlers:
            handler = logging.StreamHandler(sys.stdout)
            handler.setFormatter(self._formatter())
            self._logger.addHandler(handler)
        self._level = level
        self._logger.setLevel(_LEVEL_MAP[level])
        self._kill_fatal = False
        self._file_handler: Optional[logging.Handler] = None
        self._lock = threading.Lock()

    @staticmethod
    def _formatter() -> logging.Formatter:
        return logging.Formatter(
            "[%(levelname)s] [%(asctime)s] %(message)s", datefmt="%Y-%m-%d %H:%M:%S"
        )

    # -- configuration ----------------------------------------------------
    def reset_log_level(self, level: LogLevel) -> None:
        self._level = level
        self._logger.setLevel(_LEVEL_MAP[level])

    def reset_log_file(self, path: str) -> None:
        with self._lock:
            if self._file_handler is not None:
                self._logger.removeHandler(self._file_handler)
                self._file_handler.close()
                self._file_handler = None
            if path:
                handler = logging.FileHandler(path)
                handler.setFormatter(self._formatter())
                self._logger.addHandler(handler)
                self._file_handler = handler

    def reset_kill_fatal(self, kill: bool) -> None:
        self._kill_fatal = kill

    @property
    def level(self) -> LogLevel:
        return self._level

    # -- emission ---------------------------------------------------------
    def debug(self, msg: str, *args: Any) -> None:
        self._logger.debug(msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self._logger.info(msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self._logger.error(msg, *args)

    def fatal(self, msg: str, *args: Any) -> None:
        rendered = msg % args if args else msg
        self._logger.critical(rendered)
        if self._kill_fatal:
            if threading.current_thread() is threading.main_thread():
                sys.exit(1)
            # sys.exit in a worker thread raises SystemExit that threading
            # swallows — the process would keep training past a fatal
            # invariant violation. Kill for real (message already flushed
            # through the critical handler above).
            os._exit(1)
        raise FatalError(rendered)


_LOGGER = Logger()


class Log:
    """Static facade (reference ``Log::Info`` etc.)."""

    @staticmethod
    def logger() -> Logger:
        return _LOGGER

    @staticmethod
    def reset_log_level(level: LogLevel) -> None:
        _LOGGER.reset_log_level(level)

    @staticmethod
    def reset_log_level_by_name(name: str) -> None:
        _LOGGER.reset_log_level(_LEVEL_NAMES.get(name.lower(), LogLevel.INFO))

    @staticmethod
    def reset_log_file(path: str) -> None:
        _LOGGER.reset_log_file(path)

    @staticmethod
    def reset_kill_fatal(kill: bool) -> None:
        _LOGGER.reset_kill_fatal(kill)

    @staticmethod
    def debug(msg: str, *args: Any) -> None:
        _LOGGER.debug(msg, *args)

    @staticmethod
    def info(msg: str, *args: Any) -> None:
        _LOGGER.info(msg, *args)

    @staticmethod
    def error(msg: str, *args: Any) -> None:
        _LOGGER.error(msg, *args)

    @staticmethod
    def fatal(msg: str, *args: Any) -> None:
        _LOGGER.fatal(msg, *args)


def check(condition: bool, msg: str = "CHECK failed") -> None:
    """Reference ``CHECK`` macro (``log.h:9-13``)."""
    if not condition:
        Log.fatal(msg)


def check_notnull(value: Any, name: str = "value") -> Any:
    """Reference ``CHECK_NOTNULL`` macro (``log.h:15-18``)."""
    if value is None:
        Log.fatal(f"{name} must not be None")
    return value
