"""multiverso-tpu: a TPU-native distributed ML framework.

A from-scratch re-design of the Multiverso parameter-server framework
(reference: ``dongruiqing/multiverso``) for TPU: parameter tables are
HBM-resident sharded ``jax.Array``s, worker<->server Push/Pull lowers to XLA
collectives over ICI, server-side updaters run as jitted device steps, and
pod topology comes from JAX slice metadata over DCN.

Top-level functions mirror the reference public API
(``include/multiverso/multiverso.h:9-62``): ``init`` / ``shutdown`` /
``barrier`` / ``rank`` / ``size`` / ``num_workers`` / ``num_servers`` /
``worker_id`` / ``server_id`` / ``aggregate``, plus ``create_table``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from . import config, trace
from .config import (define_bool, define_float, define_int, define_string,
                     get_flag, parse_cmd_flags, set_flag)
from .dashboard import (Counter, Dashboard, Gauge, Histogram,
                        MetricsExporter, Monitor, Timer, monitor,
                        profile_trace, render_prometheus)
from .log import Log, LogLevel, check, check_notnull
from .quantization import SparseFilter
from .runtime import Session
from .topology import (SERVER_AXIS, SEQ_AXIS, WORKER_AXIS, make_mesh,
                       net_bind, net_connect, sharding_for)

__version__ = "0.1.0"


def init(argv: Optional[Sequence[str]] = None, sync: Optional[bool] = None,
         updater: Optional[str] = None, **flags: Any) -> List[str]:
    """Initialise the process (``MV_Init``, ``src/multiverso.cpp:10``)."""
    if sync is not None:
        set_flag("sync", bool(sync))
    if updater is not None:
        set_flag("updater_type", updater)
    for key, value in flags.items():
        set_flag(key, value)
    return Session.get().start(argv)


def shutdown(finalize: bool = True) -> None:
    """``MV_ShutDown`` (``src/multiverso.cpp:14``)."""
    Session.get().stop(finalize)


def barrier() -> None:
    """``MV_Barrier`` (``src/multiverso.cpp:19``)."""
    Session.get().barrier()


def rank() -> int:
    return Session.get().rank


def size() -> int:
    return Session.get().size


def num_workers() -> int:
    return Session.get().num_workers


def num_servers() -> int:
    return Session.get().num_servers


def worker_id() -> int:
    return Session.get().worker_id


def server_id() -> int:
    return Session.get().server_id


def is_worker() -> bool:
    return Session.get().is_worker()


def is_server() -> bool:
    return Session.get().is_server()


def aggregate(data):
    """``MV_Aggregate`` allreduce of a host buffer (``src/multiverso.cpp:47``)."""
    return Session.get().aggregate(data)


def session() -> Session:
    return Session.get()


def create_table(kind: str, *args: Any, **kwargs: Any):
    """``MV_CreateTable`` factory (``include/multiverso/multiverso.h:31-37``).

    ``kind`` is one of ``array`` / ``matrix`` / ``kv`` / ``sparse`` / ``ftrl``.
    """
    from . import tables

    factory = {
        "array": tables.ArrayTable,
        "matrix": tables.MatrixTable,
        "kv": tables.KVTable,
        "sparse": tables.SparseTable,
        "ftrl": tables.FTRLTable,
    }
    try:
        cls = factory[kind]
    except KeyError:
        Log.fatal(f"unknown table kind {kind!r}; expected one of {sorted(factory)}")
    table = cls(*args, **kwargs)
    barrier()  # MV_CreateTable barriers after creation (multiverso.h:35)
    return table
