"""Write-ahead delta journal: per-rank durable log of acknowledged applies.

The reference treats fault tolerance as open design space (SURVEY §5.3);
Li et al.'s Parameter Server (OSDI '14) makes *logged, replayable
updates* the core of PS recovery, and Check-N-Run (NSDI '22) shows
production recsys treating checkpoint + incremental-delta durability as
a first-class serving dependency. This module is that layer for the
table stack: every acknowledged LOCAL apply (``table.add`` returning is
the acknowledgment) appends one record here *before* the caller gets
its handle back, so a trainer crash loses nothing it acknowledged —
``io/checkpoint.py`` restores the newest complete checkpoint and
replays the journal records past its per-table version watermark to
reach the **exact** pre-crash version.

On-disk format (little-endian throughout):

* segment files ``wal-r<rank>-<index>.seg``: an 12-byte header
  (``MVWAL1\\n\\0`` magic + u32 rank) followed by records;
* record: ``<u32 crc><u32 length><i32 table_id><u64 version>`` +
  ``length`` payload bytes. The crc32 covers the header-sans-crc AND
  the payload, so a torn header, torn payload, or bit flip all read as
  one thing: a bad record. The payload reuses the async-PS wire
  framing (:func:`multiverso_tpu.parallel.async_ps._serialize` — kind,
  table_id, AddOption scalars, arrays, epoch, version), so a journal
  record and a bus record are the same bytes.

Recovery contract (property-tested over random truncation points):
:func:`recover` scans segments in order and truncates **at the first
torn/bad-CRC record** — the file is physically truncated there and any
later segments are deleted, so recovery is deterministic and a later
replay never re-reads ambiguous bytes. A fresh :class:`DeltaWAL` runs
recovery before opening a NEW segment (a restarted incarnation never
appends into the torn file).

Bounded replay: after a successful checkpoint save the ``Autosaver``
calls :meth:`DeltaWAL.reap` with the checkpoint's per-table version
watermarks; closed segments whose every record is covered by the
watermark are deleted, so replay work is bounded by one checkpoint
interval and reaped segments are never re-read.

Locking: appends serialize under the journal's own lock; the journal
is NEVER touched under any table lock (the fsync/write are blocking IO
— locklint LK203), so the apply hot path orders as apply -> release
table lock -> journal. Replay therefore orders records by their
post-apply version per table (concurrent local adders may journal out
of apply order); a version GAP — possible only when a crash lands
between two racing adders' journal appends — stops that table's replay
at the gap, loudly, rather than applying a delta against the wrong
predecessor state. The single-writer trainer (the online-learning
deployment this protects) never produces gaps.
"""

from __future__ import annotations

import os
import re
import struct
import zlib
from ..analysis import lockwatch
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..log import Log

_MAGIC = b"MVWAL1\n\x00"
_SEG_HEADER = struct.Struct("<I")           # rank
_REC = struct.Struct("<IIiQ")               # crc, length, table_id, version
_REC_TAIL = struct.Struct("<IiQ")           # length, table_id, version (crc'd)
_SEG_RE = re.compile(r"^wal-r(\d+)-(\d+)\.seg$")


def _record_crc(length: int, table_id: int, version: int,
                payload: bytes) -> int:
    crc = zlib.crc32(_REC_TAIL.pack(length, table_id, version))
    return zlib.crc32(payload, crc)


def _segment_name(rank: int, index: int) -> str:
    return f"wal-r{rank:03d}-{index:06d}.seg"


def segments(directory: str, rank: int) -> List[Tuple[int, str]]:
    """(index, path) of this rank's journal segments, ascending."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _SEG_RE.match(name)
        if m and int(m.group(1)) == rank:
            out.append((int(m.group(2)), os.path.join(directory, name)))
    return sorted(out)


def _walk_segment(path: str, read_payloads: bool = True
                  ) -> Tuple[List[Tuple[int, int, int, Optional[bytes]]],
                             Optional[int]]:
    """THE one segment walker: ``([(offset, table_id, version,
    payload-or-None), ...], bad_offset)`` with ``bad_offset`` the first
    torn/bad record (None = clean to EOF). ``read_payloads=False``
    seeks past payloads without reading or CRC-checking them — the
    reaping path's O(records) mode; recovery/replay read + verify."""
    records: List[Tuple[int, int, int, Optional[bytes]]] = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC) + _SEG_HEADER.size)
        if (len(head) < len(_MAGIC) + _SEG_HEADER.size
                or head[:len(_MAGIC)] != _MAGIC):
            return records, 0
        while True:
            offset = f.tell()
            hdr = f.read(_REC.size)
            if not hdr:
                return records, None          # clean EOF at a boundary
            if len(hdr) < _REC.size:
                return records, offset        # torn header
            crc, length, table_id, version = _REC.unpack(hdr)
            if read_payloads:
                payload = f.read(length)
                if len(payload) < length:
                    return records, offset    # torn payload
                if _record_crc(length, table_id, version,
                               payload) != crc:
                    return records, offset    # bit rot / seeded bad crc
            else:
                payload = None
                if size - f.tell() < length:
                    return records, offset    # torn payload
                f.seek(length, 1)
            records.append((offset, table_id, version, payload))


def _scan_segment(path: str) -> Tuple[List[Tuple[int, int, bytes]],
                                      Optional[int]]:
    """Read+CRC walk: ``([(table_id, version, payload), ...],
    bad_offset)`` — recovery/replay's view."""
    records, bad = _walk_segment(path, read_payloads=True)
    return [(t, v, p) for _, t, v, p in records], bad


def _scan_segment_headers(path: str) -> Tuple[List[Tuple[int, int]],
                                              Optional[int]]:
    """Header-only walk: ``([(table_id, version), ...], bad_offset)``
    with payloads seeked past, never read or CRC'd — the reaping
    path's scan (corruption detection is recovery's job, and a
    checkpoint-covered segment is reapable regardless of payload
    rot)."""
    records, bad = _walk_segment(path, read_payloads=False)
    return [(t, v) for _, t, v, _ in records], bad


def recover(directory: str, rank: int = 0) -> Dict[str, int]:
    """Deterministic torn-tail recovery: truncate the journal at the
    FIRST torn/bad-CRC record and drop every later segment. Returns
    ``{"segments", "records", "truncated_segments", "truncated_at"}``
    (``truncated_at`` = -1 when the journal was clean)."""
    segs = segments(directory, rank)
    stats = {"segments": len(segs), "records": 0,
             "truncated_segments": 0, "truncated_at": -1}
    for i, (index, path) in enumerate(segs):
        records, bad = _scan_segment(path)
        stats["records"] += len(records)
        if bad is None:
            continue
        Log.error("wal: torn/bad record in %s at byte %d; truncating "
                  "there and dropping %d later segment(s)",
                  path, bad, len(segs) - i - 1)
        # a truncation that leaves no records (bad header, or the bad
        # record was the segment's first) removes the file outright
        empty = bad <= len(_MAGIC) + _SEG_HEADER.size
        if empty:
            os.remove(path)
        else:
            with open(path, "r+b") as f:
                f.truncate(bad)
        for _, later in segs[i + 1:]:
            os.remove(later)
        stats["truncated_at"] = bad
        # segments REMOVED: all later ones, plus this one when nothing
        # of it was left to keep
        stats["truncated_segments"] = (len(segs) - i if empty
                                       else len(segs) - i - 1)
        break
    return stats


def iter_records(directory: str, rank: int = 0
                 ) -> Iterator[Tuple[int, int, bytes, int]]:
    """Yield ``(table_id, version, payload, segment_index)`` across all
    segments in order, stopping (loudly) at the first bad record — run
    :func:`recover` first to make the stop a physical truncation."""
    for index, path in segments(directory, rank):
        records, bad = _scan_segment(path)
        for table_id, version, payload in records:
            yield table_id, version, payload, index
        if bad is not None:
            Log.error("wal: stopping read at torn record (%s byte %d); "
                      "records after it are discarded", path, bad)
            return


class DeltaWAL:
    """Append side of the journal (one per process rank).

    Construction RUNS RECOVERY (torn-tail truncation) and then opens a
    fresh segment — a restarted incarnation never appends into a file a
    crash may have torn.

    Concurrency/locking: appends go through an ``O_APPEND`` fd with one
    ``os.write`` per record — the kernel serializes the append offset,
    so racing appenders (and a racing rotation's old-fd stragglers)
    produce whole, non-interleaved records in SOME order; replay
    re-orders by version. The journal's lock guards only in-memory
    bookkeeping (fd swap, counters) — **no file IO ever runs under it**
    (LK203), and none of this ever runs under a table lock (the table
    layer orders apply -> unlock -> journal).
    """

    def __init__(self, directory: str, rank: int = 0,
                 segment_bytes: int = 64 << 20,
                 fsync: bool = False) -> None:
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.rank = int(rank)
        self.segment_bytes = max(int(segment_bytes), 1024)
        self.fsync = bool(fsync)
        self._lock = lockwatch.lock("io.DeltaWAL._lock")
        self.appended = 0
        self.rotations = 0
        self.reaped_segments = 0
        self.recovery = recover(directory, self.rank)
        segs = segments(directory, self.rank)
        self._index = (segs[-1][0] + 1) if segs else 0
        self._fd: Optional[int] = None
        self._path = ""
        self._size = 0
        self._rotating = False
        # per-fd in-flight writer refcounts: a racing append captures
        # the current fd under the lock, and closing that fd under its
        # os.write would land the record in whatever file reuses the
        # descriptor next — so a rotated-out fd is only closed once its
        # last in-flight writer has left (O_APPEND keeps the straggler
        # record valid in the old segment; replay orders by version)
        self._fd_refs: Dict[int, int] = {}
        self._retired_fds: set = set()
        self._fd, self._path, self._size = self._open_segment(self._index)

    # -- write path --------------------------------------------------------
    def _open_segment(self, index: int):
        path = os.path.join(self.directory,
                            _segment_name(self.rank, index))
        fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                     0o644)
        size = os.path.getsize(path)
        if size == 0:
            header = _MAGIC + _SEG_HEADER.pack(self.rank)
            os.write(fd, header)
            size = len(header)
        return fd, path, size

    def append(self, table_id: int, version: int, payload: bytes) -> None:
        """Durably journal one applied record (post-apply ``version``)."""
        crc = _record_crc(len(payload), int(table_id), int(version),
                          payload)
        rec = _REC.pack(crc, len(payload), int(table_id),
                        int(version)) + payload
        with self._lock:
            fd = self._fd
            if fd is not None:
                self._fd_refs[fd] = self._fd_refs.get(fd, 0) + 1
                self._size += len(rec)
                self.appended += 1
                rotate = self._size >= self.segment_bytes
        if fd is None:
            Log.fatal("wal: append after close()")
        try:
            # one O_APPEND write per record: atomic end-of-file
            # positioning, no byte interleave with racing appenders
            os.write(fd, rec)
            if self.fsync:
                os.fsync(fd)
        finally:
            self._release_fd(fd)
        if rotate:
            self._rotate(fd)

    def _release_fd(self, fd: int) -> None:
        """Drop one in-flight writer; close the fd if it was rotated
        out and this writer was the last one on it."""
        to_close = None
        with self._lock:
            self._fd_refs[fd] -= 1
            if self._fd_refs[fd] == 0 and fd in self._retired_fds:
                self._retired_fds.discard(fd)
                del self._fd_refs[fd]
                to_close = fd
        if to_close is not None:
            os.close(to_close)

    def _rotate(self, old_fd: int) -> None:
        with self._lock:
            if (self._fd != old_fd or self._size < self.segment_bytes
                    or self._rotating):
                return      # a racing appender is already rotating / did
            # claim the rotation UNDER the lock: two appenders passing
            # the size check concurrently must not both open (and
            # double-header) the same next segment
            self._rotating = True
            next_index = self._index + 1
        fd, path, size = self._open_segment(next_index)
        to_close = None
        with self._lock:
            self._fd, self._path, self._size = fd, path, size
            self._index = next_index
            self.rotations += 1
            self._rotating = False
            if self._fd_refs.get(old_fd, 0) == 0:
                self._fd_refs.pop(old_fd, None)
                to_close = old_fd       # no writer in flight: close now
            else:
                self._retired_fds.add(old_fd)   # last writer closes it
        if to_close is not None:
            os.close(to_close)

    def close(self) -> None:
        to_close = []
        with self._lock:
            fd, self._fd = self._fd, None
            if fd is not None:
                if self._fd_refs.get(fd, 0) == 0:
                    self._fd_refs.pop(fd, None)
                    to_close.append(fd)
                else:
                    # a straggling append still writes; its release
                    # closes the fd (teardown order makes this rare)
                    self._retired_fds.add(fd)
            for r in list(self._retired_fds):
                if self._fd_refs.get(r, 0) == 0:
                    self._retired_fds.discard(r)
                    self._fd_refs.pop(r, None)
                    to_close.append(r)
        for f in to_close:
            if self.fsync:
                os.fsync(f)
            os.close(f)

    # -- bounded replay ----------------------------------------------------
    def reap(self, watermarks: Dict[int, int]) -> List[str]:
        """Delete CLOSED segments fully covered by a completed
        checkpoint's per-table version watermarks (``{table_id:
        version}``). The active segment is never reaped; a segment
        holding any record above its table's watermark (or for a table
        the checkpoint does not cover) is kept whole — replay re-reads
        whole segments, so reaping is all-or-nothing per segment."""
        reaped: List[str] = []
        active = _segment_name(self.rank, self._index)
        for index, path in segments(self.directory, self.rank):
            if os.path.basename(path) == active:
                continue
            # header-only walk: reaping must not re-read (and crc) every
            # retained payload byte on the training thread per checkpoint
            records, bad = _scan_segment_headers(path)
            if bad is not None:
                continue            # recovery's business, not reaping's
            covered = all(
                version <= watermarks.get(table_id, -1)
                for table_id, version in records)
            if covered:
                os.remove(path)
                reaped.append(path)
        if reaped:
            with self._lock:
                self.reaped_segments += len(reaped)
            Log.info("wal: reaped %d segment(s) covered by the "
                     "checkpoint watermark", len(reaped))
        return reaped

    # -- chaos hooks (serving/faultinject.py wal_torn_tail/wal_bad_crc) ----
    def corrupt_tail(self, kind: str) -> None:
        """Stage the crash-corruption the recovery path must survive:
        ``torn_tail`` halves the last record's bytes (a write the crash
        interrupted), ``bad_crc`` flips a payload bit (rot/partial
        overwrite). Test/chaos-only by construction; races with live
        appends are the caller's problem (the next act is a kill)."""
        path = self._path
        records, bad = _walk_segment(path, read_payloads=False)
        if not records or bad is not None:
            return
        last_off = records[-1][0]            # the final record's offset
        if kind == "torn_tail":
            end = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(last_off + max((end - last_off) // 2, 1))
        elif kind == "bad_crc":
            with open(path, "r+b") as f:
                f.seek(last_off + _REC.size)
                b = f.read(1)
                f.seek(last_off + _REC.size)
                f.write(bytes([b[0] ^ 0xFF]))
        else:
            Log.fatal(f"wal: unknown corruption kind {kind!r}")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"directory": self.directory, "rank": self.rank,
                    "appended": self.appended,
                    "rotations": self.rotations,
                    "reaped_segments": self.reaped_segments,
                    "active_segment": self._index,
                    "recovery": dict(self.recovery)}


# -- journal hook (tables layer) ---------------------------------------------

def journal_local(sess, table_id: int, kind: int, option,
                  arrays, version: int) -> None:
    """Append one acknowledged local apply to the session's journal
    (no-op without ``-wal``). Runs AFTER the apply released the table
    lock — the journal's own lock is the only one held across the
    write/fsync (LK203)."""
    wal = getattr(sess, "wal", None)
    if wal is None:
        return
    from ..parallel.async_ps import _serialize

    payload = _serialize(kind, table_id, option, arrays,
                         version=int(version))
    wal.append(table_id, int(version), payload)


# -- replay ------------------------------------------------------------------

def replay(directory: str, rank: int = 0, session=None,
           tables: Optional[Dict[int, Any]] = None) -> Dict[str, int]:
    """Replay journal records with ``version > table.version`` into the
    session's tables, per table in version order, reaching the exact
    pre-crash version. Records at or below the table's current version
    (the checkpoint watermark installed by ``restore``) are skipped;
    a version gap stops that table's replay loudly (``gaps``/
    ``dropped`` count it). Returns
    ``{"replayed", "skipped", "gaps", "dropped", "unknown_tables"}``.
    """
    from ..parallel.async_ps import _deserialize
    from ..runtime import Session

    if tables is None:
        sess = session or Session.get()
        tables = {t.table_id: t for t in sess.tables}
    per_table: Dict[int, Dict[int, bytes]] = {}
    stats = {"replayed": 0, "skipped": 0, "gaps": 0, "dropped": 0,
             "unknown_tables": 0}
    for table_id, version, payload, _ in iter_records(directory, rank):
        if table_id not in tables:
            stats["unknown_tables"] += 1
            continue
        bucket = per_table.setdefault(table_id, {})
        if version in bucket:
            Log.error("wal: duplicate version %d for table %d; the "
                      "newer segment's record supersedes", version,
                      table_id)
        bucket[version] = payload
    for table_id in sorted(per_table):
        table = tables[table_id]
        for version in sorted(per_table[table_id]):
            if version <= table.version:
                stats["skipped"] += 1
                continue
            if version != table.version + 1:
                remaining = sum(1 for v in per_table[table_id]
                                if v >= version)
                Log.error("wal: version gap on table %d (have %d, next "
                          "record %d); stopping its replay and dropping "
                          "%d record(s)", table_id, table.version,
                          version, remaining)
                stats["gaps"] += 1
                stats["dropped"] += remaining
                break
            (kind, _, option, arrays, _, _, epoch,
             rec_version) = _deserialize(per_table[table_id][version])
            _apply_record(table, kind, option, arrays, rec_version)
            if table.version != version:
                Log.fatal(f"wal: replay of table {table_id} reached "
                          f"version {table.version}, record said "
                          f"{version} — journal/apply drift")
            stats["replayed"] += 1
    if stats["replayed"] or stats["dropped"]:
        Log.info("wal: replayed %d record(s) past the checkpoint "
                 "watermark (%d skipped, %d dropped)",
                 stats["replayed"], stats["skipped"], stats["dropped"])
    return stats


def _apply_record(table, kind: int, option, arrays,
                  version: int) -> None:
    from ..parallel import async_ps

    if kind == async_ps.DENSE:
        table._apply_dense(
            arrays[0].reshape(table.shape), option)
    elif kind == async_ps.KEYED:
        table._dispatch_keyed(arrays[0], arrays[1], option)
    elif kind == async_ps.KV:
        table._apply_remote_kv(arrays[0], arrays[1])
    elif kind == async_ps.STATE:
        table._install_state_arrays(arrays, version)
    else:
        Log.fatal(f"wal: unknown record kind {kind}")
