"""URI-dispatched binary streams + buffered text reading.

TPU-native equivalent of the reference IO layer
(``include/multiverso/io/io.h:24-130``, ``src/io/local_stream.cpp``,
``src/io/hdfs_stream.cpp`` in the Multiverso reference): ``file://`` URIs map
to local streams; other schemes (``hdfs://`` behind libhdfs in the reference)
raise a clear error unless a handler is registered — cloud storage on TPU VMs
is typically fuse-mounted or handled by tensorstore/orbax (see
``io/checkpoint.py``), so the extension point is a scheme registry.

``write_array``/``read_array`` define the framework's table serialisation
record: little-endian header (dtype tag, ndim, dims) + raw buffer — the
binary Store/Load contract (``table_interface.h:59-66``).
"""

from __future__ import annotations

import io as _io
import os
import struct
from typing import BinaryIO, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from ..log import Log


class URI:
    """Scheme/host/path split (``io.h:24-56``)."""

    def __init__(self, uri: str) -> None:
        self.uri = uri
        if "://" in uri:
            self.scheme, rest = uri.split("://", 1)
            if "/" in rest:
                self.host, path = rest.split("/", 1)
                self.path = "/" + path
            else:
                self.host, self.path = rest, "/"
        else:
            self.scheme, self.host, self.path = "file", "", uri


_OPENERS: Dict[str, Callable[[URI, str], BinaryIO]] = {}


def register_scheme(scheme: str, opener: Callable[[URI, str], BinaryIO]) -> None:
    _OPENERS[scheme] = opener


def _open_local(uri: URI, mode: str) -> BinaryIO:
    if "w" in mode or "a" in mode:
        parent = os.path.dirname(uri.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    return open(uri.path, mode)


register_scheme("file", _open_local)


def open_stream(uri: str, mode: str = "rb") -> BinaryIO:
    """``StreamFactory::GetStream`` (``src/io/io.cpp:8-21``)."""
    parsed = URI(uri)
    opener = _OPENERS.get(parsed.scheme)
    if opener is None and parsed.scheme in ("gs", "memory"):
        # remote backends (tensorstore) register on first use — the
        # reference's compile-time MULTIVERSO_USE_HDFS becomes a lazy import
        from . import remote

        remote.register()
        opener = _OPENERS.get(parsed.scheme)
    elif opener is None and parsed.scheme == "hdfs":
        # WebHDFS backend (fsspec) — the JVM-free hdfs:// analogue
        from . import hdfs

        hdfs.register()
        opener = _OPENERS.get(parsed.scheme)
    if opener is None:
        Log.fatal(f"no stream handler for scheme {parsed.scheme!r} ({uri})")
    if "b" not in mode:
        mode += "b"
    return opener(parsed, mode)


def is_remote(path: str) -> bool:
    """True when ``path`` is a non-file URI (no local mkdir/exists)."""
    return URI(path).scheme != "file"


class TextReader:
    """Buffered line reader (``io.h:114-130``)."""

    def __init__(self, uri: str, buf_size: int = 1 << 20) -> None:
        self._stream = open_stream(uri, "rb")
        self._reader = _io.BufferedReader(self._stream, buffer_size=buf_size)

    def get_line(self) -> Optional[str]:
        line = self._reader.readline()
        if not line:
            return None
        return line.decode("utf-8", errors="replace").rstrip("\r\n")

    def __iter__(self) -> Iterator[str]:
        while True:
            line = self.get_line()
            if line is None:
                return
            yield line

    def close(self) -> None:
        self._reader.close()

    def __enter__(self) -> "TextReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- binary array records ---------------------------------------------------

_MAGIC = b"MVTA"


def write_array(stream: BinaryIO, array: np.ndarray) -> None:
    array = np.ascontiguousarray(array)
    # extension dtypes (ml_dtypes bfloat16 etc.) stringify as opaque '|V2';
    # their NAME round-trips (resolved via ml_dtypes on read)
    tag = (array.dtype.str if array.dtype.kind != "V" else array.dtype.name)
    dtype_tag = tag.encode("ascii")
    stream.write(_MAGIC)
    stream.write(struct.pack("<B", len(dtype_tag)))
    stream.write(dtype_tag)
    stream.write(struct.pack("<B", array.ndim))
    for dim in array.shape:
        stream.write(struct.pack("<q", dim))
    stream.write(array.tobytes())


def _read_record_header(stream: BinaryIO):
    """``(dtype, shape)`` of the next ``write_array`` record, or None
    at a clean EOF. THE one framing parser — ``read_array`` (load) and
    ``validate_record_stream`` (torn-file detection) both ride it, so
    the format cannot drift between them. Raises ValueError on a
    malformed/truncated header."""
    magic = stream.read(4)
    if not magic:
        return None
    if magic != _MAGIC:
        raise ValueError(f"bad table record magic {magic!r}")
    head = stream.read(1)
    if len(head) < 1:
        raise ValueError("truncated record header")
    (tag_len,) = struct.unpack("<B", head)
    tag = stream.read(tag_len)
    ndim_b = stream.read(1)
    if len(tag) < tag_len or len(ndim_b) < 1:
        raise ValueError("truncated record header")
    (ndim,) = struct.unpack("<B", ndim_b)
    dims = stream.read(8 * ndim)
    if len(dims) < 8 * ndim:
        raise ValueError("truncated record header")
    shape = (tuple(struct.unpack(f"<{ndim}q", dims)) if ndim else ())
    try:
        dtype = np.dtype(tag.decode("ascii"))
    except (TypeError, UnicodeDecodeError):
        try:
            import ml_dtypes   # extension dtype written by name

            dtype = np.dtype(getattr(ml_dtypes,
                                     tag.decode("ascii", "replace")))
        except (AttributeError, ImportError, TypeError):
            raise ValueError(f"unknown dtype tag {tag!r}") from None
    return dtype, shape


def validate_record_stream(path: str) -> Optional[str]:
    """Cheap completeness check of a local ``write_array`` record file.

    Walks the record headers (via the shared parser) and verifies every
    payload fits inside the file WITHOUT loading the arrays — the
    checkpoint layer's torn-file detector (a crash mid-``table.store``
    leaves a truncated payload or header). Returns None when complete,
    else a short reason."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            while True:
                pos = f.tell()
                try:
                    header = _read_record_header(f)
                except ValueError as exc:
                    return f"{exc} at byte {pos}"
                if header is None:
                    return None                   # clean EOF
                dtype, shape = header
                count = int(np.prod(shape)) if shape else 1
                need = count * dtype.itemsize
                if size - f.tell() < need:
                    return (f"truncated payload at byte {pos} "
                            f"(record needs {need} bytes)")
                f.seek(need, 1)
    except OSError as exc:
        return str(exc)


def read_array(stream: BinaryIO) -> np.ndarray:
    try:
        header = _read_record_header(stream)
    except ValueError as exc:
        Log.fatal(f"bad table record: {exc}")
    if header is None:
        Log.fatal("bad table record: unexpected end of stream")
    dtype, shape = header
    count = int(np.prod(shape)) if shape else 1
    buf = stream.read(count * dtype.itemsize)
    return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
