"""``hdfs://`` stream backend over WebHDFS (fsspec, pure HTTP).

The reference's HDFS stream binds libhdfs through a JVM
(``src/io/hdfs_stream.cpp``, ``include/multiverso/io/hdfs_stream.h:24`` in
the Multiverso reference, gated by ``MULTIVERSO_USE_HDFS``). TPU VMs ship
no JVM, so the native analogue is the WebHDFS REST gateway every namenode
exposes (``dfs.webhdfs.enabled``): fsspec's ``WebHDFS`` filesystem speaks
it with plain ``requests`` — no new dependencies.

URI form: ``hdfs://namenode[:port]/path`` (port defaults to fsspec's
WebHDFS default). Authentication: set ``MV_HDFS_USER`` for simple
user.name auth; Kerberos deployments use the standard fsspec config
mechanisms.

Stream semantics match the other remote backends (``io/remote.py``):
writes buffer locally and commit ONE file at close — the same
commit-on-close the reference's HDFS stream performs on ``Flush`` — and a
``with`` block that raises mid-write aborts instead of publishing a
truncated file. Reads fetch the file once and serve from memory.

Tested against a hermetic in-process WebHDFS protocol double
(``tests/test_hdfs_stream.py``) — the same strategy the reference uses of
testing streams without a live cluster.
"""

from __future__ import annotations

import io
import os
from typing import BinaryIO

from ..log import Log


def _fs_for(host_port: str):
    """fsspec WebHDFS filesystem for ``namenode[:port]`` (instance-cached
    by fsspec)."""
    from fsspec.implementations.webhdfs import WebHDFS

    if not host_port:
        Log.fatal("hdfs:// URI needs a namenode host: hdfs://host[:port]/path")
    host, _, port = host_port.partition(":")
    kwargs = {"host": host}
    if port:
        kwargs["port"] = int(port)
    user = os.environ.get("MV_HDFS_USER")
    if user:
        kwargs["user"] = user
    if os.environ.get("MV_HDFS_USE_HTTPS", "") in ("1", "true"):
        kwargs["use_https"] = True
    return WebHDFS(**kwargs)


class _HdfsReadStream(io.BytesIO):
    """Whole-file read stream (reference HDFSStream read mode)."""

    def __init__(self, fs, path: str, uri: str) -> None:
        try:
            data = fs.cat_file(path)
        except FileNotFoundError:
            raise FileNotFoundError(uri)
        except Exception as exc:
            raise FileNotFoundError(f"{uri}: {exc}") from exc
        super().__init__(bytes(data))


class _HdfsWriteStream(io.BytesIO):
    """Buffered write stream; commits ONE file at close (the reference
    HDFS stream's commit-on-Flush), with the abort-on-exception contract
    of the object-store streams."""

    def __init__(self, fs, path: str, uri: str) -> None:
        super().__init__()
        self._fs = fs
        self._path = path
        self._uri = uri
        self._committed = False
        self._aborted = False

    def abort(self) -> None:
        """Discard the buffer: a subsequent close() uploads nothing."""
        self._aborted = True

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._aborted = True
        return super().__exit__(exc_type, exc, tb)

    def close(self) -> None:
        if not self._committed and not self._aborted and not self.closed:
            self._fs.pipe_file(self._path, self.getvalue())
            self._committed = True
        super().close()


def open_hdfs(uri, mode: str) -> BinaryIO:
    """Scheme opener signature for :func:`io.stream.register_scheme`."""
    fs = _fs_for(uri.host)
    if "w" in mode:
        return _HdfsWriteStream(fs, uri.path, uri.uri)
    if "a" in mode:
        Log.fatal(f"append mode unsupported on the hdfs:// backend: "
                  f"{uri.uri}")
    return _HdfsReadStream(fs, uri.path, uri.uri)


# -- checkpoint helpers (same trio io/remote.py provides for gs://) --------

def exists(uri_str: str) -> bool:
    from .stream import URI

    uri = URI(uri_str)
    try:
        return bool(_fs_for(uri.host).exists(uri.path))
    except FileNotFoundError:
        # only a definite "not there" reads as absence; a transient
        # WebHDFS/namenode failure must NOT — restore_latest probes
        # manifests through here, and failure-as-absence would silently
        # skip a valid checkpoint
        return False


def list_subdirs_with(root_uri: str, filename: str):
    """Immediate subdirectory names under ``root_uri`` containing
    ``filename`` (checkpoint-step discovery)."""
    from .stream import URI

    uri = URI(root_uri)
    fs = _fs_for(uri.host)
    names = []
    try:
        entries = fs.ls(uri.path, detail=True)
    except FileNotFoundError:
        return []
    for e in entries:
        if e.get("type") == "directory":
            name = e["name"].rstrip("/").rsplit("/", 1)[-1]
            if fs.exists(e["name"].rstrip("/") + "/" + filename):
                names.append(name)
    return sorted(names)


def delete_prefix(dir_uri: str) -> None:
    """Delete the directory tree (remote checkpoint pruning)."""
    from .stream import URI

    uri = URI(dir_uri)
    try:
        _fs_for(uri.host).rm(uri.path, recursive=True)
    except FileNotFoundError:
        pass


def register() -> None:
    from .stream import register_scheme

    register_scheme("hdfs", open_hdfs)
