"""IO: URI streams, text reading, table/session checkpointing."""

from .stream import TextReader, URI, open_stream, read_array, register_scheme, write_array

__all__ = [
    "TextReader",
    "URI",
    "open_stream",
    "read_array",
    "register_scheme",
    "write_array",
]
