"""Session-level checkpoint/resume.

The reference leaves checkpointing to each table's ``Serializable``
Store/Load (``include/multiverso/table_interface.h:59-66`` in the Multiverso
reference) with no automatic driver (the intended ``MV_LoadTable`` driver
survives only as comments, ``Test/main.cpp:293-331``). Here the driver
exists: ``save``/``restore`` walk the session's table registry and write one
binary record per table plus a JSON manifest. Rank 0 writes; every process
restores (single-controller JAX reloads give every process the same state).

For large-model checkpointing with per-shard parallel IO, use
:func:`save_orbax`/:func:`restore_orbax` below (orbax-backed, with the same
manifest/type checks and a stream fallback for non-array tables);
``save``/``restore`` are the framework-native lightweight path matching
reference semantics.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from ..analysis import lockwatch
import time
from typing import List, Optional, Tuple

from ..log import Log
from ..runtime import Session
from .stream import is_remote, open_stream


def _join(directory: str, name: str) -> str:
    """Path join that preserves URI schemes (``gs://...`` stays a URI)."""
    return directory.rstrip("/") + "/" + name if is_remote(directory) \
        else os.path.join(directory, name)

_MANIFEST = "manifest.json"
_STEP_DIR = re.compile(r"^step_(\d+)$")


def save(directory: str, session: Optional[Session] = None
         ) -> Optional[dict]:
    """Store every registered table under ``directory``.

    The manifest records each table's version WATERMARK — the version
    of the exact bytes stored, captured atomically with the copy
    (``table.store`` returns it) — which is what bounds WAL replay:
    ``restore`` re-installs the watermark and ``restore_latest``
    replays only journal records past it. Returns the manifest on
    rank 0 (None elsewhere)."""
    sess = session or Session.get()
    if not sess.started:
        Log.fatal("save() requires an initialised session")
    sess.barrier()
    manifest = None
    if sess.rank == 0:
        if not is_remote(directory):
            os.makedirs(directory, exist_ok=True)
        manifest = {"version": 1, "tables": []}
        for table in sess.tables:
            name = f"table_{table.table_id}.bin"
            with open_stream(_join(directory, name), "wb") as stream:
                watermark = table.store(stream)
            manifest["tables"].append({
                "id": table.table_id,
                "type": type(table).__name__,
                "name": getattr(table, "name", ""),
                "file": name,
                "version": (int(watermark) if watermark is not None
                            else None),
            })
        with open_stream(_join(directory, _MANIFEST), "wb") as f:
            f.write(json.dumps(manifest, indent=2).encode("utf-8"))
        Log.info("checkpoint saved: %d table(s) -> %s", len(sess.tables), directory)
    sess.barrier()
    return manifest


def restore(directory: str, session: Optional[Session] = None) -> None:
    """Load every registered table from ``directory`` (ids must match the
    creation order, as in the reference's table-id registry)."""
    sess = session or Session.get()
    if not sess.started:
        Log.fatal("restore() requires an initialised session")
    manifest_path = _join(directory, _MANIFEST)
    try:
        with open_stream(manifest_path, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        Log.fatal(f"no checkpoint manifest at {manifest_path}")
    by_id = {entry["id"]: entry for entry in manifest["tables"]}
    for table in sess.tables:
        entry = by_id.get(table.table_id)
        if entry is None:
            Log.fatal(f"checkpoint missing table id {table.table_id}")
        if entry["type"] != type(table).__name__:
            Log.fatal(
                f"checkpoint table {table.table_id} is {entry['type']}, "
                f"session has {type(table).__name__}")
        with open_stream(_join(directory, entry["file"]), "rb") as stream:
            table.load(stream)
        if entry.get("version") is not None:
            # install the manifest's version WATERMARK: load() bumped
            # the local counter, but these bytes ARE the watermarked
            # state — WAL replay targets version > watermark and must
            # land on the exact pre-crash version
            with table._lock:
                table.version = int(entry["version"])
    Log.info("checkpoint restored: %d table(s) <- %s", len(sess.tables), directory)


def save_orbax(directory: str, session: Optional[Session] = None) -> None:
    """Orbax-backed checkpoint: per-shard parallel IO for array tables.

    The native :func:`save` funnels every table through a rank-0 host
    buffer; this path hands the HBM-resident sharded ``jax.Array``s to
    orbax's ``StandardCheckpointer`` (each host writes its own shards —
    the right tool once tables stop fitting one host). Non-array tables
    (KV) fall back to their ``Serializable`` stream records inside the
    same directory.
    """
    import orbax.checkpoint as ocp

    sess = session or Session.get()
    if not sess.started:
        Log.fatal("save_orbax() requires an initialised session")
    directory = os.path.abspath(directory)
    sess.barrier()
    arrays = {}
    manifest = {"version": 1, "format": "orbax", "tables": []}
    for table in sess.tables:
        entry = {"id": table.table_id, "type": type(table).__name__,
                 "name": getattr(table, "name", "")}
        if getattr(table, "array", None) is not None:
            arrays[f"table_{table.table_id}"] = table.array
            entry["storage"] = "orbax"
        else:
            path = os.path.join(directory, f"table_{table.table_id}.bin")
            if sess.rank == 0:
                os.makedirs(directory, exist_ok=True)
                with open_stream(path, "wb") as stream:
                    table.store(stream)
            entry["storage"] = "stream"
            entry["file"] = os.path.basename(path)
        manifest["tables"].append(entry)
    if arrays:   # orbax rejects empty items (all-KV sessions have none)
        with ocp.StandardCheckpointer() as checkpointer:
            checkpointer.save(os.path.join(directory, "arrays"), arrays,
                              force=True)
            checkpointer.wait_until_finished()
    if sess.rank == 0:
        with open(os.path.join(directory, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
    sess.barrier()
    Log.info("orbax checkpoint saved: %d table(s) -> %s",
             len(sess.tables), directory)


def restore_orbax(directory: str, session: Optional[Session] = None) -> None:
    """Restore a :func:`save_orbax` checkpoint (sharded in-place reads)."""
    import jax
    import orbax.checkpoint as ocp

    sess = session or Session.get()
    if not sess.started:
        Log.fatal("restore_orbax() requires an initialised session")
    directory = os.path.abspath(directory)
    manifest_path = os.path.join(directory, _MANIFEST)
    if not os.path.exists(manifest_path):
        Log.fatal(f"no checkpoint manifest at {manifest_path}")
    with open(manifest_path) as f:
        manifest = json.load(f)
    by_id = {entry["id"]: entry for entry in manifest["tables"]}
    targets = {}
    array_tables = {}
    for table in sess.tables:
        entry = by_id.get(table.table_id)
        if entry is None:
            Log.fatal(f"checkpoint missing table id {table.table_id}")
        if entry["type"] != type(table).__name__:
            Log.fatal(
                f"checkpoint table {table.table_id} is {entry['type']}, "
                f"session has {type(table).__name__}")
        if entry.get("storage") == "orbax":
            key = f"table_{table.table_id}"
            targets[key] = jax.ShapeDtypeStruct(
                table.array.shape, table.array.dtype,
                sharding=table.array.sharding)
            array_tables[key] = table
        else:
            with open_stream(os.path.join(directory, entry["file"]),
                             "rb") as stream:
                table.load(stream)
    if targets:
        with ocp.StandardCheckpointer() as checkpointer:
            restored = checkpointer.restore(
                os.path.join(directory, "arrays"), targets)
        for key, value in restored.items():
            array_tables[key].set_array(value)
    Log.info("orbax checkpoint restored: %d table(s) <- %s",
             len(sess.tables), directory)


def _step_dirs(root: str) -> List[Tuple[int, str]]:
    """(step, directory-name) of complete checkpoints, ascending by step.

    Directory names are preserved verbatim — zero-padded names like
    ``step_000010`` must restore from their actual path, not a
    reconstructed ``step_10``.
    """
    if is_remote(root):
        from . import remote

        names = remote.list_subdirs_with(root, _MANIFEST)
    elif os.path.isdir(root):
        names = [name for name in os.listdir(root)
                 if os.path.exists(os.path.join(root, name, _MANIFEST))]
    else:
        return []
    found = []
    for name in names:
        m = _STEP_DIR.match(name)
        if m:
            found.append((int(m.group(1)), name))
    return sorted(found)


def list_steps(root: str) -> List[int]:
    """Completed checkpoint steps under ``root``, ascending."""
    return [step for step, _ in _step_dirs(root)]


def verify_step(directory: str) -> Optional[str]:
    """None when ``directory`` holds a complete restorable checkpoint;
    else a short reason (missing/unreadable manifest, missing or
    truncated table file). Local-path checkpoints only — object-store
    checkpoints commit by manifest-last write order and are trusted."""
    if is_remote(directory):
        return None
    manifest_path = _join(directory, _MANIFEST)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError) as exc:
        return f"manifest unreadable ({exc})"
    from .stream import validate_record_stream

    for entry in manifest.get("tables", []):
        name = entry.get("file")
        if name is None:
            continue              # orbax-storage entries verify on load
        path = os.path.join(directory, name)
        if not os.path.exists(path):
            return f"missing table file {name}"
        problem = validate_record_stream(path)
        if problem:
            return f"{name}: {problem}"
    return None


#: stats of the most recent restore_latest WAL replay (None = no replay
#: ran) — benches/tests read it next to the returned step
LAST_WAL_REPLAY: Optional[dict] = None


def restore_latest(root: str, session: Optional[Session] = None,
                   wal_dir: Optional[str] = None,
                   wal_rank: Optional[int] = None) -> Optional[int]:
    """Restore the newest COMPLETE checkpoint under ``root``, then
    replay the write-ahead journal past its version watermarks.

    A torn/incomplete step dir (missing manifest, truncated or missing
    table file — a crash mid-save on a filesystem without atomic
    rename, or a partially-copied archive) is detected BEFORE any table
    is touched and skipped loudly, falling back to the newest complete
    step instead of failing or half-loading.

    WAL replay: with ``wal_dir`` given (or the ``-wal``/``-wal_dir``
    flags set), journal records with version > the restored watermark
    are replayed in version order, reaching the exact pre-crash table
    state; the replay stats land in :data:`LAST_WAL_REPLAY`. Reaped
    segments (``Autosaver`` reaps those a completed checkpoint covers)
    are gone from disk, so replay work is bounded by one checkpoint
    interval.

    Returns the restored step, or None if no restorable checkpoint
    exists (fresh start — the journal, if any, still replays from
    version 0, so a pre-first-checkpoint crash loses nothing).
    """
    global LAST_WAL_REPLAY
    from .. import config

    LAST_WAL_REPLAY = None
    sess = session or Session.get()
    dirs = _step_dirs(root)
    restored = None
    for step, name in reversed(dirs):
        path = _join(root, name)
        problem = verify_step(path)
        if problem is not None:
            Log.error("checkpoint %s is torn/incomplete (%s); falling "
                      "back to the previous complete step", path,
                      problem)
            continue
        restore(path, sess)
        restored = step
        break
    if restored is None and dirs:
        Log.error("no restorable checkpoint under %s (%d torn step "
                  "dir(s) skipped)", root, len(dirs))
    if wal_dir is None and config.get_flag("wal"):
        wal_dir = config.get_flag("wal_dir")
    if wal_dir:
        from . import wal as _wal

        rank = (wal_rank if wal_rank is not None
                else (sess.rank if sess.started else 0))
        LAST_WAL_REPLAY = _wal.replay(wal_dir, rank, session=sess)
    return restored


class Autosaver:
    """Periodic checkpointing with retention — the automatic trigger the
    reference reserved but never implemented (``Test/main.cpp:293-331``
    comments; SURVEY §5.4 "not wired to any automatic trigger").

    Call :meth:`step` from the training loop; every ``every_steps`` steps
    (and/or ``every_seconds`` wall-clock) it writes ``root/step_N`` and
    prunes to the ``keep`` newest. Writes are atomic at the directory level
    (written to ``.tmp`` then renamed) so a crash mid-save never corrupts
    the latest restorable checkpoint.
    """

    def __init__(self, root: str, every_steps: int = 0,
                 every_seconds: float = 0.0, keep: int = 3,
                 session: Optional[Session] = None) -> None:
        if every_steps <= 0 and every_seconds <= 0:
            Log.fatal("Autosaver needs every_steps and/or every_seconds > 0")
        sess = session or Session.get()
        if every_seconds > 0 and sess.started and sess.size > 1:
            # save() is collective (barriers); a rank-local wall clock lets
            # processes disagree on whether a save is due and deadlock.
            Log.fatal("Autosaver: every_seconds is rank-local and unsafe in "
                      "multi-process runs — use every_steps (deterministic "
                      "across ranks)")
        self._root = root
        self._every_steps = every_steps
        self._every_seconds = every_seconds
        self._keep = max(keep, 1)
        self._session = session
        self._last_time = time.monotonic()
        self._lock = lockwatch.lock("io.Autosaver._lock")

    def step(self, step: int) -> bool:
        """Maybe checkpoint at ``step``; returns True if a save happened."""
        due = (self._every_steps > 0 and step > 0
               and step % self._every_steps == 0)
        if not due and self._every_seconds > 0:
            sess = self._session or Session.get()
            if sess.started and sess.size > 1:
                # checked here (not just __init__) because the session may
                # start after construction; fails on the FIRST step, before
                # rank-local clocks can disagree and deadlock the collective
                # save. every_steps-triggered saves are deterministic and
                # stay allowed.
                Log.fatal("Autosaver: every_seconds is rank-local and "
                          "unsafe in multi-process runs — use every_steps")
            due = time.monotonic() - self._last_time >= self._every_seconds
        if not due:
            return False
        self.save_now(step)
        return True

    def save_now(self, step: int) -> None:
        with self._lock:
            sess = self._session or Session.get()
            final = _join(self._root, f"step_{step}")
            if is_remote(self._root):
                # object stores have no atomic rename; the manifest is
                # written LAST by save() and _step_dirs only counts
                # manifest-bearing dirs, so manifest-commit is the atomic
                # point
                manifest = save(final, sess)
                if sess.rank == 0:
                    self._prune()
            else:
                tmp = final + ".tmp"
                if os.path.isdir(tmp):
                    shutil.rmtree(tmp)
                manifest = save(tmp, sess)
                if sess.rank == 0:
                    if os.path.isdir(final):
                        shutil.rmtree(final)
                    os.replace(tmp, final)
                    self._prune()
            sess.barrier()
            self._reap_wal(sess, manifest)
            self._last_time = time.monotonic()

    @staticmethod
    def _reap_wal(sess, manifest: Optional[dict]) -> None:
        """Bounded replay: once a checkpoint is COMPLETE (renamed into
        place, barrier passed), journal segments every record of which
        the checkpoint's version watermarks cover are dead weight —
        replay starts past the watermark — so reap them. Rank 0 reaps
        by the manifest it wrote; other ranks (``save`` returns None
        there) reap their per-rank journal by their OWN table versions
        as of the post-save barrier — their local records up to that
        point are superseded by the checkpoint a restart restores, and
        an unreaped journal would otherwise grow without bound on every
        rank but 0."""
        wal = getattr(sess, "wal", None)
        if wal is None:
            return
        if manifest is not None:
            watermarks = {entry["id"]: int(entry["version"])
                          for entry in manifest.get("tables", [])
                          if entry.get("version") is not None}
        else:
            watermarks = {t.table_id: int(t.version)
                          for t in sess.tables
                          if getattr(t, "version", None) is not None}
        if watermarks:
            wal.reap(watermarks)

    def _prune(self) -> None:
        old = _step_dirs(self._root)[:-self._keep]
        if is_remote(self._root):
            from . import remote

            for _, name in old:
                remote.delete_prefix(_join(self._root, name))
        else:
            for _, name in old:
                shutil.rmtree(os.path.join(self._root, name),
                              ignore_errors=True)
