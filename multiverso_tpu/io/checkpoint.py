"""Session-level checkpoint/resume.

The reference leaves checkpointing to each table's ``Serializable``
Store/Load (``include/multiverso/table_interface.h:59-66`` in the Multiverso
reference) with no automatic driver (the intended ``MV_LoadTable`` driver
survives only as comments, ``Test/main.cpp:293-331``). Here the driver
exists: ``save``/``restore`` walk the session's table registry and write one
binary record per table plus a JSON manifest. Rank 0 writes; every process
restores (single-controller JAX reloads give every process the same state).

For large-model checkpointing with per-shard parallel IO, use orbax directly
on the tables' ``.array`` views; this module is the framework-native
lightweight path matching reference semantics.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..log import Log
from ..runtime import Session
from .stream import open_stream

_MANIFEST = "manifest.json"


def save(directory: str, session: Optional[Session] = None) -> None:
    """Store every registered table under ``directory``."""
    sess = session or Session.get()
    if not sess.started:
        Log.fatal("save() requires an initialised session")
    sess.barrier()
    if sess.rank == 0:
        os.makedirs(directory, exist_ok=True)
        manifest = {"version": 1, "tables": []}
        for table in sess.tables:
            path = os.path.join(directory, f"table_{table.table_id}.bin")
            with open_stream(path, "wb") as stream:
                table.store(stream)
            manifest["tables"].append({
                "id": table.table_id,
                "type": type(table).__name__,
                "name": getattr(table, "name", ""),
                "file": os.path.basename(path),
            })
        with open(os.path.join(directory, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        Log.info("checkpoint saved: %d table(s) -> %s", len(sess.tables), directory)
    sess.barrier()


def restore(directory: str, session: Optional[Session] = None) -> None:
    """Load every registered table from ``directory`` (ids must match the
    creation order, as in the reference's table-id registry)."""
    sess = session or Session.get()
    if not sess.started:
        Log.fatal("restore() requires an initialised session")
    manifest_path = os.path.join(directory, _MANIFEST)
    if not os.path.exists(manifest_path):
        Log.fatal(f"no checkpoint manifest at {manifest_path}")
    with open(manifest_path) as f:
        manifest = json.load(f)
    by_id = {entry["id"]: entry for entry in manifest["tables"]}
    for table in sess.tables:
        entry = by_id.get(table.table_id)
        if entry is None:
            Log.fatal(f"checkpoint missing table id {table.table_id}")
        if entry["type"] != type(table).__name__:
            Log.fatal(
                f"checkpoint table {table.table_id} is {entry['type']}, "
                f"session has {type(table).__name__}")
        with open_stream(os.path.join(directory, entry["file"]), "rb") as stream:
            table.load(stream)
    Log.info("checkpoint restored: %d table(s) <- %s", len(sess.tables), directory)
