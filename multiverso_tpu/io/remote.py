"""Remote stream backends over tensorstore KvStore (gs://, memory://).

The reference ships an HDFS stream behind libhdfs
(``src/io/hdfs_stream.cpp``, ``include/multiverso/io/hdfs_stream.h:24`` in
the Multiverso reference) so tables and corpora can live on the cluster
filesystem. The TPU-VM equivalent of "the cluster filesystem" is object
storage — GCS — and the portable driver layer shipped with JAX is
tensorstore. This module registers:

* ``gs://bucket/path`` — GCS objects via tensorstore's ``gcs`` driver
  (credentials resolved by the environment, as on any TPU VM);
* ``memory://name/path`` — an in-process object store (tensorstore
  ``memory`` driver under one shared context), the hermetic test double for
  the same code path.

Object stores have no append/seek-write, so a write stream buffers locally
and uploads one object at close — exactly how the reference's HDFS stream
commits on ``Flush``/close. Read streams fetch the object once and serve
from memory (table records are read straight through anyway).
"""

from __future__ import annotations

import io
import threading
from ..analysis import lockwatch
from typing import BinaryIO

from ..log import Log

_lock = lockwatch.lock("io.remote._lock")
_memory_context = None   # shared so memory:// writes persist per-process


def _kvstore_for(uri) -> tuple:
    """(opened KvStore, key) for a parsed ``URI``."""
    import tensorstore as ts

    global _memory_context
    key = uri.path.lstrip("/")
    if uri.scheme == "gs":
        if not uri.host:
            Log.fatal(f"gs:// URI needs a bucket: {uri.uri}")
        store = ts.KvStore.open({"driver": "gcs", "bucket": uri.host}).result()
        return store, key
    if uri.scheme == "memory":
        with _lock:
            if _memory_context is None:
                _memory_context = ts.Context()
        store = ts.KvStore.open({"driver": "memory"},
                                context=_memory_context).result()
        # host names a namespace inside the shared store
        return store, f"{uri.host}/{key}" if uri.host else key
    Log.fatal(f"unsupported remote scheme {uri.scheme!r}")


class _KvReadStream(io.BytesIO):
    """Whole-object read stream (reference HDFSStream read mode)."""

    def __init__(self, store, key: str, uri: str) -> None:
        try:
            result = store.read(key).result()
        except Exception as exc:
            raise FileNotFoundError(f"{uri}: {exc}") from exc
        if str(result.state) == "missing":
            raise FileNotFoundError(uri)
        super().__init__(bytes(result.value))


class _KvWriteStream(io.BytesIO):
    """Buffered write stream; commits ONE object at close (object stores
    have no append — same commit-on-close the reference HDFS stream has)."""

    def __init__(self, store, key: str, uri: str) -> None:
        super().__init__()
        self._store = store
        self._key = key
        self._uri = uri
        self._committed = False
        self._aborted = False

    def abort(self) -> None:
        """Discard the buffer: a subsequent close() uploads nothing."""
        self._aborted = True

    def __exit__(self, exc_type, exc, tb):
        # a `with` block that raises mid-write must NOT publish the
        # truncated object (partial garbage accumulating beside the
        # manifest-last protocol could be mistaken for valid data)
        if exc_type is not None:
            self._aborted = True
        return super().__exit__(exc_type, exc, tb)

    def close(self) -> None:
        if not self._committed and not self._aborted and not self.closed:
            self._store.write(self._key, self.getvalue()).result()
            self._committed = True
        super().close()


def open_remote(uri, mode: str) -> BinaryIO:
    """Scheme opener signature for :func:`io.stream.register_scheme`."""
    store, key = _kvstore_for(uri)
    if "w" in mode:
        return _KvWriteStream(store, key, uri.uri)
    if "a" in mode:
        Log.fatal(f"append mode unsupported on object store: {uri.uri}")
    return _KvReadStream(store, key, uri.uri)


def _hdfs_if_hdfs(uri_str: str):
    """The checkpoint helpers dispatch per scheme: hdfs:// roots route to
    the WebHDFS backend, everything else to the tensorstore KvStores."""
    from .stream import URI

    if URI(uri_str).scheme == "hdfs":
        from . import hdfs

        return hdfs
    return None


def exists(uri_str: str) -> bool:
    """Object existence probe (manifest checks on remote checkpoints)."""
    from .stream import URI

    alt = _hdfs_if_hdfs(uri_str)
    if alt is not None:
        return alt.exists(uri_str)
    uri = URI(uri_str)
    store, key = _kvstore_for(uri)
    try:
        return str(store.read(key).result().state) != "missing"
    except FileNotFoundError:
        # only a definite "not there" reads as absence — a transient
        # object-store/auth failure must NOT (restore_latest probes
        # manifests through here; failure-as-absence would silently skip
        # a valid checkpoint). Same contract as io/hdfs.py exists().
        return False


def list_subdirs_with(root_uri: str, filename: str):
    """Immediate subdirectory names under ``root_uri`` that contain
    ``filename`` (checkpoint-step discovery on object stores, where
    "directories" are key prefixes)."""
    from .stream import URI

    alt = _hdfs_if_hdfs(root_uri)
    if alt is not None:
        return alt.list_subdirs_with(root_uri, filename)
    store, prefix = _kvstore_for(URI(root_uri))
    prefix = prefix.rstrip("/")
    prefix = prefix + "/" if prefix else ""
    names = set()
    for raw in store.list().result():
        key = raw.decode("utf-8") if isinstance(raw, bytes) else str(raw)
        if not key.startswith(prefix):
            continue
        parts = key[len(prefix):].split("/")
        if len(parts) == 2 and parts[1] == filename:
            names.add(parts[0])
    return sorted(names)


def delete_prefix(dir_uri: str) -> None:
    """Delete every object under ``dir_uri`` (remote checkpoint pruning)."""
    import tensorstore as ts

    from .stream import URI

    alt = _hdfs_if_hdfs(dir_uri)
    if alt is not None:
        return alt.delete_prefix(dir_uri)
    store, prefix = _kvstore_for(URI(dir_uri))
    prefix = prefix.rstrip("/") + "/"
    # exclusive max = prefix with '/' bumped to the next code point, i.e.
    # the tightest range covering exactly the keys under the prefix
    store.delete_range(ts.KvStore.KeyRange(prefix, prefix[:-1] + "0"))


def register() -> None:
    from .stream import register_scheme

    register_scheme("gs", open_remote)
    register_scheme("memory", open_remote)
