"""Logistic regression / softmax / FTRL model family.

TPU-native re-build of the reference LogisticRegression application's model
layer (``Applications/LogisticRegression/src`` in the Multiverso reference):
objectives (linear/sigmoid/softmax — ``objective/objective.cpp:29-315``;
FTRL-proximal — ``objective/ftrl*``), L1/L2 regularisers (``regular/*``),
dense minibatch training against a weight table, and the sparse/FTRL keyed
path. The reference computes per-sample gradients in C++ loops and pushes
averaged deltas to PS tables; here the whole minibatch is one jitted step on
the table's sharded state (weights never leave HBM on the dense path), and
the sparse path pulls/pushes only touched keys (``SparseTable``/``FTRLTable``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..log import Log
from ..tables.base import _option_scalars
from ..updaters import AddOption


@dataclass
class LogRegConfig:
    """Mirrors the reference config file keys (``LR/src/configure.h:9-93``)."""

    input_size: int = 0          # feature dim (bias handled internally)
    output_size: int = 1         # 1 = binary, >1 = softmax classes
    objective_type: str = "sigmoid"   # linear|sigmoid|softmax|ftrl
    regular_type: str = "none"        # none|l1|l2
    regular_coef: float = 0.0
    learning_rate: float = 0.1
    learning_rate_coef: float = 1.0   # lr decay: lr/(1+coef*t) (reference sgd)
    minibatch_size: int = 64
    sparse: bool = False
    sync_frequency: int = 1
    pipeline: bool = False
    reader_type: str = "default"      # default|weight|bsparse (LR/src/reader.cpp:212)
    read_buffer_size: int = 4096      # async reader ring (LR/src/configure.h:31)
    # FTRL hyperparameters (LR/src/configure.h)
    ftrl_alpha: float = 0.1
    ftrl_beta: float = 1.0
    ftrl_lambda1: float = 0.001
    ftrl_lambda2: float = 0.001


def _regular_grad(w, kind: str, coef: float):
    if kind == "l2":
        return coef * w
    if kind == "l1":
        return coef * jnp.sign(w)
    return jnp.zeros_like(w)


class LogReg:
    """Dense model against a MatrixTable of weights [output, input+1].

    The trailing column is the bias. ``train_minibatch`` runs one jitted
    step: forward, objective gradient, regulariser, updater application —
    the reference's ``Model::Update`` + ``PSModel::UpdateTable`` collapsed
    (``LR/src/model/model.cpp:58-123``, ``ps_model.cpp:185``).
    """

    def __init__(self, cfg: LogRegConfig, table) -> None:
        if cfg.objective_type not in ("linear", "sigmoid", "softmax"):
            Log.fatal(f"LogReg: unsupported objective {cfg.objective_type!r} "
                      "(use FTRLLogReg for ftrl)")
        if cfg.output_size < 1:
            Log.fatal("output_size must be >= 1")
        if table.updater.name == "default":
            # The step pre-scales delta = lr*grads; the accumulate updater
            # would ADD it (gradient ascent). The reference pins sgd too
            # (ps_model.cpp:24).
            Log.fatal("LogReg requires a descent updater on its table "
                      "(create it with updater='sgd'/'momentum_sgd'/'adagrad')")
        self.cfg = cfg
        self.table = table
        self._steps = 0
        self._step_fn = self._build_step()
        self._predict_fn = jax.jit(self._forward)

    # -- math --------------------------------------------------------------
    def _forward(self, w, x):
        """x: [B, input]; w: [output, input+1] -> scores [B, output]."""
        w = self.table.logical(w)   # drop server-padding rows (fake classes)
        scores = x @ w[:, :-1].T + w[:, -1]
        obj = self.cfg.objective_type
        if obj == "sigmoid":
            return jax.nn.sigmoid(scores)
        if obj == "softmax":
            return jax.nn.softmax(scores, axis=-1)
        return scores

    def _build_step(self):
        cfg = self.cfg
        updater = self.table.updater

        table = self.table

        def step(w, ustate, x, y, lr, momentum, rho, lam, wid):
            def loss_fn(wf):
                w = table.logical(wf)   # pad rows get zero grads
                scores = x @ w[:, :-1].T + w[:, -1]
                if cfg.objective_type == "sigmoid":
                    # y: [B, output] in {0,1}
                    loss = jnp.mean(
                        jnp.sum(jax.nn.softplus(scores) - y * scores, axis=-1))
                elif cfg.objective_type == "softmax":
                    logp = jax.nn.log_softmax(scores, axis=-1)
                    loss = -jnp.mean(jnp.sum(y * logp, axis=-1))
                else:  # linear: squared error
                    loss = 0.5 * jnp.mean(jnp.sum((scores - y) ** 2, axis=-1))
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(w)
            grads = grads + _regular_grad(w, cfg.regular_type, cfg.regular_coef)
            option = AddOption(worker_id=wid, learning_rate=lr,
                               momentum=momentum, rho=rho, lam=lam)
            delta = lr * grads
            w, ustate = updater.apply(w, ustate, delta, option)
            return w, ustate, loss

        return jax.jit(step, donate_argnums=(0, 1),
                       out_shardings=(self.table.sharding,
                                      self.table._ustate_sharding, None))

    # -- API ---------------------------------------------------------------
    def current_lr(self) -> float:
        cfg = self.cfg
        return cfg.learning_rate / (1.0 + cfg.learning_rate_coef * self._steps)

    def train_minibatch(self, x: np.ndarray, y: np.ndarray,
                        option: Optional[AddOption] = None):
        """One minibatch step; y is [B, output] (one-hot for softmax)."""
        option = option or AddOption()
        option.learning_rate = self.current_lr()
        t = self.table
        with t._lock:
            t._data, t._ustate, loss = self._step_fn(
                t._data, t._ustate,
                jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
                *_option_scalars(option, t.dtype))
            t.version += 1
        self._steps += 1
        return loss

    def predict(self, x: np.ndarray) -> np.ndarray:
        with self.table._lock:
            out = self._predict_fn(self.table._data, jnp.asarray(x, jnp.float32))
        return np.asarray(out)

    def test(self, x: np.ndarray, y: np.ndarray) -> float:
        """Accuracy (reference ``LogReg::Test``, ``LR/src/logreg.cpp:118``)."""
        preds = self.predict(x)
        if self.cfg.output_size == 1:
            correct = (preds[:, 0] > 0.5) == (y.ravel() > 0.5)
        else:
            correct = preds.argmax(-1) == y.argmax(-1)
        return float(np.mean(correct))


class FTRLLogReg:
    """FTRL-proximal binary LR over an FTRLTable of (z, n) state.

    Worker-side closed-form weight reconstruction + server-side (z, n)
    accumulation — the reference's FTRL objective + FTRL sparse table
    (``LR/src/objective/ftrl*``, ``util/ftrl_sparse_table.h``). Touched keys
    only: the natural sparse path.
    """

    def __init__(self, cfg: LogRegConfig, table) -> None:
        self.cfg = cfg
        self.table = table  # FTRLTable of size input_size + 1 (bias key = last)
        self.bias_key = cfg.input_size

    def _weights_from_zn(self, z: np.ndarray, n: np.ndarray) -> np.ndarray:
        cfg = self.cfg
        sign = np.sign(z)
        w = -(z - sign * cfg.ftrl_lambda1) / (
            (cfg.ftrl_beta + np.sqrt(n)) / cfg.ftrl_alpha + cfg.ftrl_lambda2)
        w[np.abs(z) <= cfg.ftrl_lambda1] = 0.0
        return w

    def train_sample(self, keys: np.ndarray, values: np.ndarray,
                     label: float) -> float:
        """One sparse sample: keys/values + bias; returns the loss."""
        cfg = self.cfg
        keys = np.concatenate([np.asarray(keys, np.int64),
                               [self.bias_key]])
        values = np.concatenate([np.asarray(values, np.float64), [1.0]])
        z, n = self.table.get_keys(keys)
        w = self._weights_from_zn(np.asarray(z, np.float64),
                                  np.asarray(n, np.float64))
        score = float(w @ values)
        pred = 1.0 / (1.0 + np.exp(-np.clip(score, -35, 35)))
        g = (pred - label) * values
        sigma = (np.sqrt(n + g * g) - np.sqrt(n)) / cfg.ftrl_alpha
        delta_z = g - sigma * w
        delta_n = g * g
        self.table.add_keys(keys, delta_z, delta_n)
        eps = 1e-12
        return float(-(label * np.log(pred + eps)
                       + (1 - label) * np.log(1 - pred + eps)))

    def predict_sample(self, keys: np.ndarray, values: np.ndarray) -> float:
        keys = np.concatenate([np.asarray(keys, np.int64), [self.bias_key]])
        values = np.concatenate([np.asarray(values, np.float64), [1.0]])
        z, n = self.table.get_keys(keys)
        w = self._weights_from_zn(np.asarray(z, np.float64),
                                  np.asarray(n, np.float64))
        score = float(w @ values)
        return 1.0 / (1.0 + np.exp(-np.clip(score, -35, 35)))


class SparseLogReg:
    """Binary LR over a SparseTable, touched-keys-only traffic.

    The reference's sparse PS path (``LR/src/model/ps_model.cpp`` with
    ``SparseWorkerTable``): pull the minibatch's keyset, compute gradients
    host-side on the gathered slice, push keyed deltas (sgd updater applies
    ``-=``).
    """

    def __init__(self, cfg: LogRegConfig, table) -> None:
        self.cfg = cfg
        self.table = table  # SparseTable(input_size + 1, updater="sgd")
        self.bias_key = cfg.input_size
        self._steps = 0
        # local weight cache: fresh Get every ``sync_frequency`` minibatches
        # (reference DoesNeedSync, ``LR/src/model/ps_model.cpp:172``); deltas
        # are pushed every minibatch and mirrored locally in between.
        self._w_cache: Dict[int, float] = {}
        self._cache_fresh = False

    @property
    def steps(self) -> int:
        """Minibatches trained so far; window phase = ``steps % sync_frequency``."""
        return self._steps

    def current_lr(self) -> float:
        cfg = self.cfg
        return cfg.learning_rate / (1.0 + cfg.learning_rate_coef * self._steps)

    def _fetch_into_cache(self, keys: np.ndarray) -> None:
        values = np.asarray(self.table.get_keys(keys), np.float64)
        for k, v in zip(keys, values):
            self._w_cache[int(k)] = float(v)

    def load_cache(self, keys: np.ndarray, values: np.ndarray) -> None:
        """Install a pipelined pull's result for the coming sync window.

        The pipelined driver pulls the *next* window's keyset on a background
        thread (reference ``PSModel::GetPipelineTable``,
        ``LR/src/model/ps_model.cpp:236``) and hands it over here; the next
        window-boundary refresh in :meth:`train_minibatch` is then skipped.
        """
        for k, v in zip(np.asarray(keys, np.int64).tolist(),
                        np.asarray(values, np.float64).tolist()):
            self._w_cache[int(k)] = float(v)
        self._cache_fresh = True

    def train_minibatch(self, samples) -> float:
        """samples: list of (keys, values, label)."""
        all_keys = sorted({int(k) for keys, _, _ in samples for k in keys}
                          | {self.bias_key})
        key_arr = np.asarray(all_keys, np.int64)
        idx = {k: i for i, k in enumerate(all_keys)}
        sync_every = max(self.cfg.sync_frequency, 1)
        if self._steps % sync_every == 0 and self._cache_fresh:
            self._cache_fresh = False  # window pre-pulled via load_cache
            missing = np.asarray([k for k in all_keys
                                  if k not in self._w_cache], np.int64)
            if missing.size:
                self._fetch_into_cache(missing)
        elif self._steps % sync_every == 0:
            self._fetch_into_cache(key_arr)  # full refresh this window
        else:
            missing = np.asarray([k for k in all_keys
                                  if k not in self._w_cache], np.int64)
            if missing.size:
                self._fetch_into_cache(missing)
        w = np.asarray([self._w_cache[k] for k in all_keys], np.float64)
        grad = np.zeros_like(w)
        loss = 0.0
        for keys, values, label in samples:
            cols = [idx[int(k)] for k in keys] + [idx[self.bias_key]]
            vals = np.concatenate([np.asarray(values, np.float64), [1.0]])
            score = float(w[cols] @ vals)
            pred = 1.0 / (1.0 + np.exp(-np.clip(score, -35, 35)))
            g = pred - label
            grad[cols] += g * vals
            eps = 1e-12
            loss += -(label * np.log(pred + eps)
                      + (1 - label) * np.log(1 - pred + eps))
        grad /= len(samples)
        delta = self.current_lr() * grad  # sgd updater applies data -= delta
        self.table.add_keys(key_arr, delta.astype(np.float32))
        for k, d in zip(all_keys, delta):  # read-your-writes between syncs
            self._w_cache[k] = self._w_cache.get(k, 0.0) - float(d)
        self._steps += 1
        return loss / len(samples)

    def predict_sample(self, keys, values) -> float:
        key_arr = np.concatenate([np.asarray(keys, np.int64), [self.bias_key]])
        vals = np.concatenate([np.asarray(values, np.float64), [1.0]])
        w = np.asarray(self.table.get_keys(key_arr), np.float64)
        score = float(w @ vals)
        return 1.0 / (1.0 + np.exp(-np.clip(score, -35, 35)))
