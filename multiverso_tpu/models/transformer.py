"""Transformer language model: the parallelism-showcase model family.

The reference predates transformers (SURVEY §5.7) — its model families are
word2vec and logistic regression, both reproduced in this package. This
module is the framework's forward-looking flagship: a decoder-only LM whose
training step composes the mesh axes the framework provides:

* **dp** — the batch shards over the ``worker`` axis; the mean-loss gradient
  becomes a ``psum`` over ICI (exactly the sync-PS contract,
  ``parallel/sync_step.py``);
* **tp** — attention/FFN weights shard over the ``server`` axis
  (Megatron-style column/row splits expressed as ``NamedSharding``s; XLA
  inserts the all-gathers/reduce-scatters);
* layers are **stacked** on a leading dim and applied with ``lax.scan`` —
  the same stacked layout ``parallel/pipeline.py`` consumes for pipeline
  parallelism over a ``stage`` axis;
* long-context attention is pluggable: the default local (full-sequence)
  attention shares :func:`ops.reference_attention`'s math; for sequence
  parallelism use :func:`ops.ring_attention` / :func:`ops.ulysses_attention`
  over a ``seq`` axis mesh.

Pre-LN, learned positions, tied input/output embeddings, SGD-with-momentum
update inline in the jitted step (params never leave HBM; buffers donated).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from ..log import Log
from ..ops.ring_attention import ring_prefill_attention
from ..ops.ulysses import ulysses_prefill_attention
from ..topology import SERVER_AXIS, WORKER_AXIS


@dataclass
class TransformerConfig:
    vocab_size: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_seq: int = 256
    dtype: Any = jnp.float32
    learning_rate: float = 0.1
    momentum: float = 0.9
    seed: int = 0
    # scan over the layer stack instead of unrolling: cheaper compiles
    # for very deep models, ~30% slower steps (see forward())
    scan_layers: bool = False
    # attention implementation: "reference" (jnp, XLA-fused), "flash"
    # (crossover dispatch — Pallas kernel at/above the measured ~1.5k-seq
    # win threshold, XLA below; never slower than reference), or
    # "flash_force" (always the Pallas kernel, fwd+bwd;
    # ops/flash_attention.py — runs in interpret mode off-TPU, so tests
    # stay hermetic)
    attention: str = "reference"


def init_params(cfg: TransformerConfig,
                rng: Optional[np.random.Generator] = None) -> Dict[str, Any]:
    """Random parameter pytree; per-layer weights stacked on dim 0."""
    rng = rng or np.random.default_rng(cfg.seed)
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    s = 1.0 / np.sqrt(D)
    sf = 1.0 / np.sqrt(F)

    def mk(shape, scale):
        return jnp.asarray(rng.standard_normal(shape) * scale, cfg.dtype)

    return {
        "embed": mk((cfg.vocab_size, D), s),
        "pos": mk((cfg.max_seq, D), 0.02),
        "layers": {
            "ln1_g": jnp.ones((L, D), cfg.dtype),
            "ln2_g": jnp.ones((L, D), cfg.dtype),
            # separate Q/K/V projections: a fused [D, 3D] column-sharded
            # weight would put the Q/K/V split boundaries mid-shard for tp
            # sizes not divisible by 3, forcing a reshard every layer
            "w_q": mk((L, D, D), s),
            "w_k": mk((L, D, D), s),
            "w_v": mk((L, D, D), s),
            "w_o": mk((L, D, D), s),
            "w_ff1": mk((L, D, F), s),
            "w_ff2": mk((L, F, D), sf),
        },
        "ln_f_g": jnp.ones((D,), cfg.dtype),
    }


def param_shardings(cfg: TransformerConfig, mesh,
                    tp_axis: str = SERVER_AXIS) -> Dict[str, Any]:
    """Tensor-parallel layout over ``tp_axis``.

    Column-parallel ``w_q``/``w_k``/``w_v``/``w_ff1`` (output dim sharded),
    row-parallel ``w_o``/``w_ff2`` (input dim sharded) — XLA propagates
    these into the Megatron collective pattern. Embeddings shard by row like
    parameter tables; norms replicate.
    """
    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return {
        "embed": ns(tp_axis, None),
        "pos": ns(),
        "layers": {
            "ln1_g": ns(),
            "ln2_g": ns(),
            "w_q": ns(None, None, tp_axis),
            "w_k": ns(None, None, tp_axis),
            "w_v": ns(None, None, tp_axis),
            "w_o": ns(None, tp_axis, None),
            "w_ff1": ns(None, None, tp_axis),
            "w_ff2": ns(None, tp_axis, None),
        },
        "ln_f_g": ns(),
    }


def _rmsnorm(x, g):
    return x * jax.lax.rsqrt(
        jnp.mean(jnp.square(x.astype(jnp.float32)), -1,
                 keepdims=True) + 1e-6).astype(x.dtype) * g


def _attention(q, k, v, n_heads: int, impl: str = "reference"):
    """Causal multi-head attention, [B, T, D] in/out.

    ``impl="reference"``: :func:`ops.reference_attention` vmapped over
    batch — one causal-attention implementation shared by the model, the
    sequence-parallel ops, and the tests. ``impl="flash"``: crossover
    dispatch (:func:`ops.flash_attention.best_attention`) — the Pallas
    flash kernel at/above the measured ~1.5k-seq win threshold, the
    XLA-fused reference below it, so picking "flash" can never slow a
    model down. ``impl="flash_force"`` pins the Pallas kernel
    (online-softmax tiles in VMEM, Pallas fwd+bwd via custom VJP).
    """
    B, T, D = q.shape
    dh = D // n_heads
    split = lambda x: x.reshape(B, T, n_heads, dh)
    if impl == "flash":
        from ..ops.flash_attention import best_attention as fn

        if B * n_heads >= 64:
            # many-program calls amortise the kernel's launch and epilogue
            # over B x heads programs, and the surrounding model denies
            # XLA the fusions that make its attention cheap standalone:
            # measured in-model (12 layers, ~8k tok/step, d_model 768,
            # 96-192 programs), flash TIES reference at seq 512 and wins
            # 1.5x/2x at 1024/2048 — so the crossover drops to 512 there.
            # Few-program calls (the standalone 8-program sweep ran
            # 0.44-0.63x below seq 1536, docs/TPU_VALIDATE.json) keep the
            # 1536 default; the 64-program gate is the measured boundary's
            # conservative side.
            fn = partial(fn, min_flash_seq=512)
    elif impl == "flash_force":
        from ..ops.flash_attention import flash_attention as fn
    elif impl == "reference":
        from ..ops.ring_attention import reference_attention as fn
    else:
        Log.fatal(f"unknown attention impl {impl!r} "
                  "(expected 'reference', 'flash' or 'flash_force')")
    out = jax.vmap(partial(fn, causal=True))(split(q), split(k), split(v))
    return out.reshape(B, T, D)


def forward(cfg: TransformerConfig, params: Dict[str, Any],
            tokens: jax.Array) -> jax.Array:
    """Logits [B, T, V] for token ids [B, T] (causal LM)."""
    B, T = tokens.shape
    h = jnp.take(params["embed"], tokens, axis=0) + params["pos"][:T]

    def block(h, layer):
        x = _rmsnorm(h, layer["ln1_g"])
        # measured rejection (r5): concatenating w_q/w_k/w_v into one
        # [D, 3D] gemm saved only 0.18 ms of the 50.9 ms flagship step
        # (XLA already schedules the three thin gemms near-optimally);
        # not worth the concat + split in the hot path
        h = h + _attention(x @ layer["w_q"], x @ layer["w_k"],
                           x @ layer["w_v"], cfg.n_heads,
                           cfg.attention) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
        return h, None

    if cfg.scan_layers:
        # O(1) compile size for very deep stacks, at a measured ~30%
        # device-time cost (the scan's per-layer param slices and backward
        # grad-stack dynamic-update-slices are real HBM traffic)
        h, _ = jax.lax.scan(block, h, params["layers"])
    else:
        # unrolled (default): XLA schedules each layer's matmuls directly
        # with no carry copies — 112 ms -> 79 ms grad step at the
        # tools/lm_mfu.py flagship shape
        for i in range(cfg.n_layers):
            h, _ = block(h, jax.tree.map(lambda a: a[i], params["layers"]))
    h = _rmsnorm(h, params["ln_f_g"])
    return jnp.einsum("btd,vd->btv", h, params["embed"],
                      preferred_element_type=jnp.float32)


def loss_fn(cfg: TransformerConfig, params: Dict[str, Any],
            tokens: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy over [B, T] token ids.

    Runs the forward at the FULL length and slices the logits, rather
    than slicing the tokens first: causal attention makes the two
    mathematically identical (position i sees only tokens <= i), but a
    T-1-length forward mis-tiles every flash call — the r5 trace showed
    the resulting pad/slice copies around all 12 layers' kernels cost
    ~1.4 ms/step (2.5%) at the flagship shape; the last position's
    logits row is orders of magnitude cheaper than that. Callers that
    feed ``max_seq + 1`` tokens (the LM app's chunking) keep the
    slice-first form — their sliced length IS the aligned one."""
    if tokens.shape[1] <= cfg.max_seq:
        logits = forward(cfg, params, tokens)[:, :-1]
    else:
        logits = forward(cfg, params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(
        jnp.take_along_axis(logp, targets[..., None], axis=-1))


# -- serving: KV-cache greedy decode -----------------------------------------
#
# The training ``forward`` recomputes attention over the whole prefix for
# every new token — O(T^2) per generated token. The serving path splits
# generation into PREFILL (one causal forward over the right-padded prompt
# batch that also records per-layer K/V projections) and DECODE (one token per
# step against the cached K/V — O(T) per token). Prompts are right-padded to
# the batcher's bucket length; per-example ``lengths`` drive the position
# embeddings, the logits gather, and the attention mask, so padding never
# leaks into a response. Cache layout: [n_layers, B, max_seq, d_model],
# pre-head-split (the head split is a free reshape).

_NEG_INF = jnp.float32(-1e30)


def _cached_attention(q, k_cache, v_cache, n_heads: int, pos) -> jax.Array:
    """One-token attention: ``q`` [B, D] against cache [B, T, D].

    ``pos`` [B] is each example's current position; cache entries at
    positions <= pos are live (prompt + previously generated tokens),
    everything past is masked. Math matches :func:`ops.reference_attention`
    (1/sqrt(dh) scale, f32 softmax) so cached decode is numerically the
    training forward's argmax path.
    """
    B, D = q.shape
    T = k_cache.shape[1]
    dh = D // n_heads
    qh = q.reshape(B, n_heads, dh)
    kh = k_cache.reshape(B, T, n_heads, dh)
    vh = v_cache.reshape(B, T, n_heads, dh)
    scores = jnp.einsum("bhd,bthd->bht", qh, kh,
                        preferred_element_type=jnp.float32) / np.sqrt(dh)
    mask = (jnp.arange(T)[None, :] <= pos[:, None])[:, None, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bht,bthd->bhd", probs.astype(vh.dtype), vh)
    return out.reshape(B, D).astype(q.dtype)


def prefill(cfg: TransformerConfig, params: Dict[str, Any],
            tokens: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Causal forward over right-padded prompts, recording per-layer K/V.

    Returns ``(logits [B, P, V], k [L, B, P, D], v [L, B, P, D])``. Padding
    positions produce garbage hidden states — callers gather logits at
    ``lengths - 1`` and decode overwrites pad-slot cache entries before the
    mask ever reaches them, so the garbage is never observable.
    """
    B, P = tokens.shape
    h = jnp.take(params["embed"], tokens, axis=0) + params["pos"][:P]
    ks, vs = [], []
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        ks.append(k)
        vs.append(v)
        h = h + _attention(q, k, v, cfg.n_heads, cfg.attention) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    logits = jnp.einsum("btd,vd->btv", h, params["embed"],
                        preferred_element_type=jnp.float32)
    return logits, jnp.stack(ks), jnp.stack(vs)


def decode_step(cfg: TransformerConfig, params: Dict[str, Any],
                k_cache: jax.Array, v_cache: jax.Array, tok: jax.Array,
                pos: jax.Array, active: jax.Array
                ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One fused token step over S persistent slots.

    ``k_cache``/``v_cache`` [L, S, T, D], ``tok``/``pos`` [S] int32,
    ``active`` [S] bool. Each slot is an independent sequence: writes its
    token's K/V at ``pos``, attends the cache through ``pos``
    (:func:`_cached_attention` — the math of :func:`greedy_decode`'s scan
    body, with the batch dim reinterpreted as the slot dim), and emits its
    greedy next token. Dead slots still flow through the fused program
    (one compiled trace regardless of which slots live) but emit pad and
    keep a frozen ``pos``; their cache writes are parked at position
    ``T - 1`` — never at the frozen ``pos``, which could sit inside a
    prompt region a chunked admission is prefilling between iterations —
    and a later admission/live decode overwrites anything they left
    before attending it.

    Returns ``(k_cache, v_cache, next_tok [S], pos [S])`` — jit with
    ``donate_argnums`` on the caches so XLA updates them in place.
    """
    S = tok.shape[0]
    T = k_cache.shape[2]
    slot_ix = jnp.arange(S)
    # dead lanes still flow through the fused program but must NOT write
    # at their frozen ``pos``: a chunked prefill may be mid-flight in
    # that slot (serving/decode_engine.py), and a stale-pos write
    # between two chunks would clobber prompt K/V already inserted.
    # Park dead writes at T-1 — a position strictly past any prompt
    # (T = max_prompt + max_new, max_new >= 1) that a live generation
    # overwrites before its attention mask ever reaches it.
    write_pos = jnp.where(active, pos, T - 1)
    h = (jnp.take(params["embed"], tok, axis=0)
         + jnp.take(params["pos"], pos, axis=0))
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        k_cache = k_cache.at[i, slot_ix, write_pos].set(k)
        v_cache = v_cache.at[i, slot_ix, write_pos].set(v)
        h = h + _cached_attention(
            q, k_cache[i], v_cache[i], cfg.n_heads, pos) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    out = jnp.einsum("sd,vd->sv", h, params["embed"],
                     preferred_element_type=jnp.float32)
    nxt = jnp.argmax(out, axis=-1).astype(tok.dtype)
    nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
    pos = jnp.where(active, pos + 1, pos)
    return k_cache, v_cache, nxt, pos


def _chunk_attention(q, k_cache, v_cache, n_heads: int, offset) -> jax.Array:
    """Chunk attention: ``q`` [C, D] against one slot's cache [T, D].

    Chunk position ``i`` (cache position ``offset + i``) attends cache
    entries at positions ``<= offset + i`` — the already-inserted prefix
    from earlier chunks plus this chunk's own K/V (written before the
    call), everything past is masked. Math matches
    :func:`_cached_attention` (1/sqrt(dh) scale, f32 softmax) so a
    chunked prefill's last-position logits argmax to the same first
    token the fused whole-prompt :func:`prefill` produces.
    """
    C, D = q.shape
    T = k_cache.shape[0]
    dh = D // n_heads
    qh = q.reshape(C, n_heads, dh)
    kh = k_cache.reshape(T, n_heads, dh)
    vh = v_cache.reshape(T, n_heads, dh)
    scores = jnp.einsum("chd,thd->hct", qh, kh,
                        preferred_element_type=jnp.float32) / np.sqrt(dh)
    mask = (jnp.arange(T)[None, :]
            <= (offset + jnp.arange(C))[:, None])[None, :, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hct,thd->chd", probs.astype(vh.dtype), vh)
    return out.reshape(C, D).astype(q.dtype)


def prefill_chunk(cfg: TransformerConfig, params: Dict[str, Any],
                  k_cache: jax.Array, v_cache: jax.Array, slot: jax.Array,
                  tokens: jax.Array, offset: jax.Array, length: jax.Array
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Incremental prefill: one fixed-size chunk of one slot's prompt.

    ``k_cache``/``v_cache`` [L, S, T, D] (the decode engine's slot
    caches), ``tokens`` [C] right-padded chunk ids, ``slot`` the target
    slot, ``offset`` the cache position of ``tokens[0]``, ``length`` the
    real token count in this chunk (``1 <= length <= C``). All of slot/
    offset/length are traced scalars: ONE compiled trace per chunk size
    serves every (slot, offset, partial-fill) combination — the
    Sarathi-style budget knob adds exactly one trace to the engine's
    accounting, next to the single fused :func:`decode_step`.

    Each chunk position's K/V is written in place at
    ``[l, slot, offset + i]`` (a per-position scatter) BEFORE attention,
    so causal attention for position ``offset + i`` covers the already-
    inserted prefix ``[0, offset)`` from earlier chunks plus the chunk's
    own positions ``<= i`` via :func:`_chunk_attention`'s mask. The
    write is a scatter, NOT a C-wide dynamic-update-slice: a final
    chunk's pad tail can extend past ``T`` (``ceil(P/C)*C`` need not fit
    ``max_prompt + max_new``), and a DUS would CLAMP its start index
    back over real prompt positions — silent K/V corruption. Scatter
    pad writes past ``T - 1`` simply drop (the ``add_rows`` XLA
    out-of-bounds contract); in-bounds real positions are distinct, so
    the write stays deterministic. In-bounds pad garbage lands at cache
    positions the decode mask only reaches AFTER :func:`decode_step`
    overwrites them (the :func:`prefill` pad contract), and pad
    position-embedding reads clamp (``jnp.take``'s OOB mode), so the
    garbage is never observable.

    Returns ``(k_cache, v_cache, last_logits [V])`` — the logits of
    position ``offset + length - 1``. Callers use them only on the
    FINAL chunk of a prompt, where they are the prompt's last real
    position: the first generated token still falls out of the last
    chunk, exactly as it falls out of a whole-prompt prefill.
    """
    C = tokens.shape[0]
    pos_ix = offset + jnp.arange(C)
    h = (jnp.take(params["embed"], tokens, axis=0)
         + jnp.take(params["pos"], pos_ix, axis=0))
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        k_cache = k_cache.at[i, slot, pos_ix].set(k)
        v_cache = v_cache.at[i, slot, pos_ix].set(v)
        kc = jax.lax.dynamic_index_in_dim(k_cache[i], slot, 0, keepdims=False)
        vc = jax.lax.dynamic_index_in_dim(v_cache[i], slot, 0, keepdims=False)
        h = h + _chunk_attention(
            q, kc, vc, cfg.n_heads, offset) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    last = jnp.take(h, length - 1, axis=0)
    logits = jnp.einsum("d,vd->v", last, params["embed"],
                        preferred_element_type=jnp.float32)
    return k_cache, v_cache, logits


# -- serving: paged KV cache --------------------------------------------------
#
# The slotted [L, S, T, D] cache above gives every slot a contiguous strip
# sized for the worst case T = max_prompt + max_new; a short sequence wastes
# almost its whole strip. The paged layout (vLLM/PagedAttention) replaces the
# strips with ONE block pool [L, n_blocks, block_size, D] plus a per-slot
# BLOCK TABLE [S, max_blocks_per_seq] of int32 block ids: logical cache
# position p of slot s lives at physical (block_tables[s, p // Bs], p % Bs).
# Block tables are TRACED DATA (fixed [S, M] shape), so the one-compiled-
# trace-per-engine-config invariant survives paging: reads become gathers
# through the table, writes become (block, offset) scatters, and which blocks
# a slot owns never touches a shape.
#
# Conventions shared by the three paged entry points below (and by
# serving/block_pool.py, which owns the host-side allocator):
#
# * block id 0 is the SCRATCH block: the block-table pad sentinel, the
#   parking target for dead-lane decode writes, and where pad-position
#   prefill garbage lands. Nothing a live attention mask can reach ever
#   maps there — a slot's reservation covers prompt + max_new positions, so
#   every position <= pos resolves to a real allocated block.
# * gathered per-slot views are SLICED to the engine's logical cache length
#   ``t_logical`` (= max_prompt + max_new) before attention, so the paged
#   attention operand has the exact shape (and therefore the exact reduction
#   order, hence bit-exact outputs) of the contiguous cache it replaces —
#   the gather's tail positions past a slot's allocation hold scratch
#   garbage, masked off exactly like the contiguous strips' dead writes.


def decode_step_paged(cfg: TransformerConfig, params: Dict[str, Any],
                      k_pool: jax.Array, v_pool: jax.Array,
                      block_tables: jax.Array, tok: jax.Array,
                      pos: jax.Array, active: jax.Array,
                      t_logical: Optional[int] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One fused token step over S slots against the paged KV pool.

    ``k_pool``/``v_pool`` [L, N, Bs, D] (block 0 = scratch),
    ``block_tables`` [S, M] int32 (traced — one compiled trace per
    engine config regardless of block assignment), ``tok``/``pos``/
    ``active`` as in :func:`decode_step`. Each live slot writes its
    token's K/V at ``(block_tables[s, pos // Bs], pos % Bs)`` and
    attends its gathered view sliced to ``t_logical``; dead lanes park
    their writes in the scratch block (the paged analogue of the
    contiguous path's ``T - 1`` parking — scratch is never reachable by
    a live mask, so a mid-flight chunked prefill's prompt region cannot
    be clobbered).

    Returns ``(k_pool, v_pool, next_tok [S], pos [S])``.
    """
    S = tok.shape[0]
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    blk = jnp.take_along_axis(block_tables, (pos // Bs)[:, None],
                              axis=1)[:, 0]
    write_blk = jnp.where(active, blk, 0)      # dead lanes -> scratch
    write_off = jnp.where(active, pos % Bs, 0)
    h = (jnp.take(params["embed"], tok, axis=0)
         + jnp.take(params["pos"], pos, axis=0))
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        k_pool = k_pool.at[i, write_blk, write_off].set(k)
        v_pool = v_pool.at[i, write_blk, write_off].set(v)
        # gather each slot's blocks into a contiguous [S, T, D] view —
        # the same operand shape as the contiguous cache, so the
        # attention math (and its reduction order) is unchanged
        kv_shape = (S, M * Bs, -1)
        kc = jnp.take(k_pool[i], block_tables, axis=0).reshape(kv_shape)
        vc = jnp.take(v_pool[i], block_tables, axis=0).reshape(kv_shape)
        h = h + _cached_attention(
            q, kc[:, :T], vc[:, :T], cfg.n_heads, pos) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    out = jnp.einsum("sd,vd->sv", h, params["embed"],
                     preferred_element_type=jnp.float32)
    nxt = jnp.argmax(out, axis=-1).astype(tok.dtype)
    nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
    pos = jnp.where(active, pos + 1, pos)
    return k_pool, v_pool, nxt, pos


def prefill_chunk_paged(cfg: TransformerConfig, params: Dict[str, Any],
                        k_pool: jax.Array, v_pool: jax.Array,
                        block_tables: jax.Array, slot: jax.Array,
                        tokens: jax.Array, offset: jax.Array,
                        length: jax.Array, t_logical: Optional[int] = None
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Incremental prefill of one fixed-size chunk into the paged pool.

    The paged :func:`prefill_chunk`: same contract (``slot``/``offset``/
    ``length`` all traced, ONE compiled trace per chunk size), but K/V
    writes scatter to ``(block_tables[slot, p // Bs], p % Bs)`` and the
    chunk attends the slot's gathered view. Pad positions (``i >=
    length``) route to the scratch block explicitly — the paged
    analogue of the contiguous scatter's drop-past-``T`` contract: a
    final chunk's pad tail must not clamp onto real prompt blocks, and
    with the table gather it would (the table row gather clamps), so
    the pad lanes are masked to scratch before the scatter instead.
    In-bounds pad garbage (real positions past ``length`` inside the
    reservation) lands in allocated blocks that decode overwrites
    before its mask reaches them, exactly as in the contiguous layout.

    Returns ``(k_pool, v_pool, last_logits [V])``.
    """
    C = tokens.shape[0]
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    bt_row = jax.lax.dynamic_index_in_dim(block_tables, slot, 0,
                                          keepdims=False)        # [M]
    pos_ix = offset + jnp.arange(C)
    valid = jnp.arange(C) < length
    blk = jnp.where(
        valid, jnp.take(bt_row, jnp.clip(pos_ix // Bs, 0, M - 1)), 0)
    off = jnp.where(valid, pos_ix % Bs, 0)
    h = (jnp.take(params["embed"], tokens, axis=0)
         + jnp.take(params["pos"], pos_ix, axis=0))
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        k_pool = k_pool.at[i, blk, off].set(k)
        v_pool = v_pool.at[i, blk, off].set(v)
        kc = jnp.take(k_pool[i], bt_row, axis=0).reshape(M * Bs, -1)
        vc = jnp.take(v_pool[i], bt_row, axis=0).reshape(M * Bs, -1)
        h = h + _chunk_attention(
            q, kc[:T], vc[:T], cfg.n_heads, offset) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    last = jnp.take(h, length - 1, axis=0)
    logits = jnp.einsum("d,vd->v", last, params["embed"],
                        preferred_element_type=jnp.float32)
    return k_pool, v_pool, logits


def prefill_chunk_paged_sp(cfg: TransformerConfig, params: Dict[str, Any],
                           k_pool: jax.Array, v_pool: jax.Array,
                           block_tables: jax.Array, slot: jax.Array,
                           tokens: jax.Array, offset: jax.Array,
                           length: jax.Array, mesh, backend: str,
                           t_logical: Optional[int] = None,
                           tp_axis: str = "tp"
                           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel :func:`prefill_chunk_paged` over the decode mesh.

    Identical contract and — row for row — identical math: the only
    change is that the chunk's attention runs through
    :func:`ops.ring_prefill_attention` (``backend="ring"``) or
    :func:`ops.ulysses_prefill_attention` (``backend="ulysses"``), which
    shard the ``C`` chunk rows over the decode mesh's ``tp_axis`` and
    reassemble with collectives. Per-row chunk attention is independent
    of how rows are grouped across devices and the serving entry points
    reproduce ``_chunk_attention`` expression-for-expression, so outputs
    are bit-identical to the single-lane path; what changes is that a
    ``C = budget * tp`` chunk costs each device one budget's worth of
    rows per iteration, so a long prompt prefills in ``tp``x fewer
    iterations. Everything around the attention (embedding, K/V
    projections, paged scatter/gather, MLP) is left to GSPMD exactly as
    in the single-lane program. Requires ``C % tp == 0`` always and
    ``t_logical % tp == 0`` for the ring backend (the ulysses backend
    instead needs ``n_heads % tp == 0`` — the pool's native head shard).
    """
    if backend not in ("ring", "ulysses"):
        raise ValueError(f"unknown seqpar backend {backend!r}")
    C = tokens.shape[0]
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    bt_row = jax.lax.dynamic_index_in_dim(block_tables, slot, 0,
                                          keepdims=False)        # [M]
    pos_ix = offset + jnp.arange(C)
    valid = jnp.arange(C) < length
    blk = jnp.where(
        valid, jnp.take(bt_row, jnp.clip(pos_ix // Bs, 0, M - 1)), 0)
    off = jnp.where(valid, pos_ix % Bs, 0)
    h = (jnp.take(params["embed"], tokens, axis=0)
         + jnp.take(params["pos"], pos_ix, axis=0))
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        k_pool = k_pool.at[i, blk, off].set(k)
        v_pool = v_pool.at[i, blk, off].set(v)
        kc = jnp.take(k_pool[i], bt_row, axis=0).reshape(M * Bs, -1)
        vc = jnp.take(v_pool[i], bt_row, axis=0).reshape(M * Bs, -1)
        if backend == "ring":
            attn = ring_prefill_attention(q, kc[:T], vc[:T], cfg.n_heads,
                                          offset, mesh, axis=tp_axis)
        else:
            attn = ulysses_prefill_attention(q, kc[:T], vc[:T],
                                             cfg.n_heads, offset, mesh,
                                             axis=tp_axis)
        h = h + attn @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    last = jnp.take(h, length - 1, axis=0)
    logits = jnp.einsum("d,vd->v", last, params["embed"],
                        preferred_element_type=jnp.float32)
    return k_pool, v_pool, logits


def cache_insert_paged(k_pool: jax.Array, v_pool: jax.Array,
                       block_tables: jax.Array, ks: jax.Array, vs: jax.Array
                       ) -> Tuple[jax.Array, jax.Array]:
    """Write b prefilled sequences' K/V [L, b, P, D] through block tables.

    The paged :func:`cache_insert`: ``block_tables`` [b, M] carries one
    PER-ROW table (traced), so placement is encoded in data, not in a
    DUS chain — row ``r``'s position ``p`` scatters to
    ``(block_tables[r, p // Bs], p % Bs)``. A caller padding a partial
    batch points the pad rows' tables entirely at the scratch sentinel
    (block 0): their writes land in scratch, where the order-undefined
    duplicate-index scatter is harmless because nothing reads it (the
    contiguous path needed the row-0-last DUS ordering for exactly this;
    the paged path needs only the sentinel). Positions past a row's
    true prompt length write garbage into its reservation (overwritten
    by decode before the mask reaches them — the :func:`prefill`
    contract) or, past the reservation, into scratch via the table's
    sentinel padding.
    """
    L, b, P, _ = ks.shape
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    p_ix = jnp.arange(P)
    blk = jnp.take(block_tables, jnp.clip(p_ix // Bs, 0, M - 1),
                   axis=1)                                       # [b, P]
    off = jnp.broadcast_to(p_ix % Bs, (b, P))
    for i in range(L):
        k_pool = k_pool.at[i, blk, off].set(ks[i])
        v_pool = v_pool.at[i, blk, off].set(vs[i])
    return k_pool, v_pool


# -- serving: speculative decoding (fixed-K verify step) ----------------------
#
# Speculative decoding amortizes per-step fixed costs (dispatch, host
# scheduling, all-reduces at decode_tp > 1) over up to K + 1 tokens per
# engine iteration: a host-side drafter proposes K cheap continuation
# guesses (n-gram prompt lookup — no draft model), and ONE fused forward
# scores all K + 1 positions against the paged pool. Greedy verification
# then accepts the longest drafted prefix that matches the model's own
# argmax chain plus one correction token, so outputs are token-identical
# to plain one-token decode by construction. The hard invariant survives:
# K is FIXED per engine config (the [S, K + 1] window is the only new
# static shape), while the drafted tokens, per-slot valid counts, block
# tables and positions are all traced data — exactly one compiled verify
# trace per engine config, next to the one fused step.


def _verify_attention(q, k_cache, v_cache, n_heads: int, pos) -> jax.Array:
    """Windowed multi-position attention: ``q`` [S, K1, D] against each
    slot's gathered cache [S, T, D].

    Window position ``j`` of slot ``s`` sits at cache position
    ``pos[s] + j`` and attends entries at positions ``<= pos[s] + j`` —
    the committed prefix plus the window's own already-written K/V
    (causal WITHIN the drafted window, exactly
    :func:`_chunk_attention`'s mask with the chunk offset per slot).
    Math matches :func:`_cached_attention` (1/sqrt(dh) scale, f32
    softmax), so window position 0's argmax is the token the plain
    fused step would emit.
    """
    S, K1, D = q.shape
    T = k_cache.shape[1]
    dh = D // n_heads
    qh = q.reshape(S, K1, n_heads, dh)
    kh = k_cache.reshape(S, T, n_heads, dh)
    vh = v_cache.reshape(S, T, n_heads, dh)
    scores = jnp.einsum("skhd,sthd->shkt", qh, kh,
                        preferred_element_type=jnp.float32) / np.sqrt(dh)
    mask = (jnp.arange(T)[None, None, :]
            <= (pos[:, None] + jnp.arange(K1))[:, :, None])[:, None, :, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("shkt,sthd->skhd", probs.astype(vh.dtype), vh)
    return out.reshape(S, K1, D).astype(q.dtype)


def verify_step_paged(cfg: TransformerConfig, params: Dict[str, Any],
                      k_pool: jax.Array, v_pool: jax.Array,
                      block_tables: jax.Array, toks: jax.Array,
                      pos: jax.Array, active: jax.Array,
                      n_valid: jax.Array, t_logical: Optional[int] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused multi-position step: score K drafted tokens in one forward.

    ``toks`` [S, K1] is each slot's verification window — position 0 is
    the token the plain step would consume, positions ``1 .. K1 - 1``
    are drafted guesses; ``pos`` [S] is the cache position of
    ``toks[:, 0]``; ``n_valid`` [S] int32 in ``[1, K1]`` counts each
    slot's REAL window entries (a slot with no drafts this iteration
    runs ``n_valid = 1``). K1 = K + 1 is the ONLY static the feature
    adds: toks/pos/active/n_valid and the block tables are all traced,
    so one compiled trace serves every draft mix, acceptance outcome
    and block assignment — the accepted length is handled host-side as
    data, never as a shape.

    Every valid window position writes its K/V at
    ``(block_tables[s, (pos + j) // Bs], (pos + j) % Bs)`` BEFORE
    attention (so the causal window sees itself), then attends the
    slot's gathered view sliced to ``t_logical`` via
    :func:`_verify_attention`. Dead lanes and pad positions
    (``j >= n_valid``) park their writes in the scratch block — the
    engine clamps drafts to ``remaining - 1`` tokens, so valid writes
    never escape the slot's admission-time reservation and rejected
    positions need NO device-side rollback: the next window starts at
    the first unverified position and rewrites every speculated
    position before any mask can reach it (the same
    overwrite-before-the-mask contract pad garbage already rides).

    Returns ``(k_pool, v_pool, out_tok [S, K1])`` where
    ``out_tok[s, j]`` is the greedy token following inputs
    ``toks[s, : j + 1]``: the host accepts drafts while
    ``toks[s, j] == out_tok[s, j - 1]`` and emits
    ``out_tok[s, : accepted + 1]`` — position ``accepted``'s entry is
    the correction token, so every iteration emits at least the one
    token the plain step would have.
    """
    S, K1 = toks.shape
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    pos_ix = pos[:, None] + jnp.arange(K1)[None, :]            # [S, K1]
    valid = (jnp.arange(K1)[None, :] < n_valid[:, None]) & active[:, None]
    blk = jnp.where(
        valid,
        jnp.take_along_axis(block_tables,
                            jnp.clip(pos_ix // Bs, 0, M - 1), axis=1), 0)
    off = jnp.where(valid, pos_ix % Bs, 0)
    h = (jnp.take(params["embed"], toks, axis=0)
         + jnp.take(params["pos"], pos_ix, axis=0))
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        k_pool = k_pool.at[i, blk, off].set(k)
        v_pool = v_pool.at[i, blk, off].set(v)
        kv_shape = (S, M * Bs, -1)
        kc = jnp.take(k_pool[i], block_tables, axis=0).reshape(kv_shape)
        vc = jnp.take(v_pool[i], block_tables, axis=0).reshape(kv_shape)
        h = h + _verify_attention(
            q, kc[:, :T], vc[:, :T], cfg.n_heads, pos) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    out = jnp.einsum("skd,vd->skv", h, params["embed"],
                     preferred_element_type=jnp.float32)
    nxt = jnp.argmax(out, axis=-1).astype(toks.dtype)
    return k_pool, v_pool, jnp.where(valid, nxt, jnp.zeros_like(nxt))


# -- serving: tensor-parallel sharded decode ----------------------------------
#
# PR 2 gated decode to a single-device params replica because feeding the
# train mesh's ``NamedSharding``s to the tiny per-token programs dragged
# every call through the spmd partitioner (~10x step wall). That was a
# workaround with a hard ceiling: params + KV pool had to fit ONE device.
# The fix is a DECODE-SPECIFIC mesh — Megatron-style tensor parallelism over
# attention heads and the MLP hidden dim, applied to autoregressive decode
# the way Pope et al. apply it: weights and KV cache partitioned ONCE, and
# every serving program jitted ONCE against matched ``in_shardings``/
# ``out_shardings`` (the pre-partitioned-pjit pattern — when a call's inputs
# already carry the shardings the program was compiled for, dispatch never
# goes back through the partitioner). The paged K/V pools
# ``[L, n_blocks + 1, Bs, D]`` shard over the head slice of ``D``; block
# tables, token ids, positions and the active mask stay REPLICATED
# traced-as-data, so the one-compiled-trace-per-engine-config invariant
# holds per mesh exactly as it does on one device.
#
# Head-sharding math: ``D = n_heads * dh`` and the decode kernels reshape
# ``[..., D] -> [..., n_heads, dh]``. Sharding ``D`` into ``tp`` contiguous
# slices of ``D/tp = (n_heads/tp) * dh`` therefore lands WHOLE heads on each
# device: the reshape is a local split (no resharding), attention is
# embarrassingly parallel over its head axis, and the only collectives are
# the two Megatron all-reduces per layer (after row-parallel ``w_o`` and
# ``w_ff2``). Requires ``tp | n_heads`` and ``tp | d_ff``.

DECODE_TP_AXIS = "tp"


def validate_decode_tp(cfg: TransformerConfig, tp: int,
                       name: str = "decode") -> None:
    """Fail fast on a tp width the head-sharding math cannot honour."""
    if tp < 1:
        Log.fatal(f"{name}: decode_tp must be >= 1, got {tp}")
    if cfg.n_heads % tp != 0:
        Log.fatal(f"{name}: decode_tp {tp} does not divide n_heads "
                  f"{cfg.n_heads} — head sharding needs whole heads per "
                  f"device")
    if cfg.d_ff % tp != 0:
        Log.fatal(f"{name}: decode_tp {tp} does not divide d_ff "
                  f"{cfg.d_ff} — the MLP hidden dim shards over tp")


def decode_param_shardings(mesh, tp_axis: str = DECODE_TP_AXIS
                           ) -> Dict[str, Any]:
    """Serving-param layout under the decode mesh.

    Column-parallel ``w_q``/``w_k``/``w_v``/``w_ff1`` (output dim — the
    head slice of ``D`` / the hidden dim — sharded), row-parallel
    ``w_o``/``w_ff2`` (input dim sharded, partial sums all-reduced by
    XLA). Embeddings REPLICATE, unlike the train layout's row shard: the
    decode logits einsum contracts over ``d`` for an ``[S, V]`` output
    that is already tiny, and a vocab shard would all-gather it every
    token; positions and norms replicate as always.
    """
    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return {
        "embed": ns(),
        "pos": ns(),
        "layers": {
            "ln1_g": ns(),
            "ln2_g": ns(),
            "w_q": ns(None, None, tp_axis),
            "w_k": ns(None, None, tp_axis),
            "w_v": ns(None, None, tp_axis),
            "w_o": ns(None, tp_axis, None),
            "w_ff1": ns(None, None, tp_axis),
            "w_ff2": ns(None, tp_axis, None),
        },
        "ln_f_g": ns(),
    }


def kv_pool_sharding(mesh, tp_axis: str = DECODE_TP_AXIS) -> NamedSharding:
    """Paged K/V pools ``[L, n_blocks + 1, Bs, D]`` sharded over the
    head slice of ``D`` — each device holds its heads' cache for every
    block, so table gathers/scatters stay device-local."""
    return NamedSharding(mesh, P(None, None, None, tp_axis))


def admit_insert_paged(cfg: TransformerConfig, params: Dict[str, Any],
                       k_pool: jax.Array, v_pool: jax.Array,
                       block_tables: jax.Array, tokens: jax.Array,
                       lengths: jax.Array
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused monolithic admission against the paged pool: whole-prompt
    :func:`prefill`, last-REAL-position first tokens, and the
    :func:`cache_insert_paged` table scatter — one dispatch. The body
    the engine jits; shared by the replicated and sharded variants so
    the two paths cannot drift."""
    logits, ks, vs = prefill(cfg, params, tokens)
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    first = jnp.argmax(last, axis=-1).astype(tokens.dtype)
    k_pool, v_pool = cache_insert_paged(k_pool, v_pool, block_tables,
                                        ks, vs)
    return first, k_pool, v_pool


def cow_block_copy(k_pool: jax.Array, v_pool: jax.Array, src: jax.Array,
                   dst: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Copy-on-write block duplication across both pools; ``src``/``dst``
    are traced block ids (one compiled trace serves every copy)."""
    return (k_pool.at[:, dst].set(k_pool[:, src]),
            v_pool.at[:, dst].set(v_pool[:, src]))


# -- serving: int8 per-block-scaled paged KV cache ----------------------------
#
# The ``_q`` variants below store the paged pools as int8 with ONE fp32
# scale per (layer, block) — ``k_scales``/``v_scales`` [L, N] arrays that
# ride every program as TRACED OPERANDS next to the block tables (never
# static), so the one-compiled-trace-per-engine-config invariant is
# untouched: which blocks hold what scale is data, exactly like which
# blocks a slot owns.
#
# Write semantics (quantize-on-write): gather the affected blocks,
# dequantize with the OLD scale, insert the new fp32 rows, then requantize
# the whole block against ``new_scale = max(old_scale, rowmax / 127)``.
# Two properties make this sound:
#
# * **identity when the scale is unchanged** — ``round(q * s / s) == q``
#   exactly for |q| <= 127 in fp32, so re-quantizing untouched rows (and
#   untouched blocks swept up by a whole-row scatter: scratch padding,
#   shared prefix blocks visible from several tables) rewrites their
#   exact bytes — repeated writes cause NO drift, and duplicate scatters
#   carry identical values (deterministic). A scale GROWTH re-rounds the
#   block's earlier rows once onto the coarser grid — the per-block-scale
#   trade, bounded by one rounding step.
# * **reset at block entry** — the first write into a block (block-local
#   offset 0) discards the previous occupant's scale instead of
#   max-merging it, so a freed-and-reallocated block cannot ratchet the
#   pool's scales up forever. The stale occupant's rows requantize as
#   clipped garbage under the new scale — finite, and never reachable by
#   a live attention mask before being overwritten (the standard pad
#   contract).
#
# Reads (dequantize-on-gather) multiply the gathered int8 view by its
# gathered scales before attention, so the operand shape (and masking)
# matches the fp32 kernels exactly; quality is measured as argmax-match
# rate against the fp32 oracle (docs/SERVING.md "Quantized KV & params").

_KV_QMAX = 127.0


def _kv_q_safe(scale: jax.Array) -> jax.Array:
    """Zero-divide guard: an all-zero (never-written / reset) block keeps
    scale 0 and dequantizes to exact zeros; dividing by 1 there quantizes
    zeros to zeros."""
    return jnp.where(scale > 0, scale, jnp.ones_like(scale))


def _kv_q_requant(rows: jax.Array, scale: jax.Array) -> jax.Array:
    """fp32 ``rows`` [..., Bs, D] against per-block ``scale`` [...] ->
    int8 (symmetric, clipped)."""
    q = jnp.round(rows / _kv_q_safe(scale)[..., None, None])
    return jnp.clip(q, -_KV_QMAX, _KV_QMAX).astype(jnp.int8)


def _kv_q_dequant(q: jax.Array, scale: jax.Array) -> jax.Array:
    """int8 blocks [..., Bs, D] * per-block ``scale`` [...] -> fp32."""
    return q.astype(jnp.float32) * scale[..., None, None]


def decode_step_paged_q(cfg: TransformerConfig, params: Dict[str, Any],
                        k_pool: jax.Array, v_pool: jax.Array,
                        k_scales: jax.Array, v_scales: jax.Array,
                        block_tables: jax.Array, tok: jax.Array,
                        pos: jax.Array, active: jax.Array,
                        t_logical: Optional[int] = None
                        ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                   jax.Array, jax.Array, jax.Array]:
    """Quantized :func:`decode_step_paged`: int8 pools [L, N, Bs, D] +
    fp32 ``k_scales``/``v_scales`` [L, N]. Each live slot writes exactly
    ONE block (exclusively owned — the engine CoWs shared blocks before
    any write), so the write is a per-slot gather/requant/scatter of that
    block; dead lanes park on scratch, where order-undefined duplicates
    are unobservable exactly as in the fp32 kernel.

    Returns ``(k_pool, v_pool, k_scales, v_scales, next_tok, pos)``.
    """
    S = tok.shape[0]
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    blk = jnp.take_along_axis(block_tables, (pos // Bs)[:, None],
                              axis=1)[:, 0]
    write_blk = jnp.where(active, blk, 0)      # dead lanes -> scratch
    write_off = jnp.where(active, pos % Bs, 0)
    lanes = jnp.arange(S)
    h = (jnp.take(params["embed"], tok, axis=0)
         + jnp.take(params["pos"], pos, axis=0))

    def write(pool, scales, rows):
        cur_s = jnp.take(scales, write_blk, axis=0)            # [S]
        cur = _kv_q_dequant(jnp.take(pool, write_blk, axis=0), cur_s)
        rows32 = rows.astype(jnp.float32)
        cur = cur.at[lanes, write_off].set(rows32)
        # entering a fresh block (offset 0) drops the prior occupant's
        # scale; otherwise scales only grow within an occupancy
        base = jnp.where(write_off == 0, 0.0, cur_s)
        new_s = jnp.maximum(base,
                            jnp.max(jnp.abs(rows32), axis=-1) / _KV_QMAX)
        return (pool.at[write_blk].set(_kv_q_requant(cur, new_s)),
                scales.at[write_blk].set(new_s))

    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        kp, ks = write(k_pool[i], k_scales[i], k)
        vp, vs = write(v_pool[i], v_scales[i], v)
        k_pool, k_scales = k_pool.at[i].set(kp), k_scales.at[i].set(ks)
        v_pool, v_scales = v_pool.at[i].set(vp), v_scales.at[i].set(vs)
        kv_shape = (S, M * Bs, -1)
        kc = _kv_q_dequant(
            jnp.take(k_pool[i], block_tables, axis=0),
            jnp.take(k_scales[i], block_tables, axis=0)
        ).astype(h.dtype).reshape(kv_shape)
        vc = _kv_q_dequant(
            jnp.take(v_pool[i], block_tables, axis=0),
            jnp.take(v_scales[i], block_tables, axis=0)
        ).astype(h.dtype).reshape(kv_shape)
        h = h + _cached_attention(
            q, kc[:, :T], vc[:, :T], cfg.n_heads, pos) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    out = jnp.einsum("sd,vd->sv", h, params["embed"],
                     preferred_element_type=jnp.float32)
    nxt = jnp.argmax(out, axis=-1).astype(tok.dtype)
    nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
    pos = jnp.where(active, pos + 1, pos)
    return k_pool, v_pool, k_scales, v_scales, nxt, pos


def prefill_chunk_paged_q(cfg: TransformerConfig, params: Dict[str, Any],
                          k_pool: jax.Array, v_pool: jax.Array,
                          k_scales: jax.Array, v_scales: jax.Array,
                          block_tables: jax.Array, slot: jax.Array,
                          tokens: jax.Array, offset: jax.Array,
                          length: jax.Array, t_logical: Optional[int] = None
                          ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                     jax.Array, jax.Array]:
    """Quantized :func:`prefill_chunk_paged`: the chunk's writes span
    several blocks of ONE slot, so the kernel works on the slot's whole
    table row — gather all M blocks, dequantize, scatter the chunk's
    rows into the flat [M*Bs, D] view (invalid lanes get out-of-range
    indices and DROP — the paged pad contract without the scratch
    detour), fold per-block scale contributions in with a scatter-max,
    requantize the row, scatter it back. Untouched blocks requantize to
    their exact old bytes (identity), so the row-wide scatter is safe.

    Returns ``(k_pool, v_pool, k_scales, v_scales, last_logits)``.
    """
    C = tokens.shape[0]
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    bt_row = jax.lax.dynamic_index_in_dim(block_tables, slot, 0,
                                          keepdims=False)        # [M]
    pos_ix = offset + jnp.arange(C)
    valid = jnp.arange(C) < length
    flat_ix = jnp.where(valid, pos_ix, M * Bs)       # OOB lanes drop
    blk_local = jnp.where(valid, jnp.clip(pos_ix // Bs, 0, M - 1), M)
    fresh = (valid & (pos_ix % Bs == 0)).astype(jnp.float32)
    h = (jnp.take(params["embed"], tokens, axis=0)
         + jnp.take(params["pos"], pos_ix, axis=0))

    def write(pool, scales, rows):
        row_s = jnp.take(scales, bt_row, axis=0)                 # [M]
        flat = _kv_q_dequant(jnp.take(pool, bt_row, axis=0),
                             row_s).reshape(M * Bs, -1)
        rows32 = rows.astype(jnp.float32)
        flat = flat.at[flat_ix].set(rows32, mode="drop")
        reset = jnp.zeros((M,), jnp.float32).at[blk_local].max(
            fresh, mode="drop") > 0
        contrib = jnp.zeros((M,), jnp.float32).at[blk_local].max(
            jnp.where(valid, jnp.max(jnp.abs(rows32), axis=-1), 0.0),
            mode="drop")
        new_s = jnp.maximum(jnp.where(reset, 0.0, row_s),
                            contrib / _KV_QMAX)
        new_q = _kv_q_requant(flat.reshape(M, Bs, -1), new_s)
        return (pool.at[bt_row].set(new_q), scales.at[bt_row].set(new_s),
                _kv_q_dequant(new_q, new_s).reshape(M * Bs, -1))

    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        kp, ks, kc = write(k_pool[i], k_scales[i], k)
        vp, vs, vc = write(v_pool[i], v_scales[i], v)
        k_pool, k_scales = k_pool.at[i].set(kp), k_scales.at[i].set(ks)
        v_pool, v_scales = v_pool.at[i].set(vp), v_scales.at[i].set(vs)
        h = h + _chunk_attention(
            q, kc[:T].astype(h.dtype), vc[:T].astype(h.dtype),
            cfg.n_heads, offset) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    last = jnp.take(h, length - 1, axis=0)
    logits = jnp.einsum("d,vd->v", last, params["embed"],
                        preferred_element_type=jnp.float32)
    return k_pool, v_pool, k_scales, v_scales, logits


def verify_step_paged_q(cfg: TransformerConfig, params: Dict[str, Any],
                        k_pool: jax.Array, v_pool: jax.Array,
                        k_scales: jax.Array, v_scales: jax.Array,
                        block_tables: jax.Array, toks: jax.Array,
                        pos: jax.Array, active: jax.Array,
                        n_valid: jax.Array, t_logical: Optional[int] = None
                        ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                   jax.Array, jax.Array]:
    """Quantized :func:`verify_step_paged`: the whole-row form of
    :func:`prefill_chunk_paged_q` per slot — a window can write several
    positions of one block, so per-position block scatters would race;
    instead every slot's full table row round-trips through fp32. Blocks
    a slot does not validly write (shared prefix blocks visible from
    several rows, scratch padding) requantize to their exact old bytes,
    so the cross-slot duplicate scatters all carry identical values.

    Returns ``(k_pool, v_pool, k_scales, v_scales, out_tok [S, K1])``.
    """
    S, K1 = toks.shape
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    T = M * Bs if t_logical is None else int(t_logical)
    pos_ix = pos[:, None] + jnp.arange(K1)[None, :]            # [S, K1]
    valid = (jnp.arange(K1)[None, :] < n_valid[:, None]) & active[:, None]
    flat_ix = jnp.where(valid, pos_ix, M * Bs)       # OOB lanes drop
    blk_local = jnp.where(valid, jnp.clip(pos_ix // Bs, 0, M - 1), M)
    fresh = (valid & (pos_ix % Bs == 0)).astype(jnp.float32)
    lanes = jnp.arange(S)[:, None]
    h = (jnp.take(params["embed"], toks, axis=0)
         + jnp.take(params["pos"], pos_ix, axis=0))

    def write(pool, scales, rows):
        rows_s = jnp.take(scales, block_tables, axis=0)        # [S, M]
        flat = _kv_q_dequant(jnp.take(pool, block_tables, axis=0),
                             rows_s).reshape(S, M * Bs, -1)
        rows32 = rows.astype(jnp.float32)
        flat = flat.at[lanes, flat_ix].set(rows32, mode="drop")
        reset = jnp.zeros((S, M), jnp.float32).at[lanes, blk_local].max(
            fresh, mode="drop") > 0
        contrib = jnp.zeros((S, M), jnp.float32).at[lanes, blk_local].max(
            jnp.where(valid, jnp.max(jnp.abs(rows32), axis=-1), 0.0),
            mode="drop")
        new_s = jnp.maximum(jnp.where(reset, 0.0, rows_s),
                            contrib / _KV_QMAX)
        new_q = _kv_q_requant(flat.reshape(S, M, Bs, -1), new_s)
        return (pool.at[block_tables].set(new_q),
                scales.at[block_tables].set(new_s),
                _kv_q_dequant(new_q, new_s).reshape(S, M * Bs, -1))

    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        x = _rmsnorm(h, layer["ln1_g"])
        q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
        kp, ks, kc = write(k_pool[i], k_scales[i], k)
        vp, vs, vc = write(v_pool[i], v_scales[i], v)
        k_pool, k_scales = k_pool.at[i].set(kp), k_scales.at[i].set(ks)
        v_pool, v_scales = v_pool.at[i].set(vp), v_scales.at[i].set(vs)
        h = h + _verify_attention(
            q, kc[:, :T].astype(h.dtype), vc[:, :T].astype(h.dtype),
            cfg.n_heads, pos) @ layer["w_o"]
        x = _rmsnorm(h, layer["ln2_g"])
        h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
    h = _rmsnorm(h, params["ln_f_g"])
    out = jnp.einsum("skd,vd->skv", h, params["embed"],
                     preferred_element_type=jnp.float32)
    nxt = jnp.argmax(out, axis=-1).astype(toks.dtype)
    return (k_pool, v_pool, k_scales, v_scales,
            jnp.where(valid, nxt, jnp.zeros_like(nxt)))


def cache_insert_paged_q(k_pool: jax.Array, v_pool: jax.Array,
                         k_scales: jax.Array, v_scales: jax.Array,
                         block_tables: jax.Array, ks: jax.Array,
                         vs: jax.Array
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array]:
    """Quantized :func:`cache_insert_paged`: b whole prompts' fp32 K/V
    [L, b, P, D] quantize through per-row block tables. Positions write
    from 0, so every written block's offset 0 is covered — its scale
    resets from the fresh data (the reallocation contract). Pad rows
    point at scratch, where order-undefined duplicates stay unobservable.
    """
    L, b, P, _ = ks.shape
    Bs = k_pool.shape[2]
    M = block_tables.shape[1]
    p_ix = jnp.arange(P)
    loc = jnp.clip(p_ix // Bs, 0, M - 1)
    flat_ix = jnp.broadcast_to(loc * Bs + p_ix % Bs, (b, P))
    fresh = jnp.broadcast_to((p_ix % Bs == 0).astype(jnp.float32), (b, P))
    rows_ix = jnp.arange(b)[:, None]
    loc_b = jnp.broadcast_to(loc, (b, P))

    def write(pool, scales, rows):
        rows_s = jnp.take(scales, block_tables, axis=0)        # [b, M]
        flat = _kv_q_dequant(jnp.take(pool, block_tables, axis=0),
                             rows_s).reshape(b, M * Bs, -1)
        rows32 = rows.astype(jnp.float32)
        flat = flat.at[rows_ix, flat_ix].set(rows32)
        reset = jnp.zeros((b, M), jnp.float32).at[rows_ix, loc_b].max(
            fresh) > 0
        contrib = jnp.zeros((b, M), jnp.float32).at[rows_ix, loc_b].max(
            jnp.max(jnp.abs(rows32), axis=-1))
        new_s = jnp.maximum(jnp.where(reset, 0.0, rows_s),
                            contrib / _KV_QMAX)
        new_q = _kv_q_requant(flat.reshape(b, M, Bs, -1), new_s)
        return (pool.at[block_tables].set(new_q),
                scales.at[block_tables].set(new_s))

    for i in range(L):
        kp, ksc = write(k_pool[i], k_scales[i], ks[i])
        vp, vsc = write(v_pool[i], v_scales[i], vs[i])
        k_pool, k_scales = k_pool.at[i].set(kp), k_scales.at[i].set(ksc)
        v_pool, v_scales = v_pool.at[i].set(vp), v_scales.at[i].set(vsc)
    return k_pool, v_pool, k_scales, v_scales


def admit_insert_paged_q(cfg: TransformerConfig, params: Dict[str, Any],
                         k_pool: jax.Array, v_pool: jax.Array,
                         k_scales: jax.Array, v_scales: jax.Array,
                         block_tables: jax.Array, tokens: jax.Array,
                         lengths: jax.Array
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array, jax.Array]:
    """Quantized :func:`admit_insert_paged`: the fp32 whole-prompt
    prefill and first-token argmax are unchanged (the first token is
    computed BEFORE quantization, like the chunked path's final-chunk
    logits); only the cache insert quantizes."""
    logits, ks, vs = prefill(cfg, params, tokens)
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    first = jnp.argmax(last, axis=-1).astype(tokens.dtype)
    k_pool, v_pool, k_scales, v_scales = cache_insert_paged_q(
        k_pool, v_pool, k_scales, v_scales, block_tables, ks, vs)
    return first, k_pool, v_pool, k_scales, v_scales


def cow_block_copy_q(k_pool: jax.Array, v_pool: jax.Array,
                     k_scales: jax.Array, v_scales: jax.Array,
                     src: jax.Array, dst: jax.Array
                     ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                jax.Array]:
    """Quantized :func:`cow_block_copy`: the duplicate carries its
    source's int8 bytes AND its scale column — content-identical by
    construction."""
    return (k_pool.at[:, dst].set(k_pool[:, src]),
            v_pool.at[:, dst].set(v_pool[:, src]),
            k_scales.at[:, dst].set(k_scales[:, src]),
            v_scales.at[:, dst].set(v_scales[:, src]))


# -- serving: quantized decode param snapshots --------------------------------


def _is_quant_param_leaf(x: Any) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {"q", "s"}


def dequantize_decode_params(qparams: Any, dtype=jnp.float32) -> Any:
    """Traced inverse of :func:`serving.snapshot.quantize_decode_params`:
    each ``{"q": int8, "s": fp32}`` leaf multiplies out to ``dtype``.
    Expressed as ordinary jnp ops at the TOP of a jitted decode program,
    so XLA folds the dequant into the compiled module — per-device param
    residency is the int8 pytree, and the program's one-trace accounting
    never notices (``decode_step_retraces`` stays 0)."""
    return jax.tree.map(
        lambda leaf: (leaf["q"].astype(jnp.float32)
                      * leaf["s"]).astype(dtype),
        qparams, is_leaf=_is_quant_param_leaf)


def decode_param_quant_shardings(mesh, tp_axis: str = DECODE_TP_AXIS
                                 ) -> Dict[str, Any]:
    """Decode-mesh shardings for the QUANTIZED param pytree: each leaf's
    ``q`` carries the weight's :func:`decode_param_shardings` spec (same
    shape as the weight, so the spec applies unchanged) and the tiny
    ``s`` scales REPLICATE — a keepdims per-column scale has a size-1
    dim exactly where the row-parallel specs shard, so replication is
    the only layout that fits every leaf (and costs ~nothing)."""
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda s: {"q": s, "s": rep},
                        decode_param_shardings(mesh, tp_axis))


def make_sharded_decode_programs(cfg: TransformerConfig, mesh,
                                 t_logical: int, donate: bool = False,
                                 tp_axis: str = DECODE_TP_AXIS,
                                 kv_quant: str = "none",
                                 param_quant: str = "none",
                                 prefill_sp: str = "none"
                                 ) -> Dict[str, Any]:
    """Pre-partitioned decode-mesh variants of the paged serving programs.

    Returns ``{"step", "chunk", "admit", "cow", "verify",
    "param_shardings", "pool_sharding"}`` — each program jitted exactly
    once with matched
    ``in_shardings``/``out_shardings``: params carry
    :func:`decode_param_shardings`, both pools carry
    :func:`kv_pool_sharding` (outputs included, so iteration N's pools
    re-enter iteration N+1 already partitioned), and everything traced
    as data (block tables, tokens, positions, masks, scalars)
    replicates. A caller that pins its params via
    ``serving.snapshot.shard_for_decode`` and round-trips the pools
    through these programs never re-enters the spmd partitioner after
    the first compile — the construction-time contract ``DecodeEngine``
    builds these under (``__init__``/``warmup`` only; RT106).

    ``kv_quant="int8"`` returns the quantized program set instead: each
    program additionally takes/returns the fp32 ``k_scales``/``v_scales``
    [L, N] operands (REPLICATED — they are KBs next to the pools' MBs,
    and the per-block scale multiplies the full ``D`` of its block, so a
    head-shard would buy nothing), with the int8 pools still sharded
    over the head slice of ``D``. ``param_quant="int8"`` makes every
    program accept the quantized param pytree
    (:func:`serving.snapshot.quantize_decode_params` leaves), sharded per
    :func:`decode_param_quant_shardings`, with
    :func:`dequantize_decode_params` folded in at compile time. Both
    default off; the default programs are exactly the pre-quantization
    ones.

    ``prefill_sp="ring"|"ulysses"`` adds a ``"chunk_sp"`` program — the
    sequence-parallel :func:`prefill_chunk_paged_sp` jitted with the
    SAME shardings/donation as ``"chunk"``; the chunk size rides the
    token-array shape (the engine passes ``budget * tp`` tokens, one
    budget's worth of rows per device). It rides next to, not
    instead of, the single-lane ``"chunk"``: the engine routes prompts
    by ``prefill_sp_threshold``. Incompatible with ``kv_quant="int8"``.
    """
    if prefill_sp != "none" and kv_quant == "int8":
        raise ValueError("prefill_sp is incompatible with kv_quant=int8")
    if param_quant == "int8":
        ps = decode_param_quant_shardings(mesh, tp_axis)
        pf = lambda p: dequantize_decode_params(p, cfg.dtype)
    else:
        ps = decode_param_shardings(mesh, tp_axis)
        pf = lambda p: p
    pool = kv_pool_sharding(mesh, tp_axis)
    rep = NamedSharding(mesh, P())
    T = int(t_logical)
    if kv_quant == "int8":
        # pools at positions 1-2, scales at 3-4: donate all four (the
        # scales round-trip every program exactly like the pools)
        kv_donate = (1, 2, 3, 4) if donate else ()
        step = jax.jit(
            lambda params, kc, vc, ksc, vsc, bt, tok, pos, active:
            decode_step_paged_q(cfg, pf(params), kc, vc, ksc, vsc, bt,
                                tok, pos, active, t_logical=T),
            in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep, rep),
            out_shardings=(pool, pool, rep, rep, rep, rep),
            donate_argnums=kv_donate)
        chunk = jax.jit(
            lambda params, kc, vc, ksc, vsc, bt, slot, toks, off, n:
            prefill_chunk_paged_q(cfg, pf(params), kc, vc, ksc, vsc, bt,
                                  slot, toks, off, n, t_logical=T),
            in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep, rep,
                          rep),
            out_shardings=(pool, pool, rep, rep, rep),
            donate_argnums=kv_donate)
        admit = jax.jit(
            lambda params, kc, vc, ksc, vsc, bts, toks, lens:
            admit_insert_paged_q(cfg, pf(params), kc, vc, ksc, vsc, bts,
                                 toks, lens),
            in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep),
            out_shardings=(rep, pool, pool, rep, rep),
            donate_argnums=kv_donate)
        cow = jax.jit(
            lambda kc, vc, ksc, vsc, src, dst: cow_block_copy_q(
                kc, vc, ksc, vsc, src, dst),
            in_shardings=(pool, pool, rep, rep, rep, rep),
            out_shardings=(pool, pool, rep, rep),
            donate_argnums=(0, 1, 2, 3) if donate else ())
        verify = jax.jit(
            lambda params, kc, vc, ksc, vsc, bt, toks, pos, active, nv:
            verify_step_paged_q(cfg, pf(params), kc, vc, ksc, vsc, bt,
                                toks, pos, active, nv, t_logical=T),
            in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep, rep,
                          rep),
            out_shardings=(pool, pool, rep, rep, rep),
            donate_argnums=kv_donate)
        return {"step": step, "chunk": chunk, "admit": admit,
                "cow": cow, "verify": verify, "param_shardings": ps,
                "pool_sharding": pool}
    kv_donate = (1, 2) if donate else ()
    step = jax.jit(
        lambda params, kc, vc, bt, tok, pos, active: decode_step_paged(
            cfg, pf(params), kc, vc, bt, tok, pos, active, t_logical=T),
        in_shardings=(ps, pool, pool, rep, rep, rep, rep),
        out_shardings=(pool, pool, rep, rep),
        donate_argnums=kv_donate)
    chunk = jax.jit(
        lambda params, kc, vc, bt, slot, toks, off, n: prefill_chunk_paged(
            cfg, pf(params), kc, vc, bt, slot, toks, off, n, t_logical=T),
        in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep),
        out_shardings=(pool, pool, rep),
        donate_argnums=kv_donate)
    admit = jax.jit(
        lambda params, kc, vc, bts, toks, lens: admit_insert_paged(
            cfg, pf(params), kc, vc, bts, toks, lens),
        in_shardings=(ps, pool, pool, rep, rep, rep),
        out_shardings=(rep, pool, pool),
        donate_argnums=kv_donate)
    # every program wraps in a FRESH lambda (cow included): jit caches
    # key on the function object, so jitting a shared module-level
    # function directly would pool every engine's compiled traces on
    # one handle and break per-engine one-trace accounting
    cow = jax.jit(
        lambda kc, vc, src, dst: cow_block_copy(kc, vc, src, dst),
        in_shardings=(pool, pool, rep, rep),
        out_shardings=(pool, pool),
        donate_argnums=(0, 1) if donate else ())
    # the speculative verify step pins and partitions exactly like the
    # fused step: params sharded, pools round-tripped pool-sharded, the
    # [S, K + 1] window / positions / valid counts replicated traced-as-
    # data. K rides the window SHAPE, so the engine (which always passes
    # its fixed spec_k + 1 columns) gets exactly one compiled trace; a
    # spec_k=0 engine never dispatches it and its cache stays empty.
    verify = jax.jit(
        lambda params, kc, vc, bt, toks, pos, active, nv:
        verify_step_paged(cfg, pf(params), kc, vc, bt, toks, pos, active,
                          nv, t_logical=T),
        in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep),
        out_shardings=(pool, pool, rep),
        donate_argnums=kv_donate)
    progs = {"step": step, "chunk": chunk, "admit": admit, "cow": cow,
             "verify": verify, "param_shardings": ps,
             "pool_sharding": pool}
    if prefill_sp != "none":
        progs["chunk_sp"] = jax.jit(
            lambda params, kc, vc, bt, slot, toks, off, n:
            prefill_chunk_paged_sp(cfg, pf(params), kc, vc, bt, slot,
                                   toks, off, n, mesh, prefill_sp,
                                   t_logical=T, tp_axis=tp_axis),
            in_shardings=(ps, pool, pool, rep, rep, rep, rep, rep),
            out_shardings=(pool, pool, rep),
            donate_argnums=kv_donate)
    return progs


def cache_insert(k_cache: jax.Array, v_cache: jax.Array, slots: jax.Array,
                 ks: jax.Array, vs: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """Write b prefilled sequences' K/V [L, b, P, D] into slots ``slots``.

    ``slots`` [b] are traced slot indices (one compiled insert per
    (batch bucket b, prompt bucket P), reused for every slot choice).
    The rows land as a CHAIN of dynamic-update-slices, iterated so row 0
    writes LAST: a caller padding a partial batch up to bucket b points
    the pad rows at ``slots[0]`` and the real row deterministically
    overwrites them (an XLA scatter with duplicate indices would be
    order-undefined). Positions past a prompt's true length hold prefill
    garbage — decode overwrites position ``pos`` before the attention
    mask ever reaches it, so the garbage is never observable (the
    :func:`prefill` contract).
    """
    zero = jnp.zeros((), slots.dtype)
    for i in reversed(range(ks.shape[1])):
        start = (zero, slots[i], zero, zero)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, ks[:, i][:, None], start)
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, vs[:, i][:, None], start)
    return k_cache, v_cache


def greedy_decode(cfg: TransformerConfig, params: Dict[str, Any],
                  tokens: jax.Array, lengths: jax.Array,
                  max_new: int, eos_id: Optional[int] = None) -> jax.Array:
    """Greedy continuation: up to ``max_new`` tokens per prompt.

    ``tokens`` [B, P] right-padded prompt ids, ``lengths`` [B] true prompt
    lengths (callers guarantee ``lengths + max_new <= cfg.max_seq``).
    Returns [B, max_new] generated ids. jit-able with static ``max_new``
    (the serving workload jits one instance per (B, P) shape bucket).

    With ``eos_id`` set, a lane that emits ``eos_id`` is FROZEN: later
    emissions are pad (0) and its ``pos`` stops advancing, so the lane
    stops widening the attention mask while the rest of the batch
    finishes — the batch still runs all ``max_new`` scan iterations
    (static shape), but finished lanes' output prefixes are bit-identical
    to the ``eos_id=None`` run up to and including the eos token.
    """
    B, P = tokens.shape
    # cache bound: positions can only ever reach P + max_new - 1 (callers
    # guarantee lengths <= P), so sizing the cache/attention to max_seq
    # would pay max_seq-width attention per generated token for nothing
    L, D, T = cfg.n_layers, cfg.d_model, P + max_new
    logits, ks, vs = prefill(cfg, params, tokens)
    k_cache = jnp.zeros((L, B, T, D), cfg.dtype).at[:, :, :P].set(ks)
    v_cache = jnp.zeros((L, B, T, D), cfg.dtype).at[:, :, :P].set(vs)
    # next token comes from each example's LAST REAL position, not slot P-1
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    first = jnp.argmax(last, axis=-1).astype(tokens.dtype)

    batch_ix = jnp.arange(B)

    def step(carry, _):
        k_cache, v_cache, pos, tok, done = carry
        h = (jnp.take(params["embed"], tok, axis=0)
             + jnp.take(params["pos"], pos, axis=0))
        for i in range(L):
            layer = jax.tree.map(lambda a: a[i], params["layers"])
            x = _rmsnorm(h, layer["ln1_g"])
            q, k, v = x @ layer["w_q"], x @ layer["w_k"], x @ layer["w_v"]
            k_cache = k_cache.at[i, batch_ix, pos].set(k)
            v_cache = v_cache.at[i, batch_ix, pos].set(v)
            h = h + _cached_attention(
                q, k_cache[i], v_cache[i], cfg.n_heads, pos) @ layer["w_o"]
            x = _rmsnorm(h, layer["ln2_g"])
            h = h + jax.nn.gelu(x @ layer["w_ff1"]) @ layer["w_ff2"]
        h = _rmsnorm(h, params["ln_f_g"])
        out = jnp.einsum("bd,vd->bv", h, params["embed"],
                         preferred_element_type=jnp.float32)
        nxt = jnp.argmax(out, axis=-1).astype(tok.dtype)
        # frozen lanes emit pad and stop paying attention width; live
        # lanes run the exact eos_id=None math (prefix-identical outputs)
        emit = jnp.where(done, jnp.zeros_like(nxt), nxt)
        new_done = done if eos_id is None else done | (emit == eos_id)
        new_pos = jnp.where(done, pos, pos + 1)
        return (k_cache, v_cache, new_pos, emit, new_done), emit

    if max_new <= 1:
        return first[:, None]
    done0 = (first == eos_id) if eos_id is not None else jnp.zeros(
        (B,), bool)
    _, rest = jax.lax.scan(
        step, (k_cache, v_cache, lengths, first, done0), None,
        length=max_new - 1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


class TransformerLM:
    """Trainer over a (worker, server) mesh: dp batches, tp weights."""

    def __init__(self, config: TransformerConfig, mesh=None,
                 dp_axis: str = WORKER_AXIS, tp_axis: str = SERVER_AXIS):
        from ..runtime import Session

        self.config = config
        self.mesh = mesh if mesh is not None else Session.get().mesh
        if config.d_model % config.n_heads != 0:
            Log.fatal("d_model must divide by n_heads")
        # Serving contract (mirrors TableBase): ``version`` counts train
        # steps; ``snapshot_params`` copies under the lock so the serving
        # layer never reads a params buffer a concurrent train step is
        # about to donate.
        import threading

        self._lock = threading.Lock()
        self.version = 0
        self._shardings = param_shardings(config, self.mesh, tp_axis)
        params = init_params(config)
        self.params = jax.tree.map(jax.device_put, params, self._shardings)
        self._momentum = jax.tree.map(
            lambda p, s: jax.device_put(jnp.zeros_like(p), s),
            self.params, self._shardings)
        batch_sharding = NamedSharding(self.mesh, P(dp_axis, None))

        cfg = config

        def train_step(params, mom, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, tokens))(params)
            mom = jax.tree.map(
                lambda m, g: cfg.momentum * m + g.astype(m.dtype), mom, grads)
            params = jax.tree.map(
                lambda p, m: p - cfg.learning_rate * m.astype(p.dtype),
                params, mom)
            return params, mom, loss

        self._step = jax.jit(
            train_step,
            in_shardings=(self._shardings, self._shardings, batch_sharding),
            out_shardings=(self._shardings, self._shardings, None),
            donate_argnums=(0, 1),
        )

    def train_batch(self, tokens: np.ndarray) -> jax.Array:
        """One dp+tp step on [B, T] token ids; returns async scalar loss."""
        with self._lock:
            self.params, self._momentum, loss = self._step(
                self.params, self._momentum, jnp.asarray(tokens, jnp.int32))
            self.version += 1
        return loss

    def snapshot_params(self) -> Tuple[Dict[str, Any], int]:
        """``(params copy, version)`` for the serving read path.

        The copies dispatch under the train lock — device-stream ordering
        guarantees they read the pre-donation buffers even while a train
        step races (the :meth:`tables.base.TableBase.snapshot_array`
        contract, for model params instead of a table).
        """
        with self._lock:
            return jax.tree.map(jnp.copy, self.params), self.version

    def logits(self, tokens: np.ndarray) -> jax.Array:
        return forward(self.config, self.params,
                       jnp.asarray(tokens, jnp.int32))
