"""Word2vec (skip-gram / CBOW, negative sampling / hierarchical softmax).

TPU-native re-design of the reference WordEmbedding application's model core
(``Applications/WordEmbedding/src/wordembedding.cpp`` in the Multiverso
reference — ``FeedForward :57``, ``BPOutputLayer :74``, ``TrainSample :120``).
The reference trains scalar dot products in per-thread C++ loops against
row-cached parameters pulled from matrix tables. Here one jitted SPMD step
trains a whole batch of (center, target) pairs at once:

* embeddings are the tables' HBM-resident sharded arrays (input + output
  matrices — the same two tables the reference allocates,
  ``WE/src/communicator.cpp:17-33``), threaded through the step with donated
  buffers;
* negative sampling draws on-device from a unigram^0.75 alias table;
* gradients are closed-form (sigmoid loss), applied as row scatter-adds — the
  sparse "touched rows only" traffic the reference routes through the PS is
  the native dataflow of the gather/scatter pair;
* AdaGrad keeps full G-matrices like the reference's two AdaGrad tables
  (``communicator.cpp:17-33``), updated on the same touched rows;
* the batch is sharded over the ``worker`` mesh axis: XLA inserts the ICI
  collectives that replace worker->server delta pushes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..log import Log
from ..topology import SERVER_AXIS, WORKER_AXIS

_ADAGRAD_EPS = 1e-8


def _dp_enter(key, tables):
    """Enter a ``dp_sync="dispatch"`` manual-worker region: advance the
    replicated key once (the dispatch's global stream), fold the worker
    index into the local draw key (decorrelated sampling per worker), and
    mark the table copies worker-varying so local training may diverge
    until :func:`_dp_exchange`. Returns (local_key, key_out, tables)."""
    key_out = jax.random.split(key)[0]
    lkey = jax.random.fold_in(key, jax.lax.axis_index(WORKER_AXIS))
    varying = tuple(
        None if a is None
        else jax.lax.pcast(a, (WORKER_AXIS,), to="varying")
        for a in tables)
    return lkey, key_out, varying


def _keyed_exchange_one(a, a0, cap: int):
    """Dirty-row-union delta exchange for ONE table (exact).

    The dense exchange psums the full ``[V, D]`` delta — fine over ICI,
    ruinous over DCN (~57 MB/table at the real 71k x 200 shape). The
    reference's cross-host Adds already send only touched rows
    (``src/table/sparse_matrix_table.cpp:145-153`` in the Multiverso
    reference); this is the jitted SPMD form of that:

    1. psum a ``[V]`` row-moved mask over the worker axis (V*4 wire) —
       its result is REPLICATED, so every worker derives the identical
       fixed-size index list and the row psum below is row-aligned;
    2. gather the first ``cap`` union rows of the local delta (static
       shape; absent rows gather 0) and psum just those (cap*D*4 wire);
    3. scatter-add the summed rows onto the saved base.

    Exact whenever the union fits the cap; an overflow falls back to
    the dense psum INSIDE the dispatch (`lax.cond` on the replicated
    count — every worker takes the same branch, so the collective
    stays uniform) — never silently drops movement. Wire per table:
    ``V*4 + cap*D*4`` vs dense ``V*D*4``.
    """
    V = a.shape[0]
    delta = a - a0
    moved = jnp.any(delta != 0, axis=1)
    union = jax.lax.psum(moved.astype(jnp.float32), WORKER_AXIS) > 0
    n_dirty = jnp.sum(union.astype(jnp.int32))
    (idx,) = jnp.where(union, size=min(cap, V), fill_value=V)
    rows = jnp.take(delta, idx, axis=0, mode="fill", fill_value=0)
    summed = jax.lax.psum(rows, WORKER_AXIS)
    return jax.lax.cond(
        n_dirty <= idx.shape[0],
        lambda: a0.at[idx].add(summed.astype(a0.dtype), mode="drop"),
        lambda: a0 + jax.lax.psum(delta, WORKER_AXIS))


def _dp_exchange(tables, saved, mode: str = "dense", cap: int = 0):
    """ONE summed-delta exchange per dispatch: ``a0 + psum(a - a0)``
    (``mode="dense"``) or the dirty-row-union keyed form
    (``mode="keyed"``, :func:`_keyed_exchange_one`). Both are exact for
    commutative updaters (the Sigma-invariant); this is the only wire
    traffic of the dispatch-mode dp data plane (docs/DISTRIBUTED.md
    "Bytes on the wire")."""
    if mode == "keyed":
        return tuple(
            None if a0 is None else _keyed_exchange_one(a, a0, cap)
            for a, a0 in zip(tables, saved))
    return tuple(
        None if a0 is None else a0 + jax.lax.psum(a - a0, WORKER_AXIS)
        for a, a0 in zip(tables, saved))


@dataclass
class Word2VecConfig:
    """Mirrors the reference CLI options (``WE/src/util.cpp`` Option)."""

    vocab_size: int = 0
    embedding_size: int = 100
    window: int = 5
    negative: int = 5            # 0 + hs=True -> hierarchical softmax only
    hs: bool = False
    cbow: bool = False
    init_lr: float = 0.025
    min_lr_frac: float = 1e-4    # lr floor = init_lr * frac (reference :38-56)
    use_adagrad: bool = False
    batch_size: int = 1024
    steps_per_call: int = 1      # batches fused into one dispatch (lax.scan)
    max_code_length: int = 40    # huffman path pad (HS)
    seed: int = 7
    # Device-sampler candidate oversampling (corpus path only). Window /
    # sentence / subsampling tests reject ~half the sampled pairs; with
    # oversample > 1 the sampler draws ``oversample * batch_size`` cheap
    # int candidates and compacts the survivors into a dense batch, so the
    # expensive per-row gather/scatter work runs at ~full utilisation.
    # 0 disables (every candidate slot trains with a validity mask).
    oversample: float = 0.0
    # > 0 enables the pre-drawn negative pool for the device-corpus path
    # (see build_negative_pool); the pool is grown to at least twice the
    # draws per fused call. 0 = exact per-draw alias sampling.
    neg_pool_size: int = 0
    # group size G > 1 shares each K-negative draw across G consecutive
    # pairs, cutting the dominant negative-row gather/scatter traffic by G
    # (same objective in expectation; 0/1 = exact per-pair draws, the
    # reference semantics). Requires batch_size % G == 0.
    shared_negatives: int = 0
    # normalize each row's summed batch gradient by the row's occurrence
    # count before applying lr. The reference applies pairs SEQUENTIALLY
    # (one lr-scaled update per pair); a batched scatter SUMS colliding
    # pair grads, so hot (frequent) rows receive thousands-of-pairs-sized
    # steps and TRAINING DIVERGES once hot rows collect enough colliding
    # grads (zipf head words at 64k batch NaN within one dispatch — vocab
    # SIZE is not what matters, hot-row mass is). Enable for large batches;
    # None = auto: the train() driver estimates the hottest row's expected
    # hits from the sampling laws and enables it past ~512 (stable ~150,
    # divergent ~2300); False = reference-equivalent sum always. Falsy
    # when a Word2Vec is built directly without resolution.
    row_mean_updates: Optional[bool] = None
    # scatter-apply strategy for the embedding updates:
    #   "scatter"  — XLA scatter-add straight into the (bf16) table;
    #   "segsum"   — segment-sum the updates into a dense f32 delta, then
    #                one vector add (collision-free; wins when the rows
    #                are zipf-hot and the scatter serialises on duplicates);
    #   "split8"   — 8 shadow copies indexed by update position % 8, then
    #                summed (caps any row's collision chain at N/8).
    # Measured on-chip by tools/w2v_profile.py; default picked by it.
    update_impl: str = "scatter"
    # Candidate-compaction implementation (device-corpus path, M > B):
    #   "scatter" (default) — prefix-rank scatter into a zero slab
    #               (mode="drop");
    #   "gather"  — searchsorted over the survivor prefix-sum +
    #               one dense row gather per packed array.
    # Same packing either way (slot b <- the row whose inclusive
    # survivor count first reaches b+1; tests/test_compact_impl.py
    # asserts bit-identical training). The G=64 step spends ~25% on the
    # pack, so both alternatives were MEASURED on-chip and rejected:
    # "gather" hits 4.2-4.5M pairs/s vs scatter's 9.8M — binary search
    # costs ceil(log2(M))x more scalar element accesses and narrow
    # gathers pay the same per-element issue cost as scatters — and a
    # fused single wide scatter of all K arrays measured 9.74M (a wash:
    # narrow-row scatter cost is per ELEMENT, not per row, so stacking
    # K arrays into one scatter moves the same element count). The
    # compaction, like the update scatter, sits at a hardware
    # element-granularity floor.
    compact_impl: str = "scatter"
    # with row_mean_updates: use a STATIC expected-count scale table
    # (computed once per corpus chunk from the sampling laws — subsampled
    # unigram for centers/contexts, unigram^0.75 for negatives) instead of
    # realized per-step counts. Saves the per-step [V] counts scatter
    # (~12% of the stabilised step at the bench shape). Expectation ==
    # realization for the hot rows the cap exists for (CV = 1/sqrt(hits));
    # cold rows scale to 1 either way. Device-corpus path only (the
    # expected laws come from load_corpus_chunk); requires plain SGD,
    # skip-gram, no HS, and oversample > 1 (validated at construction).
    row_mean_static: bool = False
    # with row_mean_updates: per-row update = mean-grad * min(count, cap).
    # cap bounds how much a hot row can move per batch — rows with <= cap
    # collisions keep the reference's sequential-sum movement exactly;
    # hotter rows are clamped to cap pair-steps (the sigmoid saturation
    # that self-limits the reference's sequential loop has no batched
    # equivalent, so the cap plays that role). cap=1 -> pure mean.
    row_update_cap: float = 8.0
    # Cross-worker exchange cadence for in-mesh data parallelism (worker
    # axis > 1). The reference never ships a dense table on the wire (its
    # sync Adds are sparse-filtered row buckets,
    # ``src/table/sparse_matrix_table.cpp:145-153``); per-batch GSPMD BSP
    # on replicated tables does — a table-sized allreduce EVERY scan
    # iteration (43-57% measured overhead, docs/DISTRIBUTED.md).
    #   "dispatch" — workers train their batch shards LOCALLY within one
    #                fused dispatch (each sees its own updates immediately,
    #                peers' at dispatch boundaries — the async-PS staleness
    #                model, bounded by steps_per_call) and exchange ONE
    #                summed table delta per dispatch:
    #                ``w = w0 + psum(w_local - w0)``. Sigma-invariant exact
    #                for commutative updaters; wire bytes cut ~3*S vs
    #                per-batch BSP.
    #   "batch"    — per-batch BSP via GSPMD (exact per-batch freshness at
    #                S x the wire cost).
    # Falls back to "batch" when batch_size doesn't divide over the
    # worker axis (and shared-negative groups).
    dp_sync: str = "dispatch"
    # dp_sync="dispatch" exchange wire format:
    #   "dense" — ONE fused psum of the full table deltas. Right for
    #             in-mesh ICI, where a 57 MB/table allreduce is sub-ms.
    #   "keyed" — dirty-row union over the worker axis: psum a [V]
    #             row-moved mask, exchange only the first dp_keyed_cap
    #             union rows (fixed shape), exact dense fallback inside
    #             the dispatch when the union overflows the cap. Right
    #             for the cross-HOST (DCN) mesh: wire per table is
    #             V*4 + cap*D*4 vs V*D*4 dense — measured >=5x smaller
    #             at the real 71k x 200 shape with per-batch dispatches
    #             (docs/DISTRIBUTED.md "Bytes on the wire"). Size the
    #             cap just above the per-dispatch touched-row union
    #             (zipf B=8k batches measure ~6.5k; overflow only costs
    #             a dense-rate dispatch, never correctness).
    dp_exchange: str = "dense"
    dp_keyed_cap: int = 0        # 0 = auto: vocab // 4


def build_unigram_alias(counts: np.ndarray, power: float = 0.75
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Alias tables for O(1) unigram^0.75 negative sampling.

    Replaces the reference's precomputed 1e8-slot sampling table
    (``WE/src/util.cpp`` Sampler) with the alias method: two O(vocab) arrays,
    sampled on device with two uniforms.
    """
    probs = counts.astype(np.float64) ** power
    probs /= probs.sum()
    n = probs.shape[0]
    scaled = probs * n
    alias = np.zeros(n, np.int32)
    thresh = np.ones(n, np.float32)
    small = [i for i in range(n) if scaled[i] < 1.0]
    large = [i for i in range(n) if scaled[i] >= 1.0]
    while small and large:
        s, l = small.pop(), large.pop()
        thresh[s] = scaled[s]
        alias[s] = l
        scaled[l] = scaled[l] - (1.0 - scaled[s])
        (small if scaled[l] < 1.0 else large).append(l)
    for i in large + small:
        thresh[i] = 1.0
        alias[i] = i
    return thresh, alias


def pack_alias_table(thresh: jax.Array, alias: jax.Array) -> jax.Array:
    """Pack thresh/alias into one [V, 2] i32 table so a draw costs a single
    2-wide row gather instead of two scalar gathers (scalar gathers are the
    slow path on TPU).  Build once; :func:`sample_negatives` takes the result.
    """
    return jnp.stack(
        [jax.lax.bitcast_convert_type(thresh, jnp.int32), alias], axis=1)


def sample_negatives(rng_key, packed: jax.Array,
                     shape: Tuple[int, ...]) -> jax.Array:
    """Draw indices from a packed alias table (:func:`pack_alias_table`)."""
    n = packed.shape[0]
    k1, k2 = jax.random.split(rng_key)
    idx = jax.random.randint(k1, shape, 0, n)
    u = jax.random.uniform(k2, shape)
    row = jnp.take(packed, idx, axis=0)                     # [..., 2]
    t = jax.lax.bitcast_convert_type(row[..., 0], jnp.float32)
    return jnp.where(u < t, idx, row[..., 1])


def build_negative_pool(thresh: np.ndarray, alias: np.ndarray, size: int,
                        seed: int = 0) -> np.ndarray:
    """Pre-draw ``size`` unigram^0.75 samples on the host (vectorised alias).

    The device-resident pool is the TPU form of the reference's precomputed
    1e8-slot sampling table (``WE/src/util.cpp`` Sampler): drawing K
    negatives becomes one random offset + a contiguous ``dynamic_slice``
    instead of K random gathers — random gathers are the slow path on TPU
    (measured ~20%% of the fused step at batch 32k x 5 negatives).
    """
    rng = np.random.default_rng(seed)
    n = thresh.shape[0]
    idx = rng.integers(0, n, size).astype(np.int32)
    u = rng.random(size).astype(np.float32)
    return np.where(u < thresh[idx], idx, alias[idx]).astype(np.int32)


def pool_negatives(rng_key, pool: jax.Array,
                   shape: Tuple[int, ...]) -> jax.Array:
    """Take ``prod(shape)`` consecutive pool entries at a random offset."""
    n = int(np.prod(shape))
    start = jax.random.randint(rng_key, (), 0, pool.shape[0] - n + 1)
    return jax.lax.dynamic_slice(pool, (start,), (n,)).reshape(shape)


class Word2Vec:
    """Jitted trainer bound to input/output embedding tables."""

    def __init__(self, config: Word2VecConfig, input_table, output_table,
                 counts: Optional[np.ndarray] = None,
                 huffman: Optional["HuffmanCodes"] = None) -> None:
        if config.vocab_size <= 0:
            config.vocab_size = input_table.num_row
        self.config = config
        self.input_table = input_table
        self.output_table = output_table
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Replicated-committed key: keeps the key's sharding identical between
        # the first call (host-created) and later calls (jit output), so the
        # step never retraces on a sharding change.
        self._key_sharding = NamedSharding(input_table.mesh, P())
        self._key = jax.device_put(jax.random.PRNGKey(config.seed),
                                   self._key_sharding)
        if config.negative <= 0 and not config.hs:
            Log.fatal("word2vec needs an output objective: negative > 0 "
                      "and/or hs=True")
        if (config.shared_negatives > 1
                and config.batch_size % config.shared_negatives != 0):
            Log.fatal("batch_size must divide by shared_negatives group")
        if config.compact_impl not in ("gather", "scatter"):
            Log.fatal(f"unknown compact_impl {config.compact_impl!r} "
                      "(gather|scatter)")
        self._host_counts = (None if counts is None
                             else np.asarray(counts, np.float64))
        if config.row_mean_updates and config.row_mean_static:
            # Static scales only model what they can predict: word-law
            # expectations for full, compacted skip-gram batches.
            if counts is None:
                Log.fatal("row_mean_static requires vocab counts")
            if config.use_adagrad:
                Log.fatal("row_mean_static supports plain SGD only")
            if config.hs:
                # HS scatters Huffman NODE ids; the word-law table would
                # look up unrelated words and leave the hottest rows
                # (top tree nodes) uncapped. Realized counts handle HS.
                Log.fatal("row_mean_static does not support hierarchical "
                          "softmax (use realized counts)")
            if config.cbow:
                Log.fatal("row_mean_static supports skip-gram only")
            if config.oversample <= 1:
                # without candidate compaction only ~half the batch slots
                # hold valid pairs, so the full-B expectations over-cap
                # hot rows ~2x; compaction makes B the realized count
                Log.fatal("row_mean_static requires oversample > 1 "
                          "(compacted full batches make the expected "
                          "counts match realizations)")
        if config.negative > 0:
            if counts is None:
                Log.fatal("negative sampling requires vocab counts")
            # Only the packed [V, 2] table is kept on device; the separate
            # thresh/alias arrays stay host-side (numpy) for pool building.
            thresh, alias = build_unigram_alias(counts)
            self._packed_alias = pack_alias_table(jnp.asarray(thresh),
                                                  jnp.asarray(alias))
            self._host_thresh, self._host_alias = thresh, alias
            self._neg_pool = None
        if config.hs:
            if huffman is None:
                Log.fatal("hierarchical softmax requires huffman codes")
            self._paths = jnp.asarray(huffman.paths)       # [vocab, L]
            self._codes = jnp.asarray(huffman.codes)       # [vocab, L]
            self._path_mask = jnp.asarray(huffman.mask)    # [vocab, L]
        if config.use_adagrad:
            # physical table shape: G rows align 1:1 with (padded) embedding
            # rows so the scatter-accumulate shares the table's sharding
            shape = input_table.padded_shape
            zeros = lambda: jax.jit(
                lambda: jnp.zeros(shape, jnp.float32),
                out_shardings=input_table.sharding)()
            self._g_in = zeros()
            self._g_out = zeros()
        self._static_scale_in = None   # set by load_corpus_chunk when
        self._static_scale_out = None  # cfg.row_mean_static
        self._step = self._build_step()
        self._words_trained = 0.0  # corpus WORDS (not pairs) — see current_lr
        self.total_words = 0       # set by the driver for lr decay
        # device-corpus stream cursor (position of the next candidate slab);
        # persists across chunk loads so rotation continues seamlessly —
        # see set_stream_pos for the multi-process partition hook
        self._stream_pos = 0

    # -- lr schedule (reference UpdateLearningRate, wordembedding.cpp:38) --
    def current_lr(self) -> float:
        """Linear decay over corpus words, floored at ``min_lr_frac``.

        Both ``total_words`` and the trained counter are in WORD units
        (``word_count_actual`` in the reference). Batch calls advance the
        counter by ``pairs / (window + 1)`` — the expected pairs per word
        under random window shrink — unless the driver keeps it exact via
        ``set_words_trained``.
        """
        cfg = self.config
        if cfg.use_adagrad or self.total_words <= 0:
            return cfg.init_lr
        frac = 1.0 - self._words_trained / (self.total_words + 1)
        return cfg.init_lr * max(frac, cfg.min_lr_frac)

    def set_words_trained(self, words: float) -> None:
        """Exact progress hook for drivers that track corpus words."""
        self._words_trained = float(words)

    def set_stream_pos(self, pos: int) -> None:
        """Place the device-corpus stream cursor (API contract for the
        multi-process data partition: each process streams its own arc of
        the cyclic chunk, so drivers offset the cursor per rank)."""
        self._stream_pos = int(pos)

    def _pairs_to_words(self, pairs: float) -> float:
        return pairs / (self.config.window + 1)

    def _dp_local(self) -> int:
        """Worker-axis size of the local-accumulation dp exchange (1 = off).

        > 1 means the multi-batch/corpus dispatches run under shard_map
        with the worker axis MANUAL: each worker trains its batch shard
        against a local table copy and the dispatch exchanges one summed
        delta (``dp_sync="dispatch"``). The server axis stays AUTO, so
        server-sharded tables keep their GSPMD layout inside.
        """
        cfg = self.config
        dp = int(self.input_table.mesh.shape[WORKER_AXIS])
        if dp <= 1 or cfg.dp_sync != "dispatch":
            return 1
        G = max(int(cfg.shared_negatives), 1)
        if cfg.batch_size % dp != 0 or (cfg.batch_size // dp) % G != 0:
            if not getattr(self, "_dp_fallback_logged", False):
                self._dp_fallback_logged = True
                Log.info(
                    "dp_sync=dispatch needs batch_size divisible over "
                    "%d workers (and G=%d groups); falling back to "
                    "per-batch GSPMD sync", dp, G)
            return 1
        return dp

    def _keyed_cap(self) -> int:
        """Static row cap of the ``dp_exchange="keyed"`` wire format
        (ignored for dense). Auto (0) = vocab // 4 — comfortably above
        the measured per-dispatch touched-row union for zipf corpora at
        per-batch dispatches (docs/DISTRIBUTED.md), while still 3-4x
        less wire than dense; overflow costs one dense-rate dispatch,
        never correctness."""
        cfg = self.config
        if cfg.dp_exchange not in ("dense", "keyed"):
            Log.fatal(f"unknown dp_exchange {cfg.dp_exchange!r} "
                      "(expected 'dense' or 'keyed')")
        if int(cfg.dp_keyed_cap) > 0:
            return int(cfg.dp_keyed_cap)
        return max(256, cfg.vocab_size // 4)

    # -- jitted step -------------------------------------------------------
    def _build_step(self):
        cfg = self.config
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.input_table.mesh
        batch_sharding = NamedSharding(mesh, P(WORKER_AXIS))
        emb_sharding = self.input_table.sharding

        impl = cfg.update_impl

        def apply_sgd(w, rows, grads, lr, scale=None):
            upd = -lr * grads if scale is None \
                else (-lr) * scale[:, None] * grads
            if impl == "segsum":
                dense = jax.ops.segment_sum(upd, rows,
                                            num_segments=w.shape[0])
                return (w.astype(jnp.float32) + dense).astype(w.dtype)
            if impl == "split8":
                R = 8
                lane = jax.lax.rem(
                    jnp.arange(rows.shape[0], dtype=jnp.int32), R)
                shadow = jnp.zeros((R,) + w.shape, jnp.float32)
                shadow = shadow.at[lane, rows].add(upd)
                return (w.astype(jnp.float32) + shadow.sum(0)).astype(w.dtype)
            return w.at[rows].add(upd.astype(w.dtype))

        def apply_adagrad(w, g_acc, rows, grads, lr):
            g_rows = jnp.take(g_acc, rows, axis=0) + grads * grads
            g_acc = g_acc.at[rows].add(grads * grads)
            scale = lr / jnp.sqrt(g_rows + _ADAGRAD_EPS)
            return w.at[rows].add((-scale * grads).astype(w.dtype)), g_acc

        D = cfg.embedding_size

        def objective_grads(h, w_out, target_word, ex_mask, key, negs=None):
            """Shared output-side objectives on hidden vector ``h`` [B, D].

            Negative sampling and hierarchical softmax are ADDITIVE when both
            are enabled (matching the reference trainer, which runs both
            branches per sample when hs=1 and negative>0). Returns the summed
            loss, grad wrt h, and the (rows, grads) scatter sets for w_out.
            ``negs`` lets the corpus path pass bulk-predrawn negatives
            (hoisting the alias draws out of the scan body).
            """
            loss = 0.0
            # f32 accumulation regardless of table dtype (bf16 tables keep
            # the MXU/HBM win; grads stay f32 until the scatter cast)
            grad_h = jnp.zeros(h.shape, jnp.float32)
            scatters = []
            G = max(int(cfg.shared_negatives), 1)
            if cfg.negative > 0:
                # ONE implementation for exact and group-shared sampling:
                # G = 1 draws K negatives per pair (reference semantics);
                # G > 1 shares each K-draw across G consecutive pairs,
                # cutting the dominant [*, K, D] gather/scatter traffic by
                # G (the step is HBM-bound on target rows — see bench; same
                # objective in expectation).
                B = h.shape[0]
                if negs is None:
                    key, sub = jax.random.split(key)
                    negs = sample_negatives(sub, self._packed_alias,
                                            (B // G, cfg.negative))
                # positive pairs (always exact, per pair)
                u_pos = jnp.take(w_out, target_word, axis=0)     # [B, D]
                s_pos = jnp.clip(
                    jnp.einsum("bd,bd->b", h, u_pos,
                               preferred_element_type=jnp.float32),
                    -30.0, 30.0)
                g_pos = (jax.nn.sigmoid(s_pos) - 1.0) * ex_mask
                loss = loss + ((jax.nn.softplus(s_pos) - s_pos)
                               * ex_mask).sum()
                grad_h = grad_h + g_pos[:, None] * u_pos
                # scatter-bound grads are emitted in the TABLE dtype when
                # that is rounding-equivalent: the plain-SGD scatter converts
                # each update to it before adding anyway, and a bf16 [N, D]
                # buffer halves the dominant HBM traffic of the update path.
                # NOT equivalent for AdaGrad (consumes grads in f32 math),
                # shared negatives (G-group contraction must accumulate
                # f32), or row-mean (the per-row scale multiplies AFTER,
                # which would double-round).
                exact_cast = (not cfg.use_adagrad and G == 1
                              and not cfg.row_mean_updates)
                scat_dt = w_out.dtype if exact_cast else jnp.float32
                scatters.append((target_word,
                                 (g_pos[:, None] * h).astype(scat_dt),
                                 ex_mask))
                # negatives: [B/G, K, D] rows (per-pair when G == 1)
                u_neg = jnp.take(w_out, negs, axis=0)            # [B/G, K, D]
                hg = h.reshape(B // G, G, D)
                mg = ex_mask.reshape(B // G, G)
                s_neg = jnp.clip(
                    jnp.einsum("gbd,gkd->gbk", hg, u_neg,
                               preferred_element_type=jnp.float32),
                    -30.0, 30.0)
                g_neg = jax.nn.sigmoid(s_neg) * mg[:, :, None]
                loss = loss + (jax.nn.softplus(s_neg)
                               * mg[:, :, None]).sum()
                grad_h = grad_h + jnp.einsum(
                    "gbk,gkd->gbd", g_neg, u_neg,
                    preferred_element_type=jnp.float32).reshape(B, D)
                # each negative slot's grad is summed over its group's valid
                # pairs, so its occurrence weight is the valid-pair COUNT
                # (a binary flag would under-divide hot rows by up to G)
                occ_neg = jnp.broadcast_to(
                    mg.sum(axis=1)[:, None],
                    (B // G, cfg.negative)).reshape(-1)
                scatters.append((negs.reshape(-1), jnp.einsum(
                    "gbk,gbd->gkd", g_neg, hg,
                    preferred_element_type=scat_dt).reshape(-1, D),
                    occ_neg))
            if cfg.hs:
                nodes = jnp.take(self._paths, target_word, axis=0)   # [B, L]
                codes = jnp.take(self._codes, target_word, axis=0)
                pmask = jnp.take(self._path_mask, target_word, axis=0)
                labels = (1.0 - codes)
                u = jnp.take(w_out, nodes, axis=0)
                scores = jnp.clip(
                    jnp.einsum("bd,bld->bl", h, u,
                               preferred_element_type=jnp.float32),
                    -30.0, 30.0)
                g = (jax.nn.sigmoid(scores) - labels) * pmask * ex_mask[:, None]
                path_loss = (jax.nn.softplus(scores) - labels * scores) * pmask
                loss = loss + (path_loss.sum(1) * ex_mask).sum()
                grad_h = grad_h + jnp.einsum(
                    "bl,bld->bd", g, u, preferred_element_type=jnp.float32)
                scatters.append((nodes.reshape(-1),
                                 (g[:, :, None] * h[:, None, :]).reshape(-1, D),
                                 (pmask * ex_mask[:, None]).reshape(-1)))
            loss = loss / jnp.maximum(ex_mask.sum(), 1)
            return loss, grad_h, scatters, key

        def _row_counts(sets):
            """Per-row contribution counts summed over ALL scatter sets of
            one table (a single joint count keeps the cap a per-table bound
            — per-set counts would let a row move n_sets * cap pair-steps
            when it appears in several sets, e.g. as positive target AND
            shared negative)."""
            counts = jnp.zeros((cfg.vocab_size,), jnp.float32)
            for rows, occ in sets:
                counts = counts.at[rows].add(occ, mode="drop")
            return counts

        def _row_scale(counts, rows, grads):
            """Rescale a row's summed grads to ``mean * min(count, cap)``.

            ``occ``/counts weight masked/padded slots as 0 (compaction's
            row-0 filler doesn't dilute row 0; shared-negative slots carry
            their group's valid-pair count). The counts pass is [N]+[V]-
            sized — negligible next to the [N, D] grads themselves.
            """
            cap = max(float(cfg.row_update_cap), 1.0)
            c = jnp.maximum(jnp.take(counts, rows, axis=0), 1.0)
            return grads * (jnp.minimum(c, cap) / c)[:, None]

        def _row_scale_vec(counts, rows):
            """[N] multiplier form of ``_row_scale`` — handed to apply_sgd
            so the rescale fuses into the scatter operand's elementwise
            chain instead of materialising a second [N, D] grads pass
            (measured ~35%% of the step at the bench shape)."""
            cap = max(float(cfg.row_update_cap), 1.0)
            c = jnp.maximum(jnp.take(counts, rows, axis=0), 1.0)
            return jnp.minimum(c, cap) / c

        def _static_scales(in_rows, scatters):
            """Expected-count scale lookup (row_mean_static): one [N]
            gather from a per-chunk static table instead of the realized
            [V] counts scatter."""
            if self._static_scale_in is None:
                Log.fatal("row_mean_static needs the expected-count tables "
                          "from load_corpus_chunk (device-corpus path)")
            in_scale = jnp.take(self._static_scale_in, in_rows, axis=0)
            out_scales = [jnp.take(self._static_scale_out, rows, axis=0)
                          for rows, _, _ in scatters]
            return in_scale, out_scales

        def apply_updates(w_in, w_out, g_in, g_out, in_rows, in_grads,
                          in_occ, scatters, lr):
            in_scale = out_counts = None
            out_scales = None
            if cfg.row_mean_updates and cfg.row_mean_static:
                # (sgd-only, validated in __init__)
                in_scale, out_scales = _static_scales(in_rows, scatters)
            elif cfg.row_mean_updates:
                in_counts = _row_counts([(in_rows, in_occ)])
                out_counts = _row_counts(
                    [(rows, occ) for rows, _, occ in scatters])
                if cfg.use_adagrad:
                    # adagrad consumes scaled grads twice (G accumulation +
                    # update): materialise once
                    in_grads = _row_scale(in_counts, in_rows, in_grads)
                    scatters = [
                        (rows, _row_scale(out_counts, rows, grads), occ)
                        for rows, grads, occ in scatters]
                else:
                    in_scale = _row_scale_vec(in_counts, in_rows)
            if cfg.use_adagrad:
                w_in, g_in = apply_adagrad(w_in, g_in, in_rows, in_grads, lr)
                for rows, grads, _ in scatters:
                    w_out, g_out = apply_adagrad(w_out, g_out, rows, grads, lr)
            else:
                w_in = apply_sgd(w_in, in_rows, in_grads, lr, in_scale)
                if len(scatters) > 1 and impl in ("segsum", "split8"):
                    # dense impls pay per-pass table traffic: combine sets.
                    # (for the scatter impl the concat's extra [N, D]
                    # materialisation costs more than the second scatter)
                    rows = jnp.concatenate([s[0] for s in scatters])
                    grads = jnp.concatenate([s[1] for s in scatters])
                    if out_scales is not None:
                        scale = jnp.concatenate(out_scales)
                    else:
                        scale = (None if out_counts is None
                                 else _row_scale_vec(out_counts, rows))
                    w_out = apply_sgd(w_out, rows, grads, lr, scale)
                else:
                    for i, (rows, grads, _) in enumerate(scatters):
                        if out_scales is not None:
                            scale = out_scales[i]
                        else:
                            scale = (None if out_counts is None
                                     else _row_scale_vec(out_counts, rows))
                        w_out = apply_sgd(w_out, rows, grads, lr, scale)
            return w_in, w_out, g_in, g_out

        if not cfg.cbow:
            # skip-gram: input row = center word; target = context word
            def step(w_in, w_out, g_in, g_out, centers, contexts, mask, lr,
                     key, negs=None):
                h = jnp.take(w_in, centers, axis=0)
                loss, grad_h, scatters, key = objective_grads(
                    h, w_out, contexts, mask, key, negs)
                w_in, w_out, g_in, g_out = apply_updates(
                    w_in, w_out, g_in, g_out, centers, grad_h, mask,
                    scatters, lr)
                return w_in, w_out, g_in, g_out, loss, key
        else:
            # CBOW: input = mean of context window rows; target = center word
            # (reference TrainSample CBOW path; contexts [B, C] with cmask)
            def step(w_in, w_out, g_in, g_out, centers, contexts, cmask, lr,
                     key, negs=None):
                rows = jnp.take(w_in, contexts, axis=0)          # [B, C, D]
                counts = jnp.maximum(cmask.sum(axis=1), 1.0)     # [B]
                h = jnp.einsum("bcd,bc->bd", rows, cmask) / counts[:, None]
                ex_mask = (cmask.sum(axis=1) > 0).astype(jnp.float32)
                loss, grad_h, scatters, key = objective_grads(
                    h, w_out, centers, ex_mask, key, negs)
                # d h / d row_c = cmask_c / count
                in_grads = (grad_h[:, None, :]
                            * (cmask / counts[:, None])[:, :, None])
                w_in, w_out, g_in, g_out = apply_updates(
                    w_in, w_out, g_in, g_out, contexts.reshape(-1),
                    in_grads.reshape(-1, D), cmask.reshape(-1), scatters, lr)
                return w_in, w_out, g_in, g_out, loss, key

        state_shardings = (emb_sharding, emb_sharding,
                           emb_sharding if cfg.use_adagrad else None,
                           emb_sharding if cfg.use_adagrad else None)

        def multi_step(w_in, w_out, g_in, g_out, centers, contexts, mask,
                       lr, key):
            """Scan ``steps_per_call`` batches in one dispatch: amortises
            host->device dispatch latency (batches stacked on axis 0)."""

            def body(carry, xs):
                w_in, w_out, g_in, g_out, key = carry
                c, t, m = xs
                w_in, w_out, g_in, g_out, loss, key = step(
                    w_in, w_out, g_in, g_out, c, t, m, lr, key)
                return (w_in, w_out, g_in, g_out, key), loss

            (w_in, w_out, g_in, g_out, key), losses = jax.lax.scan(
                body, (w_in, w_out, g_in, g_out, key),
                (centers, contexts, mask))
            return w_in, w_out, g_in, g_out, losses.mean(), key

        dp = self._dp_local()

        def multi_step_local(w_in, w_out, g_in, g_out, centers, contexts,
                             mask, lr, key):
            """``dp_sync="dispatch"``: each worker scans its batch shards
            against a LOCAL table copy (zero collectives in the loop) and
            the dispatch ends with ONE summed-delta exchange —
            ``w = w0 + psum(w_local - w0)``. Runs under shard_map with the
            worker axis manual; the server axis stays auto, so GSPMD still
            lays the table math out over server shards. Wire bytes per
            dispatch: 2 tables once, vs (2-3 tables) x steps_per_call for
            per-batch BSP (docs/DISTRIBUTED.md has the accounting)."""
            saved = (w_in, w_out, g_in, g_out)
            lkey, key_out, (w_in, w_out, g_in, g_out) = _dp_enter(key, saved)

            def body(carry, xs):
                w_in, w_out, g_in, g_out, key = carry
                c, t, m = xs
                w_in, w_out, g_in, g_out, loss, key = step(
                    w_in, w_out, g_in, g_out, c, t, m, lr, key)
                return (w_in, w_out, g_in, g_out, key), loss

            (w_in, w_out, g_in, g_out, _), losses = jax.lax.scan(
                body, (w_in, w_out, g_in, g_out, lkey),
                (centers, contexts, mask))

            w_in, w_out, g_in, g_out = _dp_exchange(
                (w_in, w_out, g_in, g_out), saved,
                mode=cfg.dp_exchange, cap=self._keyed_cap())
            loss = jax.lax.psum(losses.mean(), WORKER_AXIS) / dp
            return w_in, w_out, g_in, g_out, loss, key_out

        if dp > 1:
            sm_batch = (P(None, WORKER_AXIS) if not cfg.cbow
                        else P(None, WORKER_AXIS, None))
            multi_step = jax.shard_map(
                multi_step_local, mesh=mesh,
                in_specs=(P(), P(), P(), P(),
                          P(None, WORKER_AXIS), sm_batch, sm_batch,
                          P(), P()),
                out_specs=(P(), P(), P(), P(), P(), P()),
                axis_names={WORKER_AXIS})

        multi_batch_sharding = NamedSharding(mesh, P(None, WORKER_AXIS))
        key_sharding = self._key_sharding
        jitted = jax.jit(
            step,
            donate_argnums=(0, 1, 2, 3),
            in_shardings=state_shardings + (batch_sharding,) * 3
            + (None, key_sharding),
            out_shardings=state_shardings + (None, key_sharding),
        )
        self._multi_step = jax.jit(
            multi_step,
            donate_argnums=(0, 1, 2, 3),
            in_shardings=state_shardings + (multi_batch_sharding,) * 3
            + (None, key_sharding),
            out_shardings=state_shardings + (None, key_sharding),
        )
        self._raw_step = step
        self._state_shardings = state_shardings
        return jitted

    def _ensure_neg_pool(self, n_draws: int) -> jax.Array:
        """Device pool with at least ``2 * n_draws`` pre-drawn negatives."""
        need = max(int(self.config.neg_pool_size), 2 * n_draws)
        if self._neg_pool is None or self._neg_pool.shape[0] < 2 * n_draws:
            pool = build_negative_pool(self._host_thresh, self._host_alias,
                                       need, seed=self.config.seed + 1)
            self._neg_pool = jnp.asarray(pool)
        return self._neg_pool

    def _candidate_batch(self, n: int) -> int:
        """GLOBAL candidate slab length M for a corpus chunk of ``n``
        positions (candidates consumed per fused step, summed over the
        worker axis — ``dp_sync="dispatch"`` gives each worker its own
        ``M // dp`` slab on its own arc of the chunk).

        Single source of truth for the oversample formula — the device
        sampler and the host-side stream-position bookkeeping must agree.
        Clamped so ``ext`` slicing (n >= M_local + 2W) stays in bounds.
        """
        cfg = self.config
        B, W = cfg.batch_size, cfg.window
        dp = self._dp_local()
        Bl = B // dp
        if n < Bl + 2 * W:
            Log.fatal(f"corpus chunk ({n} positions) smaller than "
                      f"per-worker batch + 2*window ({Bl + 2 * W}); lower "
                      "batch_size or load a larger chunk")
        Ml = (max(Bl, int(round(Bl * cfg.oversample)))
              if cfg.oversample > 1 else Bl)
        return min(Ml, n - 2 * W) * dp

    def _build_corpus_step(self, n_steps: int, M: int):
        """Fused sample+train over a device-resident corpus chunk.

        The host pipeline ships every batch over PCIe/DCN; here the corpus
        ids live in HBM and each scan iteration *samples* a batch on device
        (positions, window offset with the reference's random shrink,
        subsampling keep-test) and trains it — ``n_steps`` batches per
        dispatch with no per-batch host traffic. This is the TPU-native form
        of the reference's loader-thread + pipelined-trainer overlap
        (``distributed_wordembedding.cpp:199-208``).

        With ``dp_sync="dispatch"`` and worker axis > 1 the whole dispatch
        runs under shard_map with the worker axis manual: each worker
        samples its ``M // dp`` candidate slab from its own arc of the
        cyclic chunk (the in-mesh form of the per-process data partition),
        trains against a local table copy, and the dispatch ends with ONE
        summed-delta psum — no per-batch table collectives (the dense
        grad-table allreduce the reference never pays either; its sync
        Adds are sparse row buckets, ``src/table/matrix_table.cpp:288-316``).
        """
        cfg = self.config
        W, B = cfg.window, cfg.batch_size
        step = self._raw_step
        dp = self._dp_local()
        S = n_steps
        # per-worker candidate slab / batch (dp == 1: the global sizes)
        Ml, Bl = M // dp, B // dp
        G = max(int(cfg.shared_negatives), 1)
        draws_per_call = S * (Bl // G) * cfg.negative
        neg_pool = (self._ensure_neg_pool(draws_per_call)
                    if cfg.negative > 0 and cfg.neg_pool_size > 0 else None)

        def compact_one(ok, n_valid, *arrays):
            """Pack the ``ok`` rows of each [Ml, ...] array into [Bl, ...].

            Linear-time alternative to sorting (TPU sorts are slow). Both
            impls fill slot b with the row whose inclusive survivor count
            first reaches b+1, and zero the slots past ``n_valid``:

            * "scatter" (default): each survivor scatters to its
              prefix-count rank (overflow/rejected rows drop out of
              bounds);
            * "gather": ``searchsorted`` over the prefix-sum + one dense
              row gather per array — measured 2.2x slower end-to-end
              (the log2(Ml) search rounds multiply scalar element
              accesses; see ``compact_impl`` docs).
            """
            valid = jnp.arange(Bl) < n_valid
            if cfg.compact_impl == "gather":
                csum = jnp.cumsum(ok.astype(jnp.int32))
                # method matters: the default 'scan' lowers to a
                # SEQUENTIAL loop; 'scan_unrolled' is ceil(log2(Ml))
                # vectorised gather rounds — but that log factor is the
                # impl's downfall (see compact_impl docs)
                src = jnp.searchsorted(csum, jnp.arange(1, Bl + 1),
                                       method="scan_unrolled")
                src = jnp.minimum(src, Ml - 1)
                packed = tuple(
                    jnp.where(valid.reshape((Bl,) + (1,) * (a.ndim - 1)),
                              a[src], jnp.zeros((), a.dtype))
                    for a in arrays)
                return packed + (valid,)
            rank = jnp.cumsum(ok.astype(jnp.int32)) - 1
            dest = jnp.where(ok & (rank < Bl), rank, Bl)
            packed = tuple(
                jnp.zeros((Bl,) + a.shape[1:], a.dtype).at[dest].set(
                    a, mode="drop")
                for a in arrays)
            return packed + (valid,)

        def fused(w_in, w_out, g_in, g_out, ext_ids, ext_sents, ext_disc,
                  lr, key, start0):
            """Sequential corpus streaming (the reference reads sentences in
            order — ``WE/src/reader.cpp``): each step consumes the next Ml
            corpus positions as centers, so every word lookup is a contiguous
            slice instead of a scalar gather. The per-pair window offset is
            resolved by selecting among the 2W statically-shifted copies of
            the slab — pure vector ops, no gathers. The wrap-around-extended
            buffers are precomputed once per chunk (``load_corpus_chunk``).
            """
            n = ext_ids.shape[0] - M - 2 * W

            saved = (w_in, w_out, g_in, g_out)
            if dp > 1:
                key, key_out, (w_in, w_out, g_in, g_out) = _dp_enter(
                    key, saved)
                # each worker streams its own arc of the cyclic chunk
                widx = jax.lax.axis_index(WORKER_AXIS)
                start0 = (start0 + widx * (n // dp)) % n

            # ---- bulk RNG: ONE vectorized draw for all S batches ----
            key, k1, k2, k3, k4, k5 = jax.random.split(key, 6)
            shrink = jax.random.randint(k1, (S, Ml), 1, W + 1)
            if not cfg.cbow:
                dmag = jnp.minimum(jax.random.randint(k2, (S, Ml), 1, W + 1),
                                   shrink)
                sign = jnp.where(jax.random.bernoulli(k3, 0.5, (S, Ml)), 1, -1)
                # window offset -W..W (excl 0) → shifted-copy index 0..2W-1
                dsel = jnp.where(sign > 0, W + dmag - 1, W - dmag)
                u_ctx = jax.random.uniform(k5, (S, Ml))
            else:
                dsel = None
                u_ctx = jax.random.uniform(k5, (S, Ml, 2 * W))
            u_center = jax.random.uniform(k4, (S, Ml))
            negs = None
            if cfg.negative > 0:
                key, kn = jax.random.split(key)
                n_rows = Bl // G
                if neg_pool is not None:
                    negs = pool_negatives(kn, neg_pool,
                                          (S, n_rows, cfg.negative))
                else:
                    negs = sample_negatives(kn, self._packed_alias,
                                            (S, n_rows, cfg.negative))

            starts = (start0 + jnp.arange(S, dtype=jnp.int32) * Ml) % n

            offsets = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])

            def slab_views(start):
                """[2W+1 views of the slab] — static slices of one dynamic
                slice, so the only data movement is contiguous."""
                buf = jax.lax.dynamic_slice(ext_ids, (start,), (Ml + 2 * W,))
                sbuf = jax.lax.dynamic_slice(ext_sents, (start,),
                                             (Ml + 2 * W,))
                dbuf = jax.lax.dynamic_slice(ext_disc, (start,),
                                             (Ml + 2 * W,))
                ctr = (buf[W:W + Ml], sbuf[W:W + Ml], dbuf[W:W + Ml])
                shifted = [(buf[W + d:W + d + Ml], sbuf[W + d:W + d + Ml],
                            dbuf[W + d:W + d + Ml]) for d in offsets]
                return ctr, shifted

            def select(shifted_vals, dsel_row):
                """contexts[i] = shifted[dsel[i]][i] via masked sum (2W
                vector multiply-adds, no gather)."""
                out = jnp.zeros_like(shifted_vals[0])
                for j, v in enumerate(shifted_vals):
                    out = jnp.where(dsel_row == j, v, out)
                return out

            def sample_sg(start, dsel, u_center, u_ctx):
                (centers, csent, cdisc), shifted = slab_views(start)
                contexts = select([s[0] for s in shifted], dsel)
                xsent = select([s[1] for s in shifted], dsel)
                xdisc = select([s[2] for s in shifted], dsel)
                valid = (xsent == csent)
                keep = (u_center >= cdisc) & (u_ctx >= xdisc)
                ok = valid & keep
                if Ml > Bl:
                    n_valid = jnp.minimum(ok.sum(), Bl)
                    centers, contexts, ok = compact_one(
                        ok, n_valid, centers, contexts)
                return centers, contexts, ok.astype(jnp.float32)

            def sample_cbow(start, shrink, u_center, u_ctx):
                (centers, csent, cdisc), shifted = slab_views(start)
                contexts = jnp.stack([s[0] for s in shifted], axis=1)
                xsent = jnp.stack([s[1] for s in shifted], axis=1)
                xdisc = jnp.stack([s[2] for s in shifted], axis=1)
                in_window = (jnp.abs(offsets)[None, :]
                             <= shrink[:, None])              # [M, 2W]
                valid = in_window & (xsent == csent[:, None])
                keep = (u_center >= cdisc)[:, None] & (u_ctx >= xdisc)
                ok = valid & keep
                if Ml > Bl:
                    ex_ok = ok.any(axis=1)
                    n_valid = jnp.minimum(ex_ok.sum(), Bl)
                    centers, contexts, ok, ex_packed = compact_one(
                        ex_ok, n_valid, centers, contexts, ok)
                    ok = ok & ex_packed[:, None]
                return centers, contexts, ok.astype(jnp.float32)

            def body(carry, xs):
                w_in, w_out, g_in, g_out, key = carry
                if cfg.cbow:
                    start, shrink_r, u_c, u_x, nn = xs
                    c, t, m = sample_cbow(start, shrink_r, u_c, u_x)
                    count = (m.sum(axis=1) > 0).astype(jnp.float32).sum()
                else:
                    start, dsel_r, u_c, u_x, nn = xs
                    c, t, m = sample_sg(start, dsel_r, u_c, u_x)
                    count = m.sum()
                w_in, w_out, g_in, g_out, loss, key = step(
                    w_in, w_out, g_in, g_out, c, t, m, lr, key, nn)
                return (w_in, w_out, g_in, g_out, key), (loss, count)

            dummy_negs = (negs if negs is not None
                          else jnp.zeros((S, 1), jnp.int32))
            if cfg.cbow:
                xs = (starts, shrink, u_center, u_ctx, dummy_negs)
            else:
                xs = (starts, dsel, u_center, u_ctx, dummy_negs)

            def body_wrap(carry, xs):
                if cfg.negative <= 0:
                    xs = xs[:-1] + (None,)
                return body(carry, xs)

            (w_in, w_out, g_in, g_out, key), (losses, counts) = jax.lax.scan(
                body_wrap, (w_in, w_out, g_in, g_out, key), xs)
            loss, count = losses.mean(), counts.sum()
            if dp > 1:
                w_in, w_out, g_in, g_out = _dp_exchange(
                    (w_in, w_out, g_in, g_out), saved,
                    mode=cfg.dp_exchange, cap=self._keyed_cap())
                loss = jax.lax.psum(loss, WORKER_AXIS) / dp
                count = jax.lax.psum(count, WORKER_AXIS)
                key = key_out
            return (w_in, w_out, g_in, g_out, loss, count, key)

        if dp > 1:
            from jax.sharding import PartitionSpec as P

            fused = jax.shard_map(
                fused, mesh=self.input_table.mesh,
                in_specs=(P(),) * 10, out_specs=(P(),) * 7,
                axis_names={WORKER_AXIS})
        return jax.jit(
            fused,
            donate_argnums=(0, 1, 2, 3),
            in_shardings=self._state_shardings
            + (None, None, None, None, self._key_sharding, None),
            out_shardings=self._state_shardings
            + (None, None, self._key_sharding),
        )

    def _dispatch(self, step_fn, centers, contexts, mask, n_words: int):
        cfg = self.config
        lr = jnp.float32(self.current_lr())
        g_in = self._g_in if cfg.use_adagrad else None
        g_out = self._g_out if cfg.use_adagrad else None
        batch = (jnp.asarray(centers, jnp.int32),
                 jnp.asarray(contexts, jnp.int32),
                 jnp.asarray(mask, jnp.float32))
        if jax.process_count() > 1 and len(
                self.input_table.mesh.devices.flat) > len(
                jax.local_devices()):
            # multi-process SPMD (the worker axis spans processes): each
            # process passes ITS batch shard; assemble the global array
            # from the per-process local data (a plain device_put cannot
            # target non-addressable shards). Global batch = local x P.
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self.input_table.mesh
            # batch dim is axis 0 for single batches, axis 1 for stacked
            # [S, B] multi-batch calls; trailing dims (CBOW window) unsharded
            lead = (None,) if batch[0].ndim >= 2 else ()
            batch = tuple(
                jax.make_array_from_process_local_data(
                    NamedSharding(
                        mesh, P(*(lead + (WORKER_AXIS,))[:a.ndim])),
                    np.asarray(a))
                for a in batch)
        with self.input_table._lock, self.output_table._lock:
            (self.input_table._data, self.output_table._data,
             g_in, g_out, loss, self._key) = step_fn(
                self.input_table._data, self.output_table._data,
                g_in, g_out, *batch, lr, self._key)
            self.input_table.version += 1
            self.output_table.version += 1
        if cfg.use_adagrad:
            self._g_in, self._g_out = g_in, g_out
        self._words_trained += n_words
        return loss

    def _batch_words(self, mask: np.ndarray) -> float:
        """Word-unit progress for a batch (see ``current_lr``)."""
        if self.config.cbow:
            # one CBOW example == one center-word occurrence
            return float((mask.sum(axis=-1) > 0).sum())
        return self._pairs_to_words(float(mask.sum()))

    def train_batch(self, centers: np.ndarray, contexts: np.ndarray,
                    mask: Optional[np.ndarray] = None) -> float:
        """Train one fixed-size batch.

        Skip-gram: ``centers [B]``, ``contexts [B]``, ``mask [B]``.
        CBOW: ``centers [B]``, ``contexts [B, 2*window]``, ``mask [B, 2W]``
        (per-context-slot validity). Returns the mean loss (async jax
        scalar; float() to block).
        """
        if mask is None:
            mask = np.ones(contexts.shape, np.float32)
        return self._dispatch(self._step, centers, contexts, mask,
                              self._batch_words(np.asarray(mask)))

    def train_batches(self, centers: np.ndarray, contexts: np.ndarray,
                      mask: Optional[np.ndarray] = None) -> float:
        """Train a stack of batches [S, B(, C)] in ONE device dispatch."""
        if mask is None:
            mask = np.ones(contexts.shape, np.float32)
        return self._dispatch(self._multi_step, centers, contexts, mask,
                              self._batch_words(np.asarray(mask)))

    # -- device-resident corpus path (the fast path) -----------------------
    def load_corpus_chunk(self, ids: np.ndarray, sent_ids: np.ndarray,
                          discard: Optional[np.ndarray] = None) -> None:
        """Upload a corpus chunk to HBM (ids + sentence membership + word
        discard probabilities for subsampling)."""
        self._corpus = jnp.asarray(ids, jnp.int32)
        self._sents = jnp.asarray(sent_ids, jnp.int32)
        if discard is None:
            discard = np.zeros(self.config.vocab_size, np.float32)
        self._discard = jnp.asarray(discard, jnp.float32)
        # Hoist the wrap-around extension + per-position discard gather out
        # of the fused step: they are O(corpus) and depend only on the chunk
        # (profiled at ~13 ms/dispatch on a 2M-token chunk — pure waste when
        # re-done every call).
        n = int(self._corpus.shape[0])
        M = self._candidate_batch(n)
        W = self.config.window

        def _ext(corpus, sents, discard):
            dpos = jnp.take(discard, corpus, axis=0)
            return (
                jnp.concatenate([corpus[-W:], corpus, corpus[:M + W]]),
                jnp.concatenate([sents[-W:], sents, sents[:M + W]]),
                jnp.concatenate([dpos[-W:], dpos, dpos[:M + W]]),
            )

        self._ext_bufs = jax.jit(_ext)(self._corpus, self._sents,
                                       self._discard)
        if self.config.row_mean_updates and self.config.row_mean_static:
            self._build_static_scales(np.asarray(discard, np.float64))
        # the originals are folded into the ext buffers; keeping them would
        # pin a second copy of the corpus in HBM for the model's lifetime
        self._corpus_len = n
        del self._corpus, self._sents, self._discard

    def _build_static_scales(self, discard: np.ndarray) -> None:
        """Expected-count scale tables (``row_mean_static``): per step,
        row v's expected colliding grads are

        * input table (sg centers / cbow context slots):
          ``B * p_eff(v)`` (x expected window slots for cbow),
        * output table: ``B * p_eff(v) + B * K * p_neg(v)``
          (targets + negatives),

        where ``p_eff`` is the subsampled unigram law and ``p_neg`` the
        unigram^0.75 law — the same distributions the device sampler
        draws from. Scale = min(E, cap)/max(E, 1), the expectation form
        of ``_row_scale_vec``. The tables change only with the discard
        vector; chunk rotation reuses them (same corpus law), and a new
        law invalidates the fused cache.
        """
        cfg = self.config
        counts = np.asarray(self._host_counts, np.float64)
        keep = np.clip(1.0 - discard, 0.0, 1.0)
        eff = counts * keep
        p_eff = eff / max(eff.sum(), 1e-12)
        w75 = counts ** 0.75
        p_neg = w75 / max(w75.sum(), 1e-12)
        # the table application unit is the PER-WORKER batch: with
        # dp_sync="dispatch" each worker applies its own Bl-sized batches
        # locally, so the expected colliding grads per application scale
        # with Bl, not the global batch
        B, K = cfg.batch_size // self._dp_local(), cfg.negative
        e_in = B * p_eff                      # sg centers (sg-only mode)
        e_out = B * p_eff + B * K * p_neg     # targets + negatives

        def scale(e):
            c = np.maximum(e, 1.0)
            s = np.minimum(c, max(float(cfg.row_update_cap), 1.0)) / c
            return jnp.asarray(s, jnp.float32)

        new_in, new_out = scale(e_in), scale(e_out)
        if (self._static_scale_in is not None
                and not (np.allclose(np.asarray(self._static_scale_in),
                                     np.asarray(new_in))
                         and np.allclose(np.asarray(self._static_scale_out),
                                         np.asarray(new_out)))):
            # every traced program captured the old tables as constants:
            # drop the fused cache AND rebuild the batch-step jits
            self._fused_cache = {}
            self._static_scale_in, self._static_scale_out = new_in, new_out
            self._step = self._build_step()
            return
        self._static_scale_in, self._static_scale_out = new_in, new_out

    def train_device_steps(self, n_steps: int) -> Tuple[Any, Any]:
        """Run ``n_steps`` sample+train iterations on device in one dispatch.

        Returns (mean_loss, pairs_trained) as async jax scalars.
        """
        if not hasattr(self, "_ext_bufs"):
            Log.fatal("call load_corpus_chunk() before train_device_steps()")
        n = self._corpus_len
        M = self._candidate_batch(n)
        fused = getattr(self, "_fused_cache", {}).get((n_steps, M))
        if fused is None:
            if not hasattr(self, "_fused_cache"):
                self._fused_cache = {}
            fused = self._build_corpus_step(n_steps, M)
            self._fused_cache[(n_steps, M)] = fused
        cfg = self.config
        lr = jnp.float32(self.current_lr())
        g_in = self._g_in if cfg.use_adagrad else None
        g_out = self._g_out if cfg.use_adagrad else None
        start0 = self._stream_pos % n
        # the cursor is a PER-WORKER arc position: each of the dp workers
        # consumes n_steps * (M // dp) positions of its own arc per
        # dispatch (the in-jit widx*(n//dp) offsets place the arcs), so
        # advancing by the global M would skip/alias corpus coverage
        self._stream_pos = (start0 + n_steps * (M // self._dp_local())) % n
        # read-and-rebind of table state stays under BOTH table locks so a
        # concurrent async-PS drain apply can never land between the read
        # and the rebind (it would be silently overwritten)
        with self.input_table._lock, self.output_table._lock:
            (self.input_table._data, self.output_table._data,
             g_in, g_out, loss, count, self._key) = fused(
                self.input_table._data, self.output_table._data,
                g_in, g_out, *self._ext_bufs,
                lr, self._key, jnp.int32(start0))
            self.input_table.version += 1
            self.output_table.version += 1
        if cfg.use_adagrad:
            self._g_in, self._g_out = g_in, g_out
        # lr decay bookkeeping: count is async; approximate with the
        # expected valid fraction to avoid a sync point (word units).
        est_examples = n_steps * cfg.batch_size * 0.5
        self._words_trained += (est_examples if cfg.cbow
                                else self._pairs_to_words(est_examples))
        return loss, count


@dataclass
class HuffmanCodes:
    """Padded Huffman paths for HS (reference HuffmanEncoder output)."""

    paths: np.ndarray  # [vocab, L] inner-node ids
    codes: np.ndarray  # [vocab, L] bits (float)
    mask: np.ndarray   # [vocab, L] valid-step mask


def build_huffman(counts: np.ndarray, max_code_length: int = 40) -> HuffmanCodes:
    """Build Huffman tree over word counts (reference ``HuffmanEncoder``,
    ``WE/src/huffman_encoder.cpp``); returns padded per-word paths."""
    import heapq

    n = counts.shape[0]
    heap = [(int(c), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent = {}
    binary = {}
    next_id = n
    while len(heap) > 1:
        c1, i1 = heapq.heappop(heap)
        c2, i2 = heapq.heappop(heap)
        parent[i1], parent[i2] = next_id, next_id
        binary[i1], binary[i2] = 0, 1
        heapq.heappush(heap, (c1 + c2, next_id))
        next_id += 1
    root = heap[0][1] if heap else None
    L = max_code_length
    paths = np.zeros((n, L), np.int32)
    codes = np.zeros((n, L), np.float32)
    mask = np.zeros((n, L), np.float32)
    for w in range(n):
        path, bits = [], []
        node = w
        while node in parent:
            bits.append(binary[node])
            node = parent[node]
            path.append(node)
        # path root->leaf; inner node ids are offset into [0, n-1) range
        path = path[::-1][:L]
        bits = bits[::-1][:L]
        for j, (p, b) in enumerate(zip(path, bits)):
            paths[w, j] = p - n  # inner nodes numbered n..2n-2 -> 0..n-2
            codes[w, j] = b
            mask[w, j] = 1.0
    return HuffmanCodes(paths=paths, codes=codes, mask=mask)
