"""Model families: word2vec (skip-gram/CBOW) and logistic regression/FTRL."""

from .logreg import FTRLLogReg, LogReg, LogRegConfig, SparseLogReg
from .word2vec import (HuffmanCodes, Word2Vec, Word2VecConfig,
                       build_huffman, build_unigram_alias)

__all__ = [
    "FTRLLogReg",
    "LogReg",
    "LogRegConfig",
    "SparseLogReg",
    "HuffmanCodes",
    "Word2Vec",
    "Word2VecConfig",
    "build_huffman",
    "build_unigram_alias",
]
