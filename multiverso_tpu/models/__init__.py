"""Model families: word2vec (skip-gram/CBOW), logistic regression/FTRL,
and the transformer LM parallelism showcase."""

from .logreg import FTRLLogReg, LogReg, LogRegConfig, SparseLogReg
from .transformer import TransformerConfig, TransformerLM
from .word2vec import (HuffmanCodes, Word2Vec, Word2VecConfig,
                       build_huffman, build_unigram_alias)

__all__ = [
    "FTRLLogReg",
    "LogReg",
    "LogRegConfig",
    "SparseLogReg",
    "TransformerConfig",
    "TransformerLM",
    "HuffmanCodes",
    "Word2Vec",
    "Word2VecConfig",
    "build_huffman",
    "build_unigram_alias",
]
