"""Device-mesh topology discovery and process-group control plane.

TPU-native replacement for the reference's Zoo/Controller node registration
(``src/zoo.cpp:37-138``, ``src/controller.cpp:38-80`` in the Multiverso
reference). There, every process sends a ``Control_Register`` message to rank
0, which assigns dense worker/server ids and broadcasts the node table over
MPI/ZMQ. Here the same facts — world size, this process's rank, which devices
exist and how they are arranged — come from the JAX runtime: multi-host
process groups via ``jax.distributed`` over DCN, device topology from
``jax.devices()``, and the data plane is an SPMD ``jax.sharding.Mesh``.

The logical mesh has two axes:

* ``worker`` — the data-parallel axis. Gradients/deltas are summed across it
  (the reference's "N workers each Add a delta" contract).
* ``server`` — the table-shard axis. Parameter tables are laid out with
  ``NamedSharding(mesh, P("server"))`` so each shard is HBM-resident on its
  "server" devices (the reference's range-sharding of tables across server
  nodes, ``src/table/array_table.cpp:11-22``).

A third optional axis ``seq`` supports sequence/context parallelism for
long-context workloads (ring attention in ``ops/ring_attention.py``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import config
from .log import Log

WORKER_AXIS = "worker"
SERVER_AXIS = "server"
SEQ_AXIS = "seq"


@dataclass
class Topology:
    """Immutable snapshot of the process group + device mesh."""

    mesh: "jax.sharding.Mesh"
    process_index: int
    process_count: int
    devices: List["jax.Device"] = field(default_factory=list)
    _local_mesh: Optional["jax.sharding.Mesh"] = None

    @property
    def num_workers(self) -> int:
        return int(self.mesh.shape[WORKER_AXIS])

    @property
    def local_mesh(self) -> "jax.sharding.Mesh":
        """Mesh over THIS process's devices only (worker=1, server=n_local).

        Async-PS tables live here: each process owns an independent replica
        it can update without collective participation (the global mesh
        would make every ``device_put``/jit a group-wide collective, which
        is exactly what async mode must not require). Deltas cross
        processes via ``parallel.async_ps``, not via array sharding.
        """
        if self._local_mesh is None:
            local = [d for d in self.devices
                     if d.process_index == self.process_index]
            self._local_mesh = make_mesh(
                (1, len(local)), devices=local)
        return self._local_mesh

    @property
    def num_servers(self) -> int:
        return int(self.mesh.shape[SERVER_AXIS])

    @property
    def rank(self) -> int:
        return self.process_index

    @property
    def size(self) -> int:
        return self.process_count


def _parse_mesh_shape(text: str) -> Optional[Tuple[int, ...]]:
    text = text.strip()
    if not text:
        return None
    return tuple(int(p) for p in text.split(",") if p.strip())


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (WORKER_AXIS, SERVER_AXIS),
    devices: Optional[Sequence] = None,
) -> "jax.sharding.Mesh":
    """Build a logical mesh over the (global) device set.

    ``shape`` defaults to putting every device on the ``server`` axis
    (pure table sharding, one logical worker per process group) — the
    analogue of the reference default role ``ALL`` where each node both
    computes and serves shards (``src/zoo.cpp:23,31``).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if shape is None:
        shape = (1,) * (len(axis_names) - 1) + (n,)
    shape = tuple(int(s) for s in shape)
    if len(shape) != len(axis_names):
        raise ValueError(f"mesh shape {shape} does not match axes {tuple(axis_names)}")
    needed = int(np.prod(shape))
    if needed > n:
        raise ValueError(f"mesh shape {shape} needs {needed} devices, have {n}")
    grid = np.asarray(devices[:needed], dtype=object).reshape(shape)
    return Mesh(grid, axis_names=tuple(axis_names))


# Explicit net bootstrap state (net_bind/net_connect), consulted before the
# env vars by _maybe_init_distributed.
_explicit_net: Dict[str, object] = {}


def net_bind(rank: int, endpoint: str) -> None:
    """Declare THIS process's rank and endpoint (``MV_NetBind``,
    ``include/multiverso/multiverso.h:43-62`` — the reference's MPI-free
    ZMQ deployment mode, where a machine file / explicit bind+connect
    replaces mpirun).

    Call before :func:`multiverso_tpu.init`, paired with
    :func:`net_connect`. In this framework the transport is the JAX
    coordination service, so binding reduces to declaring identity; the
    per-rank data endpoints of the reference collapse into the single
    coordinator endpoint (rank 0's).
    """
    _explicit_net["rank"] = int(rank)
    _explicit_net["endpoint"] = str(endpoint)


def net_connect(ranks: Sequence[int], endpoints: Sequence[str]) -> None:
    """Declare the full group (``MV_NetConnect``): ``endpoints[i]`` is rank
    ``ranks[i]``'s endpoint; rank 0's endpoint becomes the coordinator.
    Call before :func:`multiverso_tpu.init` (after :func:`net_bind`)."""
    ranks = [int(r) for r in ranks]
    if len(ranks) != len(endpoints):
        Log.fatal(f"net_connect: {len(ranks)} ranks vs "
                  f"{len(endpoints)} endpoints")
    if len(set(ranks)) != len(ranks):
        Log.fatal(f"net_connect: duplicate ranks in {ranks}")
    table = dict(zip(ranks, endpoints))
    if 0 not in table:
        Log.fatal("net_connect needs rank 0's endpoint (the coordinator)")
    _explicit_net["num"] = len(table)
    _explicit_net["coordinator"] = str(table[0])


def _survivor_mode_prep() -> None:
    """Survivor mode (``-failure_timeout_s > 0``) needs the coordination
    service itself to tolerate a dead task: without
    ``jax_enable_recoverability`` the service's error polling terminates
    every HEALTHY process ~heartbeat_timeout after a peer dies —
    regardless of the framework-level live-set machinery. Fail-fast
    stays the default for non-survivor jobs (the reference's posture: a
    silent peer kills the job)."""
    try:
        from . import config as _config

        survivor = float(_config.get_flag("failure_timeout_s")) > 0
    except Exception as exc:   # flag registry not up yet -> default mode
        Log.debug("survivor-mode prep skipped: %s", exc)
        return
    if not survivor:
        return
    try:
        import jax

        jax.config.update("jax_enable_recoverability", True)
    except Exception as exc:
        # the user EXPLICITLY asked for survivor mode; silently reverting
        # to fail-fast would let a dead peer kill every healthy survivor
        Log.error("survivor mode requested (-failure_timeout_s) but "
                  "jax_enable_recoverability could not be enabled (%s): "
                  "the coordination service will terminate survivors "
                  "~heartbeat_timeout after a peer death", exc)


def _maybe_init_distributed() -> None:
    """Initialise the multi-host process group if asked to.

    Replaces MPI_Init + rank-0 registration: coordination rides DCN via the
    JAX coordination service. Bootstrap sources, in order: the explicit
    net_bind/net_connect API (the reference's machine-file/ZMQ mode), then
    the MV_*/JAX_* coordinator env vars. Single-process runs skip this.
    """
    _survivor_mode_prep()
    # Read the env BEFORE touching any jax API: probing jax.process_count()
    # would itself initialise the local backend, after which
    # jax.distributed.initialize() raises.
    if "coordinator" in _explicit_net and "rank" in _explicit_net:
        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=_explicit_net["coordinator"],
                num_processes=int(_explicit_net["num"]),
                process_id=int(_explicit_net["rank"]),
            )
        except RuntimeError as exc:
            Log.debug("jax.distributed.initialize skipped: %s", exc)
        Log.info("process group (explicit net): rank %d/%d via %s",
                 jax.process_index(), jax.process_count(),
                 _explicit_net["coordinator"])
        return
    coord = os.environ.get("MV_COORDINATOR_ADDRESS") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    nproc = os.environ.get("MV_NUM_PROCESSES")
    if not (coord and nproc):
        return
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(os.environ.get("MV_PROCESS_ID", "0")),
        )
    except RuntimeError as exc:
        # Already initialised (by the launcher or a prior init()) is fine.
        Log.debug("jax.distributed.initialize skipped: %s", exc)
    Log.info(
        "process group: rank %d/%d via %s",
        jax.process_index(), jax.process_count(), coord,
    )


def discover(mesh_shape: Optional[Sequence[int]] = None) -> Topology:
    """Discover the topology; the ``mesh_shape`` flag/argument overrides.

    Default layout: ``worker`` axis = number of processes (each host is one
    data-parallel worker, mirroring one-node-one-worker in the reference),
    ``server`` axis = devices per process (tables sharded across local chips).
    """
    import jax

    _maybe_init_distributed()
    if mesh_shape is None:
        mesh_shape = _parse_mesh_shape(config.get_flag("mesh_shape"))

    devices = jax.devices()
    n = len(devices)
    if mesh_shape is None:
        workers = jax.process_count()
        if n % workers != 0:
            workers = 1
        mesh_shape = (workers, n // workers)

    mesh = make_mesh(mesh_shape, devices=devices)
    topo = Topology(
        mesh=mesh,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        devices=devices,
    )
    Log.debug(
        "topology: %d device(s), mesh %s, process %d/%d",
        n, dict(mesh.shape), topo.process_index, topo.process_count,
    )
    return topo


def barrier(name: str = "mv_barrier", participants=None) -> None:
    """Global process barrier.

    Replaces the reference's rank-0 BarrierController round-trip
    (``src/controller.cpp:16-31``): the JAX coordination service provides the
    same rendezvous over DCN; a single-process group is a no-op.

    ``participants`` (survivor mode): rendezvous only the given live
    process ids via a coordination-service barrier — a device-collective
    barrier over ALL processes would wait on the dead peer forever. Pass
    it only from one-shot phases (e.g. shutdown): KV barrier ids are
    single-use per name.
    """
    import jax

    if jax.process_count() > 1:
        if participants is not None:
            from jax._src import distributed

            client = distributed.global_state.client
            if client is not None:
                client.wait_at_barrier(f"mvb/{name}", 600_000,
                                       sorted(participants))
                return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def sharding_for(mesh, *axes: Optional[str]):
    """NamedSharding helper: ``sharding_for(mesh, SERVER_AXIS)`` etc."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*axes))
