"""Request-level causal tracing: Dapper-style spans over the host path.

The Dashboard (``dashboard.py``) answers *how slow* (p50/p95/p99 over a
window); this module answers *why this one* — each request carries a
trace id through every host-side stage it touches (router enqueue,
batcher queue wait, engine admission/prefill, each decode iteration,
even a cross-process publish->apply hop on the async bus), and each
stage records a :class:`Span` with that trace id, its own span id and
its parent's. The resulting tree explains a single p99 outlier: queue
wait vs bucket miss vs snapshot pin vs a co-batched long prefill.

Design constraints, in order:

* **off = free** — tracing is DISABLED by default and the hot paths gate
  on :func:`enabled` (one attribute read) before touching anything here,
  so the decode loop allocates nothing per iteration when off (guarded
  by a test).
* **on = cheap** — finished spans land in a bounded preallocated ring
  (:class:`TraceCollector.record`): one short lock, no I/O, no
  serialization on the request path. Export walks the ring afterwards.
* **on can stay on** — with tail-based sampling (:class:`TailConfig`,
  ``-trace_tail``) spans buffer per trace id and only the trees worth
  keeping survive the request's completion: SLO breaches, errors/sheds,
  and a 1-in-N head sample. The ring then holds explanations, not
  traffic, and full tracing is cheap enough for benches and fleets.
* **causality crosses threads and processes** — the thread-local ambient
  span covers same-thread nesting; a :class:`SpanContext` handoff token
  (``current_context()`` / ``Span.context``) carries (trace id, span id)
  across the submit->batcher->engine thread boundaries, and two u64
  header fields carry it inside async-bus wire records so a peer's
  apply span links to the publisher's trace.
* **one timebase** — span timestamps are ``time.monotonic()`` seconds
  (the clock the serving layer already stamps ``t_enq`` with), rebased
  to epoch microseconds at export via an anchor captured at
  ``enable()``; host spans and device (xprof) captures can then be
  merged by time range (``tools/trace_summary.py --host-trace``).

Export is Chrome trace-event JSON (``{"traceEvents": [...]}``) with
B/E event pairs, one synthetic track per (trace id, recording thread)
— loadable in Perfetto / ``chrome://tracing`` next to an xprof device
capture (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from .analysis import lockwatch
import time
from typing import Any, Dict, List, NamedTuple, Optional

__all__ = [
    "Span", "SpanContext", "TailConfig", "TraceCollector", "collector",
    "enabled", "enable", "disable", "resume", "start_span", "span",
    "record_span", "current_span", "current_context", "export_chrome",
    "span_from_dict", "validate_chrome_events",
]

# Span/trace ids: process-unique, allocation-cheap. itertools.count is
# GIL-atomic per next(); the random 32-bit salt keeps ids from different
# processes (bus publisher vs consumer) from colliding in a merged view.
_SALT = int.from_bytes(os.urandom(4), "little")
_ids = itertools.count(1)


def _new_id() -> int:
    return (_SALT << 32) | (next(_ids) & 0xFFFFFFFF)


class TailConfig(NamedTuple):
    """Tail-based sampling policy (Canopy/Dapper-style): spans buffer per
    trace id until the trace's ROOT span finishes, and the whole tree is
    retained only when the request turned out to be worth keeping —

    * ``slo_ms`` — the root span breached this latency objective;
    * any span in the tree recorded an ``error`` attr (shed, validation
      reject, exec failure) or the root closed ``ok=False``;
    * ``head_n`` — a 1-in-N head sample of completed traces rides along
      regardless, so the retained set always contains *normal* requests
      to compare the anomalies against (0 keeps anomalies only).

    Everything else is discarded at the decision point, so tracing
    becomes cheap enough to leave on under sustained traffic: the ring
    holds only the explanatory traces, and ``max_pending`` bounds the
    undecided buffer (the oldest undecided trace is evicted wholesale
    past it — fragments whose root lives in another process can never
    pin memory)."""

    slo_ms: float = 250.0
    head_n: int = 64
    max_pending: int = 8192


class SpanContext(NamedTuple):
    """Handoff token: everything a child span needs from its parent.

    Immutable and thread-agnostic — capture it with
    :func:`current_context` (or ``Span.context``) on the submitting
    thread, hand it to the worker thread (a queue entry field, a wire
    header), and open children with ``span(name, parent=token)``.
    """

    trace_id: int
    span_id: int


class Span:
    """One named, timed, attributed interval of a trace.

    Created via :func:`start_span`/:func:`span`; finished with
    :meth:`end` (the context manager does it). ``attrs`` carry the
    explanatory payload (bucket choice, slot, snapshot version, ...).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "attrs", "thread")

    def __init__(self, name: str, trace_id: int, span_id: int,
                 parent_id: Optional[int], t0: float,
                 attrs: Optional[Dict[str, Any]]) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: Optional[float] = None
        self.attrs = attrs or {}
        self.thread = threading.current_thread().name

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set(self, **attrs: Any) -> "Span":
        """Attach attributes after creation (e.g. a version only known
        once the span's work ran)."""
        self.attrs.update(attrs)
        return self

    def end(self, **attrs: Any) -> "Span":
        """Close the span and hand it to the collector (idempotent)."""
        if self.t1 is None:
            self.t1 = time.monotonic()
            if attrs:
                self.attrs.update(attrs)
            _COLLECTOR.record(self)
        return self

    def duration_ms(self) -> float:
        return ((self.t1 if self.t1 is not None else time.monotonic())
                - self.t0) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        """Wire form for cross-process shipping (the fleet observability
        plane's report records): plain JSON-serializable fields,
        timestamps still in the RECORDING process's monotonic clock —
        the shipper sends its clock anchor alongside
        (:meth:`TraceCollector.anchor`) so the collector rebases each
        node to the shared epoch-µs export timebase."""
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "t0": self.t0, "t1": self.t1, "thread": self.thread,
                "attrs": dict(self.attrs)}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, trace={self.trace_id:x}, "
                f"span={self.span_id:x}, parent="
                f"{self.parent_id and f'{self.parent_id:x}'}, "
                f"dur={self.duration_ms():.3f} ms)")


class _NullSpan:
    """Shared do-nothing stand-in returned while tracing is disabled —
    callers hold/end it without a per-call allocation."""

    __slots__ = ()
    name = ""
    trace_id = 0
    span_id = 0
    parent_id = None
    context = None
    attrs: Dict[str, Any] = {}

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> "_NullSpan":
        return self

    def end(self, **attrs: Any) -> "_NullSpan":
        return self

    def duration_ms(self) -> float:
        return 0.0


NULL_SPAN = _NullSpan()

_tls = threading.local()


def _stack() -> List[Span]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class TraceCollector:
    """Bounded ring of finished spans (lock-cheap single-writer append).

    ``enabled`` is a plain attribute so hot paths can gate on one
    read; ``record`` takes one short lock to bump the ring cursor. When
    the ring wraps, the oldest spans are overwritten and ``dropped``
    counts them — tracing stays bounded under sustained traffic.
    """

    def __init__(self, capacity: int = 65536) -> None:
        self.enabled = False
        self.capacity = int(capacity)
        self._buf: List[Optional[Span]] = [None] * self.capacity
        self._pos = 0
        self._n = 0
        self.dropped = 0
        self.recorded = 0
        self._lock = lockwatch.lock("trace.TraceCollector._lock")
        # monotonic->epoch anchor for export (set at enable())
        self._anchor_wall = time.time()
        self._anchor_mono = time.monotonic()
        # tail-based sampling (None = record every finished span)
        self._tail: Optional[TailConfig] = None
        self._pending: Dict[int, List[Span]] = {}
        self._pending_n = 0
        self._decisions: Dict[int, bool] = {}
        self.tail_completed = 0          # traces whose root finished
        self.tail_kept = 0               # ... retained into the ring
        self.tail_discarded = 0          # ... dropped at decision time
        self.tail_evicted = 0            # undecided traces evicted (bound)
        self.tail_span_drops = 0         # spans dropped by either path

    # -- lifecycle ----------------------------------------------------------
    def start(self, capacity: Optional[int] = None,
              tail: Optional[TailConfig] = None) -> None:
        """(Re)start collecting: the ring, counters and clock anchor all
        reset, so a second traced session in the same process never
        exports the previous run's spans. ``tail`` switches on tail-based
        sampling (None = record everything, the pre-existing behavior)."""
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
            self._buf = [None] * self.capacity
            self._pos = self._n = 0
            self.dropped = 0
            self.recorded = 0
            self._anchor_wall = time.time()
            self._anchor_mono = time.monotonic()
            self._tail = tail
            self._clear_tail_locked()
            self.enabled = True

    def stop(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._pos = self._n = 0
            self.dropped = 0
            self.recorded = 0
            self._clear_tail_locked()

    def _clear_tail_locked(self) -> None:
        self._pending.clear()
        self._pending_n = 0
        self._decisions.clear()
        self.tail_completed = 0
        self.tail_kept = 0
        self.tail_discarded = 0
        self.tail_evicted = 0
        self.tail_span_drops = 0

    # -- record/read --------------------------------------------------------
    def _append_locked(self, sp: Span) -> None:
        if self._n == self.capacity:
            self.dropped += 1
        self._buf[self._pos] = sp
        self._pos = (self._pos + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)
        self.recorded += 1

    def record(self, sp: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            if self._tail is None:
                self._append_locked(sp)
            else:
                self._tail_record_locked(sp)

    def _tail_record_locked(self, sp: Span) -> None:
        """Buffer under the span's trace id; decide at root completion.

        A span landing AFTER its trace was decided (an engine-thread
        iteration racing the submit-thread's root end) follows the
        decision — retained traces stay whole, discarded ones don't
        resurrect. The decision memo is bounded (oldest forgotten)."""
        tid = sp.trace_id
        decided = self._decisions.get(tid)
        if decided is not None:
            if decided:
                self._append_locked(sp)
            else:
                self.tail_span_drops += 1
            return
        self._pending.setdefault(tid, []).append(sp)
        self._pending_n += 1
        if sp.parent_id is None:         # a root finished: decide its tree
            self._tail_decide_locked(tid, sp)
        elif self._pending_n > self._tail.max_pending:
            # bounded memory: evict the oldest undecided trace wholesale
            # (insertion order = arrival order of each trace's first span)
            old_tid = next(iter(self._pending))
            old = self._pending.pop(old_tid)
            self._pending_n -= len(old)
            self.tail_evicted += 1
            self.tail_span_drops += len(old)

    def _tail_decide_locked(self, tid: int, root: Span) -> None:
        cfg = self._tail
        buf = self._pending.pop(tid, [])
        self._pending_n -= len(buf)
        self.tail_completed += 1
        keep = None
        if (root.t1 is not None
                and (root.t1 - root.t0) * 1e3 >= cfg.slo_ms > 0):
            keep = "slo"
        elif any("error" in s.attrs or s.attrs.get("ok") is False
                 for s in buf):
            keep = "error"
        elif cfg.head_n > 0 and (self.tail_completed - 1) % cfg.head_n == 0:
            keep = "head"
        if keep is None:
            self.tail_discarded += 1
            self.tail_span_drops += len(buf)
            self._decisions[tid] = False
        else:
            root.attrs["tail_keep"] = keep
            self.tail_kept += 1
            for s in buf:
                self._append_locked(s)
            self._decisions[tid] = True
        # the memo only has to outlive the decision races (late children
        # of a just-ended root); cap it so ids never accumulate
        while len(self._decisions) > 4096:
            self._decisions.pop(next(iter(self._decisions)))

    def spans(self) -> List[Span]:
        """Retained spans, oldest first."""
        with self._lock:
            if self._n < self.capacity:
                out = self._buf[: self._n]
            else:
                out = self._buf[self._pos:] + self._buf[: self._pos]
        return [s for s in out if s is not None]

    def drain_since(self, cursor: int):
        """``(new_cursor, spans recorded after cursor, missed)`` — the
        fleet plane's incremental read. ``cursor`` is a previous call's
        return (start at 0); spans come back oldest first. When more
        spans were recorded since the cursor than the ring retains, the
        overwritten ones are gone — ``missed`` counts them so the
        shipper can report the loss instead of silently thinning the
        fleet trace. ``start()``/``clear()`` reset ``recorded``, so a
        stale cursor larger than it simply rebases to the new stream."""
        with self._lock:
            recorded = self.recorded
            if cursor > recorded:
                cursor = 0                     # ring was reset; rebase
            n_new = recorded - cursor
            if n_new <= 0:
                return recorded, [], 0
            take = min(n_new, self._n)
            start = (self._pos - take) % self.capacity
            if start < self._pos or take == 0:
                out = self._buf[start: self._pos]
            else:
                out = self._buf[start:] + self._buf[: self._pos]
        return recorded, [s for s in out if s is not None], n_new - take

    def anchor(self):
        """``(epoch s, monotonic s)`` captured at :meth:`start` — ships
        with serialized spans so a collector in another process can
        rebase them onto the shared epoch-µs export timebase."""
        with self._lock:
            return self._anchor_wall, self._anchor_mono

    def to_epoch_us(self, t_mono: float) -> float:
        """Rebase a monotonic timestamp to epoch microseconds (the
        export timebase, mergeable with device captures by range)."""
        return (self._anchor_wall + (t_mono - self._anchor_mono)) * 1e6

    # -- export -------------------------------------------------------------
    def chrome_events(self) -> List[dict]:
        """Chrome trace-event B/E pairs, sorted by timestamp.

        Each (trace id, recording thread) pair gets its own synthetic
        ``tid`` track. Per trace alone is not enough: spans of ONE trace
        recorded by different threads can overlap in wall time (a root
        ended early by a cancelled future while the flush thread still
        records its queue wait; a loopback ``bus.apply`` racing its
        ``bus.publish``), which would interleave B/E pairs on a shared
        track. One thread's spans for one trace are sequential by
        construction, so per-(trace, thread) tracks always nest; the
        request's spans stay joined by the ``trace_id`` arg.
        """
        pid = os.getpid()
        events: List[dict] = []
        # sequential tid per (trace, thread): collision-free by
        # construction (a hashed tid had a birthday chance of merging
        # two overlapping tracks and breaking their B/E nesting)
        tids: Dict[tuple, int] = {}
        for sp in self.spans():
            if sp.t1 is None:
                continue
            tid = tids.setdefault((sp.trace_id, sp.thread), len(tids) + 1)
            args = {"trace_id": f"{sp.trace_id:x}",
                    "span_id": f"{sp.span_id:x}",
                    "thread": sp.thread}
            if sp.parent_id is not None:
                args["parent_id"] = f"{sp.parent_id:x}"
            args.update(sp.attrs)
            ts0 = self.to_epoch_us(sp.t0)
            ts1 = self.to_epoch_us(sp.t1)
            events.append({"name": sp.name, "ph": "B", "ts": ts0,
                           "pid": pid, "tid": tid, "args": args})
            events.append({"name": sp.name, "ph": "E", "ts": ts1,
                           "pid": pid, "tid": tid})
        # stable sort: E before B at identical ts only when the E's B came
        # first; (ts, index) keeps emission order for ties within a track
        events.sort(key=lambda e: e["ts"])
        return events

    def export_chrome(self, path: Optional[str] = None) -> dict:
        """Build (and optionally write) ``{"traceEvents": [...]}``."""
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": self.dropped,
                          "recorded_spans": self.recorded,
                          "clock": "epoch_us"},
        }
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def stats(self) -> dict:
        with self._lock:
            out = {"enabled": self.enabled, "retained": self._n,
                   "capacity": self.capacity, "dropped": self.dropped,
                   "recorded": self.recorded}
            if self._tail is not None:
                out["tail"] = {
                    "slo_ms": self._tail.slo_ms,
                    "head_n": self._tail.head_n,
                    "pending_traces": len(self._pending),
                    "pending_spans": self._pending_n,
                    "completed": self.tail_completed,
                    "kept": self.tail_kept,
                    "discarded": self.tail_discarded,
                    "evicted": self.tail_evicted,
                    "span_drops": self.tail_span_drops,
                }
            return out


_COLLECTOR = TraceCollector()


def collector() -> TraceCollector:
    return _COLLECTOR


def enabled() -> bool:
    """THE hot-path gate: one attribute read, no allocation."""
    return _COLLECTOR.enabled


def enable(capacity: Optional[int] = None,
           tail: Optional[TailConfig] = None) -> None:
    _COLLECTOR.start(capacity, tail)


def disable() -> None:
    _COLLECTOR.stop()


def resume() -> None:
    """Re-enable collection WITHOUT resetting the ring, tail state or
    clock anchor — the counterpart of :func:`disable` for a momentary
    off window (e.g. the bench's tracing-off A/B leg) inside one traced
    session. :func:`enable` would wipe everything recorded so far."""
    _COLLECTOR.enabled = True


# -- span creation ----------------------------------------------------------

def current_span() -> Optional[Span]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_context() -> Optional[SpanContext]:
    """The handoff token for the ambient span (None outside any span)."""
    sp = current_span()
    return sp.context if sp is not None else None


def start_span(name: str, parent: Optional[SpanContext] = None,
               root: bool = False, **attrs: Any):
    """Open a span NOW; the caller owns ``end()``.

    Parentage: ``root=True`` starts a fresh trace; an explicit
    ``parent`` token adopts that trace (the cross-thread handoff);
    otherwise the ambient thread-local span is the parent (fresh trace
    if there is none). Returns :data:`NULL_SPAN` while disabled.
    """
    if not _COLLECTOR.enabled:
        return NULL_SPAN
    if root:
        trace_id, parent_id = _new_id(), None
    elif parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        amb = current_span()
        if amb is not None:
            trace_id, parent_id = amb.trace_id, amb.span_id
        else:
            trace_id, parent_id = _new_id(), None
    return Span(name, trace_id, _new_id(), parent_id, time.monotonic(),
                attrs or None)


class _SpanScope:
    """Context manager pushing a span onto the thread-local stack, so
    spans opened inside it become its children without explicit tokens."""

    __slots__ = ("_span",)

    def __init__(self, sp: Span) -> None:
        self._span = sp

    def __enter__(self) -> Span:
        _stack().append(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        stack = _stack()
        if stack and stack[-1] is self._span:
            stack.pop()
        if exc_type is not None:
            self._span.set(error=exc_type.__name__)
        self._span.end()
        return False


def span(name: str, parent: Optional[SpanContext] = None,
         root: bool = False, **attrs: Any):
    """``with span("stage", parent=token, k=v) as sp:`` — the ambient
    form of :func:`start_span` (children opened inside nest under it).
    A no-op shared object while disabled."""
    if not _COLLECTOR.enabled:
        return NULL_SPAN
    return _SpanScope(start_span(name, parent=parent, root=root, **attrs))


def record_span(name: str, parent: Optional[SpanContext], t0: float,
                t1: float, **attrs: Any) -> None:
    """Record an interval measured elsewhere (``time.monotonic()``
    endpoints) as a finished span — the batcher/engine use this to emit
    per-request child spans after a batch-level operation completed,
    without holding open Span objects per queued request."""
    if not _COLLECTOR.enabled:
        return
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = _new_id(), None
    sp = Span(name, trace_id, _new_id(), parent_id, t0, attrs or None)
    sp.t1 = t1
    _COLLECTOR.record(sp)


def export_chrome(path: Optional[str] = None) -> dict:
    return _COLLECTOR.export_chrome(path)


def span_from_dict(d: Dict[str, Any]) -> Span:
    """Inverse of :meth:`Span.to_dict` (collector-side tests and any
    consumer that wants Span objects back from wire records)."""
    sp = Span(d["name"], int(d["trace_id"]), int(d["span_id"]),
              d.get("parent_id"), float(d["t0"]), dict(d.get("attrs") or {}))
    sp.t1 = d.get("t1")
    sp.thread = d.get("thread", sp.thread)
    return sp


# -- validation (shared by the CI smoke test and tools) ----------------------

def validate_chrome_events(events: List[dict],
                           root_name: Optional[str] = None) -> dict:
    """Structural validation of a Chrome trace-event list.

    Checks (raises ``ValueError`` on the first violation):

    * global ``ts`` monotonicity (the export contract: sorted events);
    * per-(pid, tid) B/E matching — every E closes the innermost open B
      of the same name, nothing left open at the end;
    * every B carries trace_id/span_id args; within a trace whose root
      IS in this export, children must cite a parent_id that exists (a
      dangling parent there means a handoff token outlived its span's
      export). Traces with no local root are FRAGMENTS — e.g. a
      consumer process's ``bus.apply`` spans parented under a publisher
      process's span, or children of a request still in flight — and
      their parent links point outside this export by design;
    * with ``root_name``: no trace id has more than one parentless span
      of THAT name (the "one root per request" contract; fragments have
      zero and pass, and roots of other names — ``snapshot.pin``,
      ``table.add`` — are not counted against it).

    Returns summary counts: ``{"events", "spans", "traces", "roots"}``
    (``roots`` counts only ``root_name`` roots when one is given).
    """
    # pass 1: the full span-id population per trace (parent links may
    # cite a span whose B sorts later — e.g. identical timestamps), and
    # which traces have a local root (only those can be held to the
    # no-dangling-parent rule; the rest are cross-process/in-flight
    # fragments)
    trace_spans: Dict[str, set] = {}
    rooted: set = set()
    for i, e in enumerate(events):
        if e.get("ph") != "B":
            continue
        args = e.get("args", {})
        trace_id, span_id = args.get("trace_id"), args.get("span_id")
        if not trace_id or not span_id:
            raise ValueError(f"event {i}: B without trace_id/span_id")
        trace_spans.setdefault(trace_id, set()).add(span_id)
        if args.get("parent_id") is None:
            rooted.add(trace_id)
    # pass 2: ordering, nesting, parent links
    last_ts = None
    open_stacks: Dict[tuple, List[dict]] = {}
    roots: Dict[str, int] = {}
    n_spans = 0
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph not in ("B", "E"):
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            raise ValueError(f"event {i}: non-numeric ts {ts!r}")
        if last_ts is not None and ts < last_ts:
            raise ValueError(f"event {i}: ts {ts} < previous {last_ts} "
                             "(export must be time-sorted)")
        last_ts = ts
        key = (e.get("pid"), e.get("tid"))
        stack = open_stacks.setdefault(key, [])
        if ph == "B":
            args = e.get("args", {})
            trace_id, span_id = args["trace_id"], args["span_id"]
            parent = args.get("parent_id")
            if parent is None:
                if root_name is None or e.get("name") == root_name:
                    roots[trace_id] = roots.get(trace_id, 0) + 1
            elif (trace_id in rooted
                    and parent not in trace_spans[trace_id]):
                raise ValueError(
                    f"event {i}: span {span_id} cites unknown parent "
                    f"{parent} in trace {trace_id}")
            stack.append(e)
            n_spans += 1
        else:
            if not stack:
                raise ValueError(f"event {i}: E with no open B on {key}")
            top = stack.pop()
            if top.get("name") != e.get("name"):
                raise ValueError(
                    f"event {i}: E({e.get('name')!r}) closes "
                    f"B({top.get('name')!r}) — interleaved, not nested")
    for key, stack in open_stacks.items():
        if stack:
            raise ValueError(
                f"track {key}: {len(stack)} B event(s) never closed "
                f"(first: {stack[0].get('name')!r})")
    if root_name is not None:
        for trace_id, n in roots.items():
            if n > 1:
                raise ValueError(
                    f"trace {trace_id}: {n} root spans (expected 1)")
    return {"events": len(events), "spans": n_spans,
            "traces": len(trace_spans), "roots": sum(roots.values())}
