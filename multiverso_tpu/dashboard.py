"""Named timing monitors + process-global dashboard.

TPU-native equivalent of the reference observability layer
(``include/multiverso/dashboard.h:16-73``, ``src/dashboard.cpp:14-45`` in the
Multiverso reference): named ``Monitor`` timers (count / total ms / average)
registered into a process-global ``Dashboard``, a ``monitor(name)`` context
manager replacing the ``MONITOR_BEGIN/END`` macros, ``Dashboard.watch`` by
name and ``Dashboard.display`` at shutdown.

On TPU the interesting spans are host-side walls around dispatched programs;
``monitor(..., block=True)`` additionally calls
``jax.block_until_ready`` on a result so the span covers device execution,
not just async dispatch.
"""

from __future__ import annotations

import json
import math
import re
import threading
from .analysis import lockwatch
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional


class Timer:
    """Wall-clock start/elapse timer (reference ``util/timer.h:8-24``)."""

    def __init__(self) -> None:
        self.start()

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def elapse_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3


class Monitor:
    """Accumulating named timer (reference ``dashboard.h:26-57``).

    Start timestamps are thread-local so concurrent spans on the same
    monitor name don't clobber each other's begin().
    """

    def __init__(self, name: str, register: bool = True) -> None:
        self.name = name
        self.count = 0
        self.total_ms = 0.0
        self._local = threading.local()
        self._lock = lockwatch.lock("dashboard.Monitor._lock")
        if register:
            Dashboard.add_monitor(self)

    def begin(self) -> None:
        self._local.t0 = time.perf_counter()

    def end(self) -> None:
        t0 = getattr(self._local, "t0", None)
        if t0 is None:
            return
        elapsed = (time.perf_counter() - t0) * 1e3
        self._local.t0 = None
        self.record(elapsed)

    def record(self, elapsed_ms: float) -> None:
        """Fold an externally-measured duration (e.g. a cross-process
        publish->apply latency carried in a wire record)."""
        with self._lock:
            self.count += 1
            self.total_ms += elapsed_ms

    def average_ms(self) -> float:
        with self._lock:
            return self.total_ms / self.count if self.count else 0.0

    def info_string(self) -> str:
        with self._lock:
            avg = self.total_ms / self.count if self.count else 0.0
            return (
                f"[{self.name}] count = {self.count} total = {self.total_ms:.3f} ms "
                f"avg = {avg:.3f} ms"
            )


# -- mergeable log-bucket export ---------------------------------------------
#
# Exact sample windows cannot be merged across processes (shipping 65536
# floats per histogram per report interval would BE the fleet's traffic), so
# the fleet observability plane ships log-bucketed digests instead
# (DDSketch/Prometheus-native-histogram shape): bucket i holds samples in
# (BUCKET_BASE**i, BUCKET_BASE**(i+1)], merge = per-index count addition,
# and any percentile read off merged counts returns the containing bucket's
# geometric midpoint BUCKET_BASE**(i + 0.5).
#
# Error bound: a sample in bucket i is within a factor of BUCKET_BASE**0.5
# of that midpoint, so every percentile-from-buckets value is within
# BUCKET_REL_ERROR (= BUCKET_BASE**0.5 - 1, ~9.05% at base 2**0.25) of the
# exact nearest-rank percentile over the pooled samples — bucketing is
# monotone, so the rank-r sample of the pooled window lands in exactly the
# bucket the merged cumulative walk stops in (tests assert the bound on
# randomized multi-node splits). Values <= 0 land in a dedicated "zero"
# bucket that sorts below every indexed one and reads back as 0.0.

BUCKET_BASE = 2 ** 0.25
BUCKET_REL_ERROR = BUCKET_BASE ** 0.5 - 1
_BUCKET_LOG = math.log(BUCKET_BASE)


def bucket_index(value_ms: float) -> Optional[int]:
    """Log-bucket index for one sample (None = the zero bucket)."""
    if value_ms <= 0.0:
        return None
    return math.floor(math.log(value_ms) / _BUCKET_LOG)


def bucket_value(index: int) -> float:
    """The bucket's representative: the geometric midpoint of its edges."""
    return BUCKET_BASE ** (index + 0.5)


def merge_buckets(exports: List[Optional[Dict[str, Any]]]) -> Dict[str, Any]:
    """Sum per-index counts across node exports (:meth:`Histogram.buckets`
    dicts; ``None`` entries — nodes without that histogram — are skipped).
    Counts key as strings because the exports ride JSON wire records."""
    counts: Dict[str, int] = {}
    zero = 0
    count = 0
    for ex in exports:
        if not ex:
            continue
        zero += int(ex.get("zero", 0))
        count += int(ex.get("count", 0))
        for k, n in ex.get("counts", {}).items():
            counts[str(k)] = counts.get(str(k), 0) + int(n)
    return {"base": BUCKET_BASE, "count": count, "zero": zero,
            "counts": counts}


def bucket_percentile(export: Dict[str, Any], p: float) -> float:
    """Nearest-rank percentile over a (possibly merged) bucket export —
    same rank formula as :meth:`Histogram._rank`, walked over cumulative
    bucket counts, returning the containing bucket's midpoint (so the
    result is within :data:`BUCKET_REL_ERROR` of the pooled-sample
    truth)."""
    counts = export.get("counts", {})
    zero = int(export.get("zero", 0))
    n = zero + sum(int(v) for v in counts.values())
    if n == 0:
        return 0.0
    rank = min(n - 1, max(0, int(round(p / 100.0 * (n - 1)))))
    if rank < zero:
        return 0.0
    seen = zero
    for idx in sorted(int(k) for k in counts):
        seen += int(counts[str(idx)])
        if rank < seen:
            return bucket_value(idx)
    return bucket_value(max(int(k) for k in counts))   # pragma: no cover


def bucket_breach_frac(export: Dict[str, Any], threshold_ms: float) -> float:
    """Fraction of the bucketed window above ``threshold_ms`` (the fleet
    SLO burn numerator). Bucket-granular: a bucket counts as breaching
    when its representative midpoint exceeds the threshold, so the
    answer is exact to within the one bucket straddling the target."""
    counts = export.get("counts", {})
    n = int(export.get("zero", 0)) + sum(int(v) for v in counts.values())
    if n == 0:
        return 0.0
    over = sum(int(v) for k, v in counts.items()
               if bucket_value(int(k)) > threshold_ms)
    return over / n


class Histogram:
    """Bounded latency histogram: count/percentiles over a sliding window.

    The serving layer's per-reply latency sink (p50/p95/p99 + QPS need a
    distribution, not the Monitor's running mean). Keeps the most recent
    ``window`` samples in a ring — old traffic ages out, so percentiles
    track the CURRENT load regime, and memory stays bounded under
    sustained QPS. Thread-safe; registered in the Dashboard next to the
    Monitors so ``display()`` shows both.
    """

    WINDOW = 65536

    def __init__(self, name: str, window: int = WINDOW,
                 register: bool = True) -> None:
        self.name = name
        self.count = 0                      # lifetime samples (QPS numerator)
        self._buf = [0.0] * int(window)
        self._n = 0                         # filled slots (<= window)
        self._pos = 0                       # next write slot
        self._lock = lockwatch.lock("dashboard.Histogram._lock")
        if register:
            Dashboard.add_histogram(self)

    def record(self, value_ms: float) -> None:
        with self._lock:
            self.count += 1
            self._buf[self._pos] = float(value_ms)
            self._pos = (self._pos + 1) % len(self._buf)
            self._n = min(self._n + 1, len(self._buf))

    def reset(self) -> None:
        """Drop retained samples (benches: exclude warmup compiles from
        the measured distribution)."""
        with self._lock:
            self.count = 0
            self._n = 0
            self._pos = 0

    def _window(self):
        """ONE lock acquisition -> (lifetime count, sorted live window).
        The single source of the ring-unwrap + sort both percentile
        consumers share (a wrap-handling fix lands in both). Only the
        COPY happens under the lock: the O(n log n) sort of a full
        65536-slot window would otherwise stall every concurrent
        ``record`` on the serving hot path each time a poller (now
        including the periodic ``MetricsExporter``) asks for a summary."""
        with self._lock:
            n = self._n
            count = self.count
            # unwrapped: slots [0, n) are the live samples; wrapped: all are
            data = (list(self._buf) if n == len(self._buf)
                    else self._buf[:n])
        data.sort()
        return count, data

    @staticmethod
    def _rank(data, p: float) -> float:
        """Nearest-rank percentile over a sorted window."""
        n = len(data)
        return data[min(n - 1, max(0, int(round(p / 100.0 * (n - 1)))))]

    def percentiles(self, ps) -> Dict[float, float]:
        """Nearest-rank percentiles over the retained window in ONE sort
        (0s if empty) — summary()/stats() pollers would otherwise pay a
        full sort per percentile while contending with record()."""
        _, data = self._window()
        if not data:
            return {p: 0.0 for p in ps}
        return {p: self._rank(data, p) for p in ps}

    def percentile(self, p: float) -> float:
        return self.percentiles((p,))[p]

    def window_stats(self, p: float, threshold_ms: float, window=None):
        """``(window n, pXX, fraction of window above threshold)`` in ONE
        sort — the SLO tracker's read (a separate percentile + breach
        scan would pay two sorts and could straddle a wrap). Pass a
        ``window`` (an already-sorted sample list, e.g. the one
        ``Dashboard.snapshot()`` just paid for this histogram's own
        summary row) to skip the copy-under-lock + re-sort entirely."""
        import bisect

        data = self._window()[1] if window is None else window
        if not data:
            return 0, 0.0, 0.0
        frac = 1.0 - bisect.bisect_right(data, threshold_ms) / len(data)
        return len(data), self._rank(data, p), frac

    def summary(self) -> Dict[str, float]:
        """count + nearest-rank p50/p95/p99 + mean/max over the window.

        mean and max ride along because percentile triage alone can't
        rank outliers: a p99 says where the tail STARTS, the max says
        how bad the worst request actually was, and mean-vs-p50 skew is
        the cheapest "long tail present" signal. Count and window are
        read under ONE lock acquisition so the summary is internally
        consistent even while ``record`` hammers concurrently.
        """
        return self._summarize(*self._window())[0]

    def _summarize(self, count, data):
        """``(summary dict, sorted window)`` from one ``_window()`` read —
        ``Dashboard.snapshot()`` hands the window on to this histogram's
        SLO row so the pair shares one copy+sort AND describes the same
        samples."""
        if not data:
            return ({"count": count, "p50_ms": 0.0, "p95_ms": 0.0,
                     "p99_ms": 0.0, "mean_ms": 0.0, "max_ms": 0.0}, data)
        return ({
            "count": count,
            "p50_ms": self._rank(data, 50),
            "p95_ms": self._rank(data, 95),
            "p99_ms": self._rank(data, 99),
            "mean_ms": sum(data) / len(data),
            "max_ms": data[-1],
        }, data)

    def buckets(self) -> Dict[str, Any]:
        """Log-bucket export of the retained window (the mergeable form
        the fleet observability plane ships): ``{"base", "count"
        (lifetime), "n" (window), "zero", "counts": {str(index):
        count}}``. One window copy, no sort; see the module-level
        bucket notes for the merge rule and the documented
        :data:`BUCKET_REL_ERROR` percentile bound."""
        with self._lock:
            count = self.count
            data = (list(self._buf) if self._n == len(self._buf)
                    else self._buf[: self._n])
        counts: Dict[str, int] = {}
        zero = 0
        for v in data:
            idx = bucket_index(v)
            if idx is None:
                zero += 1
            else:
                key = str(idx)
                counts[key] = counts.get(key, 0) + 1
        return {"base": BUCKET_BASE, "count": count, "n": len(data),
                "zero": zero, "counts": counts}

    def info_string(self) -> str:
        s = self.summary()
        return (f"[{self.name}] count = {int(s['count'])} "
                f"p50 = {s['p50_ms']:.3f} ms p95 = {s['p95_ms']:.3f} ms "
                f"p99 = {s['p99_ms']:.3f} ms mean = {s['mean_ms']:.3f} ms "
                f"max = {s['max_ms']:.3f} ms")


class Gauge:
    """Last-value instrument: a point-in-time level, not a distribution.

    The serving engine's occupancy/throughput readouts (slots in use,
    decode tokens/sec) are levels — a histogram of them would average
    away exactly the saturation signal an operator looks for. ``set``
    overwrites; ``get`` reads the latest value.
    """

    def __init__(self, name: str, register: bool = True) -> None:
        self.name = name
        self._value = 0.0
        self._lock = lockwatch.lock("dashboard.Gauge._lock")
        if register:
            Dashboard.add_gauge(self)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def get(self) -> float:
        with self._lock:
            return self._value

    def info_string(self) -> str:
        return f"[{self.name}] value = {self.get():.3f}"


class Counter:
    """Monotonic event counter: things that HAPPENED, never un-happen.

    The Monitor measures durations and the Gauge levels; neither fits
    "requests shed", "idle wakeups", "tokens emitted" — monotonic
    totals whose interval-deltas (``MetricsExporter``) become rates.
    Maps to the Prometheus ``counter`` type in the text exposition.
    """

    def __init__(self, name: str, register: bool = True) -> None:
        self.name = name
        self._value = 0
        self._lock = lockwatch.lock("dashboard.Counter._lock")
        if register:
            Dashboard.add_counter(self)

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"Counter {self.name!r}: negative increment {n}")
        with self._lock:
            self._value += n

    def get(self) -> int:
        with self._lock:
            return self._value

    def info_string(self) -> str:
        return f"[{self.name}] total = {self.get()}"


class SLO:
    """Windowed latency objective over a registered :class:`Histogram`.

    ``source`` names the histogram (``SERVE_TTFT[lm]``); the objective is
    "the windowed p<percentile> stays under ``target_ms``". ``summary()``
    reports the current percentile, the fraction of the window breaching
    the target, and the **burn rate** — breach fraction over the error
    budget ``1 - percentile/100`` (burn > 1 means the tail is eating its
    budget faster than allowed; the SRE alarm convention). Rolling by
    construction: the histogram window ages old traffic out, so burn
    tracks the CURRENT regime, not the lifetime average.
    """

    def __init__(self, source: str, target_ms: float,
                 percentile: float = 99.0, register: bool = True) -> None:
        self.source = source
        self.target_ms = float(target_ms)
        self.percentile = float(percentile)
        self.name = f"SLO_P{percentile:g}[{source}]"
        if register:
            Dashboard.add_slo(self)

    def summary(self, window=None) -> Dict[str, float]:
        hist = Dashboard.get_or_create_histogram(self.source)
        n, value, frac = hist.window_stats(self.percentile, self.target_ms,
                                           window=window)
        budget = max(1.0 - self.percentile / 100.0, 1e-9)
        return {
            "target_ms": self.target_ms,
            "percentile": self.percentile,
            "window": n,
            "value_ms": value,
            "breach_frac": frac,
            "burn": frac / budget,
            "ok": 0 if (n and value > self.target_ms) else 1,
        }

    def info_string(self) -> str:
        s = self.summary()
        state = "OK" if s["ok"] else "BURNING"
        return (f"[{self.name}] p{self.percentile:g} = {s['value_ms']:.3f} "
                f"ms target = {self.target_ms:.3f} ms burn = "
                f"{s['burn']:.2f} ({state})")


class Dashboard:
    """Process-global monitor registry (reference ``dashboard.h:16-24``)."""

    _monitors: Dict[str, Monitor] = {}
    _histograms: Dict[str, "Histogram"] = {}
    _gauges: Dict[str, "Gauge"] = {}
    _counters: Dict[str, "Counter"] = {}
    _slos: Dict[str, "SLO"] = {}
    # running reporter/watchdog threads (anything with .detach());
    # reset() stops them so tests can't leak threads across each other
    _reporters: List[Any] = []
    _lock = lockwatch.lock("dashboard.Dashboard._lock")

    @classmethod
    def add_monitor(cls, mon: Monitor) -> None:
        with cls._lock:
            cls._monitors[mon.name] = mon

    @classmethod
    def add_histogram(cls, hist: "Histogram") -> None:
        with cls._lock:
            cls._histograms[hist.name] = hist

    @classmethod
    def add_gauge(cls, gauge: "Gauge") -> None:
        with cls._lock:
            cls._gauges[gauge.name] = gauge

    @classmethod
    def add_counter(cls, counter: "Counter") -> None:
        with cls._lock:
            cls._counters[counter.name] = counter

    @classmethod
    def add_slo(cls, slo: "SLO") -> None:
        with cls._lock:
            cls._slos[slo.name] = slo

    @classmethod
    def set_slo(cls, source: str, target_ms: float,
                percentile: float = 99.0) -> "SLO":
        """Declare (or re-target) a latency objective over histogram
        ``source``; its burn status rides every ``snapshot()``."""
        name = f"SLO_P{percentile:g}[{source}]"
        with cls._lock:
            slo = cls._slos.get(name)
        if slo is None:
            slo = SLO(source, target_ms, percentile)
        else:
            slo.target_ms = float(target_ms)
        return slo

    @classmethod
    def attach_reporter(cls, reporter: Any) -> None:
        """Track a running reporter thread (MetricsExporter, watchdog);
        ``reset()`` detaches and stops whatever is still attached."""
        with cls._lock:
            if reporter not in cls._reporters:
                cls._reporters.append(reporter)

    @classmethod
    def detach_reporter(cls, reporter: Any) -> None:
        with cls._lock:
            if reporter in cls._reporters:
                cls._reporters.remove(reporter)

    @classmethod
    def get_or_create_histogram(cls, name: str) -> "Histogram":
        with cls._lock:
            hist = cls._histograms.get(name)
            if hist is None:
                hist = Histogram(name, register=False)
                cls._histograms[name] = hist
            return hist

    @classmethod
    def get_or_create_gauge(cls, name: str) -> "Gauge":
        with cls._lock:
            gauge = cls._gauges.get(name)
            if gauge is None:
                gauge = Gauge(name, register=False)
                cls._gauges[name] = gauge
            return gauge

    @classmethod
    def get_or_create(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = Monitor(name, register=False)
                cls._monitors[name] = mon
            return mon

    @classmethod
    def get_or_create_counter(cls, name: str) -> "Counter":
        with cls._lock:
            counter = cls._counters.get(name)
            if counter is None:
                counter = Counter(name, register=False)
                cls._counters[name] = counter
            return counter

    @classmethod
    def watch(cls, name: str) -> str:
        """Live one-liner for ANY registered instrument. Resolves every
        kind — ``watch("SERVE_TTFT[lm]")`` must report the histogram,
        not "not monitored" (it used to check Monitors only)."""
        with cls._lock:
            inst = (cls._monitors.get(name) or cls._histograms.get(name)
                    or cls._gauges.get(name) or cls._counters.get(name)
                    or cls._slos.get(name))
        return inst.info_string() if inst else f"[{name}] not monitored"

    @classmethod
    def stats(cls, name: str) -> Optional[Dict[str, float]]:
        with cls._lock:
            mon = cls._monitors.get(name)
            hist = cls._histograms.get(name)
            gauge = cls._gauges.get(name)
            counter = cls._counters.get(name)
            slo = cls._slos.get(name)
        if mon is not None:
            return {"count": mon.count, "total_ms": mon.total_ms,
                    "avg_ms": mon.average_ms()}
        if hist is not None:
            return hist.summary()
        if gauge is not None:
            return {"value": gauge.get()}
        if counter is not None:
            return {"value": counter.get()}
        if slo is not None:
            return slo.summary()
        return None

    @classmethod
    def snapshot(cls) -> Dict[str, Dict[str, Any]]:
        """EVERY instrument's current state as one plain dict.

        ``{name: {"type": kind, ...stats}}`` — JSON-serializable floats
        and ints only, so the same object feeds the JSON-lines reporter,
        the Prometheus renderer, and bench archives
        (``tools/serving_bench.py``) without per-sink formats.
        """
        with cls._lock:
            monitors = list(cls._monitors.values())
            histograms = list(cls._histograms.values())
            gauges = list(cls._gauges.values())
            counters = list(cls._counters.values())
            slos = list(cls._slos.values())
        out: Dict[str, Dict[str, Any]] = {}
        for m in monitors:
            out[m.name] = {"type": "monitor", "count": m.count,
                           "total_ms": m.total_ms, "avg_ms": m.average_ms()}
        windows: Dict[str, list] = {}
        for h in histograms:
            summary, windows[h.name] = h._summarize(*h._window())
            out[h.name] = {"type": "histogram", **summary}
        for g in gauges:
            out[g.name] = {"type": "gauge", "value": g.get()}
        for c in counters:
            out[c.name] = {"type": "counter", "value": c.get()}
        for s in slos:
            # reuse the source histogram's sorted window: one copy+sort
            # per histogram per snapshot, and the SLO row describes the
            # SAME samples as the histogram row above it
            out[s.name] = {"type": "slo",
                           **s.summary(window=windows.get(s.source))}
        return out

    @classmethod
    def display(cls, emit=None) -> str:
        with cls._lock:
            monitors = list(cls._monitors.values())
            histograms = list(cls._histograms.values())
            gauges = list(cls._gauges.values())
            counters = list(cls._counters.values())
            slos = list(cls._slos.values())
        lines = ["--------------Dashboard--------------"]
        lines += [m.info_string() for m in monitors]
        lines += [h.info_string() for h in histograms]
        lines += [g.info_string() for g in gauges]
        lines += [c.info_string() for c in counters]
        lines += [s.info_string() for s in slos]
        text = "\n".join(lines)
        if emit is None:
            from .log import Log
            emit = Log.info
        emit("%s", text)
        return text

    @classmethod
    def reset(cls) -> None:
        """Drop every instrument AND stop any attached reporter thread
        (MetricsExporter, engine watchdogs): a test that resets the
        dashboard must not inherit a prior test's reporter still
        snapshotting (or a watchdog still polling a dead engine).
        Reporters are popped under the lock but stopped OUTSIDE it —
        their threads may be mid-``snapshot()`` and need the lock to
        finish before they can join."""
        with cls._lock:
            cls._monitors.clear()
            cls._histograms.clear()
            cls._gauges.clear()
            cls._counters.clear()
            cls._slos.clear()
            reporters = list(cls._reporters)
            cls._reporters.clear()
        for reporter in reporters:
            try:
                reporter.detach()
            except Exception as exc:    # pragma: no cover - defensive
                from .log import Log
                Log.error("dashboard reset: reporter detach failed: %s", exc)


@contextmanager
def monitor(name: str, block_on: Any = None) -> Iterator[Monitor]:
    """Span context manager replacing MONITOR_BEGIN/END.

    If ``block_on`` is supplied (a jax.Array / pytree produced inside the
    span), it is blocked on before the span closes so device time is counted.
    """
    mon = Dashboard.get_or_create(name)
    mon.begin()
    try:
        yield mon
    finally:
        if block_on is not None:
            import jax
            jax.block_until_ready(block_on)
        mon.end()


def monitored_block_until_ready(name: str, value: Any) -> Any:
    """Time a block_until_ready on ``value`` under monitor ``name``."""
    import jax

    mon = Dashboard.get_or_create(name)
    mon.begin()
    jax.block_until_ready(value)
    mon.end()
    return value


@contextmanager
def profile_trace(log_dir: str, name: str = "PROFILE") -> Iterator[Monitor]:
    """Capture an XLA profiler trace for the enclosed span.

    Observability tier above the reference's wall-clock Monitors (SURVEY
    §5.5: "no tracing spans"): wraps ``jax.profiler`` so the span's device
    timeline (HLO ops, HBM transfers, collective phases) lands in
    ``log_dir`` for TensorBoard/xprof, while a Dashboard monitor records
    the same span's wall time alongside the other counters.
    """
    import jax

    mon = Dashboard.get_or_create(name)
    mon.begin()
    jax.profiler.start_trace(log_dir)
    try:
        yield mon
    finally:
        jax.profiler.stop_trace()
        mon.end()


# -- metrics export ----------------------------------------------------------

# The ONE definition of which snapshot stats are monotonic, shared by the
# Prometheus renderer (# TYPE counter vs gauge) and the JSONL reporter's
# interval deltas — two hardcoded copies would drift and make the sinks
# disagree about which stats are rates.
_MONOTONE_STATS = frozenset({
    ("counter", "value"), ("monitor", "count"), ("monitor", "total_ms"),
    ("histogram", "count"),
})


def snapshot_deltas(prev: Optional[Dict[str, Dict[str, Any]]],
                    snap: Dict[str, Dict[str, Any]],
                    dt: Optional[float]) -> Dict[str, Dict[str, float]]:
    """Interval deltas of the monotonic stats between two snapshots —
    THE delta semantics, shared by :class:`MetricsExporter` and the
    fleet observability plane's per-node reports
    (``serving/obs_plane.py``), so the JSONL reporter and the wire can
    never drift on what counts as a rate.

    Covers the ``_MONOTONE_STATS`` fields only. An instrument whose
    monotonic stats went BACKWARDS (reset mid-interval) reports no
    delta rather than a negative rate; an instrument absent from
    ``prev`` (or whose type changed) is skipped for this interval and
    picked up on the next one."""
    if prev is None or not dt or dt <= 0:
        return {}
    deltas: Dict[str, Dict[str, float]] = {}
    for name, row in snap.items():
        last = prev.get(name)
        if last is None or last.get("type") != row.get("type"):
            continue
        kind = row.get("type")
        d: Dict[str, float] = {}
        for field, value in row.items():
            if (kind, field) not in _MONOTONE_STATS:
                continue
            diff = value - last.get(field, 0)
            if diff < 0:
                d = {}
                break               # instrument was reset mid-interval
            d[field] = diff
            d[f"{field}_per_s"] = diff / dt
        if d:
            deltas[name] = d
    return deltas


def _prom_split(name: str):
    """``SERVE_TTFT[lm]`` -> (``serve_ttft``, ``lm``); plain names pass
    through with no instance label. The bracket convention is how every
    per-model instrument in this codebase is named."""
    instance = None
    base = name
    if name.endswith("]") and "[" in name:
        base, _, rest = name.partition("[")
        instance = rest[:-1]
    metric = re.sub(r"[^a-zA-Z0-9_]", "_", base.lower()).strip("_")
    return metric or "unnamed", instance


def _prom_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _prom_format(value: Any) -> str:
    # repr() floats round-trip exactly through float() — the renderer's
    # half of the snapshot-identity contract the tests assert
    return repr(float(value)) if isinstance(value, float) else str(value)


def render_prometheus(snapshot: Optional[Dict[str, Dict[str, Any]]] = None,
                      labels: Optional[Dict[str, str]] = None) -> str:
    """Prometheus text exposition of a :meth:`Dashboard.snapshot`.

    One sample per (instrument, stat field): the histogram
    ``SERVE_TTFT[lm]`` renders as ``mv_serve_ttft_p50_ms{name="...",
    instance="lm"} 1.25`` and so on. The full original instrument name
    always rides the ``name`` label, so the mapping is lossless (and the
    round-trip test can reconstruct the snapshot from the text).
    Monotonic stats (counter values, monitor/histogram counts,
    monitor total_ms) carry ``# TYPE counter``; everything else is a
    gauge. ``labels`` appends fixed extra labels to every sample — the
    fleet plane renders each node's registry with ``{"node": "<rank>"}``
    so one scrape surface covers the whole fleet without name
    collisions (``parse_prometheus`` tolerates the extra labels).
    """
    snap = Dashboard.snapshot() if snapshot is None else snapshot
    extra = "".join(f',{k}="{_prom_escape(str(v))}"'
                    for k, v in sorted((labels or {}).items()))
    families: Dict[str, List[str]] = {}
    family_type: Dict[str, str] = {}
    for name in sorted(snap):
        row = dict(snap[name])
        kind = row.pop("type", "gauge")
        metric, instance = _prom_split(name)
        for field in sorted(row):
            value = row[field]
            if not isinstance(value, (int, float)) or isinstance(value,
                                                                 bool):
                continue            # wire-merged rows may carry strings
            full = (f"mv_{metric}" if field == "value"
                    else f"mv_{metric}_{field}")
            monotone = (kind, field) in _MONOTONE_STATS
            sample_labels = f'name="{_prom_escape(name)}"'
            if instance is not None:
                sample_labels += f',instance="{_prom_escape(instance)}"'
            sample_labels += extra
            family_type.setdefault(full,
                                   "counter" if monotone else "gauge")
            families.setdefault(full, []).append(
                f"{full}{{{sample_labels}}} {_prom_format(value)}")
    lines: List[str] = []
    for full in sorted(families):
        lines.append(f"# TYPE {full} {family_type[full]}")
        lines.extend(families[full])
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus(text: str) -> Dict[str, Dict[str, float]]:
    """Inverse of :func:`render_prometheus` keyed by the ``name`` label:
    ``{instrument_name: {sample_name: value}}``. Used by the round-trip
    test and by anyone scraping the text sink without a Prometheus."""
    out: Dict[str, Dict[str, float]] = {}
    sample = re.compile(r'^(\w+)\{name="((?:[^"\\]|\\.)*)"[^}]*\} (\S+)$')
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = sample.match(line)
        if not m:
            continue
        full, name, value = m.groups()
        # unescape left-to-right (sequential .replace would corrupt a
        # literal backslash followed by 'n' into a newline)
        name = re.sub(r"\\(.)",
                      lambda g: {"n": "\n"}.get(g.group(1), g.group(1)),
                      name)
        out.setdefault(name, {})[full] = float(value)
    return out


class MetricsExporter:
    """Periodic metrics reporter: snapshot -> JSON-lines sink + deltas.

    Every ``interval_s`` (and on :meth:`stop`) it takes ONE
    ``Dashboard.snapshot()`` and appends one JSON line::

        {"ts": <epoch s>, "interval_s": <dt since last report or null>,
         "snapshot": {...}, "deltas": {name: {field: d, field_per_s: r}}}

    ``deltas`` cover the monotonic stats only (counter values,
    monitor/histogram counts, monitor total_ms): the interval-dt rates
    an operator actually plots, computed HERE so the sink needs no
    state. A snapshot whose monotonic stats went backwards (instrument
    reset) reports no delta for that instrument rather than a negative
    rate. :meth:`prometheus` renders the same snapshot for a scrape
    endpoint; both sinks see identical values by construction.
    """

    _MONOTONE = _MONOTONE_STATS

    def __init__(self, interval_s: float = 10.0, sink: Any = None,
                 emit=None) -> None:
        self.interval_s = float(interval_s)
        self._sink_path = sink if isinstance(sink, str) else None
        self._sink_file = sink if sink is not None and not isinstance(
            sink, str) else None
        self._emit = emit
        self._last: Optional[Dict[str, Dict[str, Any]]] = None
        self._last_ts: Optional[float] = None
        # interval math runs on the monotonic clock — a wall-clock step
        # (NTP) must not skew per-second delta rates; _last_ts is the
        # archived wall timestamp
        self._last_mono: Optional[float] = None
        # serializes snapshot+commit PAIRS across concurrent
        # report_once calls (see its docstring); distinct from _lock so
        # prometheus()/stop() never wait behind a registry sweep
        self._report_lock = lockwatch.lock(
            "dashboard.MetricsExporter._report_lock")
        self._lock = lockwatch.lock("dashboard.MetricsExporter._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.reports = 0

    # -- one report ---------------------------------------------------------
    def _deltas(self, snap: Dict[str, Dict[str, Any]],
                dt: Optional[float]) -> Dict[str, Dict[str, float]]:
        # the shared helper IS the semantics; this wrapper only binds the
        # exporter's last-snapshot state
        return snapshot_deltas(self._last, snap, dt)

    def report_once(self) -> dict:
        """Take one snapshot, compute interval deltas, write one line.

        ``_lock`` covers only the last-snapshot state, NOT the registry
        fan-out or the sink write: ``Dashboard.snapshot()`` acquires the
        registry lock plus every instrument's (locklint LK204 — holding
        ``_lock`` across it would serialize concurrent ``prometheus()``
        scrapes and ``stop()`` behind the whole sweep), and a stalled
        sink (full disk, hung NFS) must not block them either; an
        ``emit`` callback may safely call back into the exporter.

        ``_report_lock`` spans the snapshot+commit pair so concurrent
        calls (the reporter loop racing ``stop()``'s final report after
        a hung-sink join timeout) commit in snapshot order — without
        it, an older snapshot could commit as newest and the following
        report would double-count the interval its deltas re-span. It
        is touched by NOTHING else, so the LK204 concern above does not
        apply to it: scrapes and stop() never wait behind the sweep.
        """
        with self._report_lock:
            snap = Dashboard.snapshot()
            now = time.time()
            mono = time.monotonic()
            with self._lock:
                dt = ((mono - self._last_mono)
                      if self._last_mono is not None else None)
                record = {"ts": now, "interval_s": dt, "snapshot": snap,
                          "deltas": self._deltas(snap, dt)}
                self._last, self._last_ts = snap, now
                self._last_mono = mono
                self.reports += 1
        line = json.dumps(record)
        if self._sink_path is not None:
            with open(self._sink_path, "a") as f:
                f.write(line + "\n")
        elif self._sink_file is not None:
            self._sink_file.write(line + "\n")
        if self._emit is not None:
            self._emit(line)
        return record

    def prometheus(self) -> str:
        """Text exposition of the LAST reported snapshot (a scrape sees
        the same values the JSON line archived), or a fresh one before
        any report."""
        with self._lock:
            snap = self._last
        return render_prometheus(snap)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "MetricsExporter":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mv-metrics", daemon=True)
        self._thread.start()
        Dashboard.attach_reporter(self)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.report_once()
            except Exception as exc:    # pragma: no cover - sink errors
                from .log import Log
                Log.error("metrics exporter: report failed: %s", exc)

    def detach(self) -> None:
        """``Dashboard.reset()`` hook: stop WITHOUT a final report (the
        instruments were just cleared; archiving an empty snapshot over
        the sink's real data would only confuse the reader)."""
        self.stop(final_report=False)

    def stop(self, final_report: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        Dashboard.detach_reporter(self)
        if final_report:
            try:
                self.report_once()
            except Exception as exc:
                # a dead sink at shutdown (disk full, hung mount) must
                # not abort the rest of Session teardown
                from .log import Log
                Log.error("metrics exporter: final report failed: %s", exc)
