"""Named timing monitors + process-global dashboard.

TPU-native equivalent of the reference observability layer
(``include/multiverso/dashboard.h:16-73``, ``src/dashboard.cpp:14-45`` in the
Multiverso reference): named ``Monitor`` timers (count / total ms / average)
registered into a process-global ``Dashboard``, a ``monitor(name)`` context
manager replacing the ``MONITOR_BEGIN/END`` macros, ``Dashboard.watch`` by
name and ``Dashboard.display`` at shutdown.

On TPU the interesting spans are host-side walls around dispatched programs;
``monitor(..., block=True)`` additionally calls
``jax.block_until_ready`` on a result so the span covers device execution,
not just async dispatch.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional


class Timer:
    """Wall-clock start/elapse timer (reference ``util/timer.h:8-24``)."""

    def __init__(self) -> None:
        self.start()

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def elapse_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3


class Monitor:
    """Accumulating named timer (reference ``dashboard.h:26-57``).

    Start timestamps are thread-local so concurrent spans on the same
    monitor name don't clobber each other's begin().
    """

    def __init__(self, name: str, register: bool = True) -> None:
        self.name = name
        self.count = 0
        self.total_ms = 0.0
        self._local = threading.local()
        self._lock = threading.Lock()
        if register:
            Dashboard.add_monitor(self)

    def begin(self) -> None:
        self._local.t0 = time.perf_counter()

    def end(self) -> None:
        t0 = getattr(self._local, "t0", None)
        if t0 is None:
            return
        elapsed = (time.perf_counter() - t0) * 1e3
        self._local.t0 = None
        self.record(elapsed)

    def record(self, elapsed_ms: float) -> None:
        """Fold an externally-measured duration (e.g. a cross-process
        publish->apply latency carried in a wire record)."""
        with self._lock:
            self.count += 1
            self.total_ms += elapsed_ms

    def average_ms(self) -> float:
        with self._lock:
            return self.total_ms / self.count if self.count else 0.0

    def info_string(self) -> str:
        with self._lock:
            avg = self.total_ms / self.count if self.count else 0.0
            return (
                f"[{self.name}] count = {self.count} total = {self.total_ms:.3f} ms "
                f"avg = {avg:.3f} ms"
            )


class Histogram:
    """Bounded latency histogram: count/percentiles over a sliding window.

    The serving layer's per-reply latency sink (p50/p95/p99 + QPS need a
    distribution, not the Monitor's running mean). Keeps the most recent
    ``window`` samples in a ring — old traffic ages out, so percentiles
    track the CURRENT load regime, and memory stays bounded under
    sustained QPS. Thread-safe; registered in the Dashboard next to the
    Monitors so ``display()`` shows both.
    """

    WINDOW = 65536

    def __init__(self, name: str, window: int = WINDOW,
                 register: bool = True) -> None:
        self.name = name
        self.count = 0                      # lifetime samples (QPS numerator)
        self._buf = [0.0] * int(window)
        self._n = 0                         # filled slots (<= window)
        self._pos = 0                       # next write slot
        self._lock = threading.Lock()
        if register:
            Dashboard.add_histogram(self)

    def record(self, value_ms: float) -> None:
        with self._lock:
            self.count += 1
            self._buf[self._pos] = float(value_ms)
            self._pos = (self._pos + 1) % len(self._buf)
            self._n = min(self._n + 1, len(self._buf))

    def reset(self) -> None:
        """Drop retained samples (benches: exclude warmup compiles from
        the measured distribution)."""
        with self._lock:
            self.count = 0
            self._n = 0
            self._pos = 0

    def percentiles(self, ps) -> Dict[float, float]:
        """Nearest-rank percentiles over the retained window in ONE sort
        (0s if empty) — summary()/stats() pollers would otherwise pay a
        full sort per percentile while contending with record()."""
        with self._lock:
            n = self._n
            if n == 0:
                return {p: 0.0 for p in ps}
            # unwrapped: slots [0, n) are the live samples; wrapped: all are
            data = sorted(self._buf if n == len(self._buf) else self._buf[:n])
        return {p: data[min(n - 1, max(0, int(round(p / 100.0 * (n - 1)))))]
                for p in ps}

    def percentile(self, p: float) -> float:
        return self.percentiles((p,))[p]

    def summary(self) -> Dict[str, float]:
        qs = self.percentiles((50, 95, 99))
        return {
            "count": self.count,
            "p50_ms": qs[50],
            "p95_ms": qs[95],
            "p99_ms": qs[99],
        }

    def info_string(self) -> str:
        s = self.summary()
        return (f"[{self.name}] count = {int(s['count'])} "
                f"p50 = {s['p50_ms']:.3f} ms p95 = {s['p95_ms']:.3f} ms "
                f"p99 = {s['p99_ms']:.3f} ms")


class Gauge:
    """Last-value instrument: a point-in-time level, not a distribution.

    The serving engine's occupancy/throughput readouts (slots in use,
    decode tokens/sec) are levels — a histogram of them would average
    away exactly the saturation signal an operator looks for. ``set``
    overwrites; ``get`` reads the latest value.
    """

    def __init__(self, name: str, register: bool = True) -> None:
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()
        if register:
            Dashboard.add_gauge(self)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def get(self) -> float:
        with self._lock:
            return self._value

    def info_string(self) -> str:
        return f"[{self.name}] value = {self.get():.3f}"


class Dashboard:
    """Process-global monitor registry (reference ``dashboard.h:16-24``)."""

    _monitors: Dict[str, Monitor] = {}
    _histograms: Dict[str, "Histogram"] = {}
    _gauges: Dict[str, "Gauge"] = {}
    _lock = threading.Lock()

    @classmethod
    def add_monitor(cls, mon: Monitor) -> None:
        with cls._lock:
            cls._monitors[mon.name] = mon

    @classmethod
    def add_histogram(cls, hist: "Histogram") -> None:
        with cls._lock:
            cls._histograms[hist.name] = hist

    @classmethod
    def add_gauge(cls, gauge: "Gauge") -> None:
        with cls._lock:
            cls._gauges[gauge.name] = gauge

    @classmethod
    def get_or_create_histogram(cls, name: str) -> "Histogram":
        with cls._lock:
            hist = cls._histograms.get(name)
            if hist is None:
                hist = Histogram(name, register=False)
                cls._histograms[name] = hist
            return hist

    @classmethod
    def get_or_create_gauge(cls, name: str) -> "Gauge":
        with cls._lock:
            gauge = cls._gauges.get(name)
            if gauge is None:
                gauge = Gauge(name, register=False)
                cls._gauges[name] = gauge
            return gauge

    @classmethod
    def get_or_create(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = Monitor(name, register=False)
                cls._monitors[name] = mon
            return mon

    @classmethod
    def watch(cls, name: str) -> str:
        with cls._lock:
            mon = cls._monitors.get(name)
        return mon.info_string() if mon else f"[{name}] not monitored"

    @classmethod
    def stats(cls, name: str) -> Optional[Dict[str, float]]:
        with cls._lock:
            mon = cls._monitors.get(name)
            hist = cls._histograms.get(name)
            gauge = cls._gauges.get(name)
        if mon is not None:
            return {"count": mon.count, "total_ms": mon.total_ms,
                    "avg_ms": mon.average_ms()}
        if hist is not None:
            return hist.summary()
        if gauge is not None:
            return {"value": gauge.get()}
        return None

    @classmethod
    def display(cls, emit=None) -> str:
        with cls._lock:
            monitors = list(cls._monitors.values())
            histograms = list(cls._histograms.values())
            gauges = list(cls._gauges.values())
        lines = ["--------------Dashboard--------------"]
        lines += [m.info_string() for m in monitors]
        lines += [h.info_string() for h in histograms]
        lines += [g.info_string() for g in gauges]
        text = "\n".join(lines)
        if emit is None:
            from .log import Log
            emit = Log.info
        emit("%s", text)
        return text

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()
            cls._histograms.clear()
            cls._gauges.clear()


@contextmanager
def monitor(name: str, block_on: Any = None) -> Iterator[Monitor]:
    """Span context manager replacing MONITOR_BEGIN/END.

    If ``block_on`` is supplied (a jax.Array / pytree produced inside the
    span), it is blocked on before the span closes so device time is counted.
    """
    mon = Dashboard.get_or_create(name)
    mon.begin()
    try:
        yield mon
    finally:
        if block_on is not None:
            import jax
            jax.block_until_ready(block_on)
        mon.end()


def monitored_block_until_ready(name: str, value: Any) -> Any:
    """Time a block_until_ready on ``value`` under monitor ``name``."""
    import jax

    mon = Dashboard.get_or_create(name)
    mon.begin()
    jax.block_until_ready(value)
    mon.end()
    return value


@contextmanager
def profile_trace(log_dir: str, name: str = "PROFILE") -> Iterator[Monitor]:
    """Capture an XLA profiler trace for the enclosed span.

    Observability tier above the reference's wall-clock Monitors (SURVEY
    §5.5: "no tracing spans"): wraps ``jax.profiler`` so the span's device
    timeline (HLO ops, HBM transfers, collective phases) lands in
    ``log_dir`` for TensorBoard/xprof, while a Dashboard monitor records
    the same span's wall time alongside the other counters.
    """
    import jax

    mon = Dashboard.get_or_create(name)
    mon.begin()
    jax.profiler.start_trace(log_dir)
    try:
        yield mon
    finally:
        jax.profiler.stop_trace()
        mon.end()
