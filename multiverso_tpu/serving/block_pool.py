"""Block-pool allocator for the paged KV cache — refcounted + content-addressed.

The decode engine's original cache gave every slot a contiguous
``[T, D]`` strip sized for the worst case ``max_prompt + max_new`` — a
short sequence wasted almost its whole strip, so concurrency was capped
by slot geometry rather than by actual KV bytes. The paged layout
(vLLM/PagedAttention) carves the same memory into fixed-size **blocks**
of ``block_size`` token positions each; a sequence owns
``ceil((prompt_len + max_new) / block_size)`` blocks, recorded in a
per-slot **block table** the jitted programs consume as traced data.

This module is the host-side half: an allocator over block ids. Device
memory itself lives in the engine (``[L, n_blocks + 1, block_size, D]``
pools); the allocator only hands out integer ids and keeps the books
honest. Since the prefix-caching PR a block is more than "free or
live" — it moves through three states:

* **free** — on the free list, content undefined;
* **live** — held by >= 1 sequences (``_ref[block] >= 1``). A block
  held by SEVERAL sequences is *shared*: every holder reads it, nobody
  writes it (the engine copy-on-writes before any write into a shared
  block — see ``decode_engine._reserve_blocks``);
* **cached** — refcount dropped to zero but the block is
  **content-addressed** (registered under a hash-chain identity), so
  it stays resident in LRU order: a later prompt with the same prefix
  reactivates it via :meth:`lookup` instead of re-prefilling, and
  allocation pressure evicts it (:data:`PREFIX_EVICTIONS`) back to the
  free list.

Content addressing: a *full* block's identity is the blake2b hash of
its token span **chained with its predecessor's hash** (plus a
caller-supplied seed — the engine seeds with the pinned snapshot
version, since K/V bytes are a function of (token prefix, params)).
:func:`chain_hashes` computes the chain; :meth:`register` indexes a
block under its hash, :meth:`peek`/:meth:`lookup` find the longest
cached prefix of an arriving prompt. Divergence is block-granular: a
prompt that differs anywhere inside a block simply misses that block's
hash and every chained one after it.

Guards are unchanged in spirit: allocating past free + cached
capacity, double-``decref``, freeing a shared block, or registering a
non-live block raises — a bookkeeping hole here silently corrupts a
NEIGHBORING sequence's KV cache, so it is a bug to crash on, not a
condition to limp through (the property tests churn all of it, and
:meth:`drift` scans every invariant non-raising for the watchdog).

Occupancy is observable: ``KV_BLOCKS_FREE``/``KV_BLOCKS_LIVE`` and the
new ``KV_BLOCKS_SHARED`` gauges, ``BLOCK_ALLOC``/``BLOCK_FREE`` churn
counters, and the prefix-cache counters ``PREFIX_HITS``/
``PREFIX_MISSES``/``PREFIX_EVICTIONS`` all land in the Dashboard next
to the engine's slot metrics (docs/OBSERVABILITY.md).

Capacity math lives here too (:func:`kv_bytes_per_block`,
:func:`blocks_for_bytes`): the ``-kv_pool_blocks`` flag sizes the pool
in blocks, and the bench's equal-KV-bytes A/B converts a bytes budget
into the equivalent block count.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from ..analysis import lockwatch
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..dashboard import Dashboard

# block id 0: reserved scratch — the block-table pad sentinel and the
# parking target for dead-lane / pad-position writes. Never allocated.
SCRATCH_BLOCK = 0


def kv_bytes_per_block(n_layers: int, d_model: int, block_size: int,
                       dtype=np.float32, quant: str = "none") -> int:
    """Device bytes one block costs across BOTH pools (K and V).

    ``quant="int8"`` reports the REAL quantized footprint: int8 payload
    plus the per-(layer, block) fp32 scale each pool carries
    (``models.transformer`` ``_q`` kernels) — the honest number the
    pool-byte budget divides by, so the bench's equal-bytes A/B cannot
    flatter quantization by forgetting its scales."""
    if quant == "int8":
        return 2 * n_layers * (block_size * d_model + 4)
    return 2 * n_layers * block_size * d_model * np.dtype(dtype).itemsize


def blocks_for_bytes(budget_bytes: int, n_layers: int, d_model: int,
                     block_size: int, dtype=np.float32,
                     quant: str = "none") -> int:
    """Usable blocks a KV-bytes budget buys (scratch block excluded:
    its bytes ride along, but it holds no sequence).

    Raises for a budget too small for scratch + one usable block: the
    result feeds ``kv_pool_blocks``, where ``0`` means AUTO-size — a
    silent 0 here would turn "tiny budget" into "contiguous-equivalent
    pool", a many-fold device-memory overshoot."""
    per = kv_bytes_per_block(n_layers, d_model, block_size, dtype, quant)
    n = budget_bytes // per - 1
    if n < 1:
        raise ValueError(
            f"KV budget {budget_bytes} B buys no usable block: need >= "
            f"{2 * per} B (scratch + 1 block of {per} B at block_size "
            f"{block_size})")
    return int(n)


def chain_hashes(tokens: Sequence[int], block_size: int,
                 seed: bytes = b"") -> List[bytes]:
    """Hash-chained identities of every FULL block of ``tokens``.

    ``hashes[k]`` identifies token span ``[k*Bs, (k+1)*Bs)`` *given its
    whole prefix*: each digest folds in its predecessor's, so equal
    hashes mean equal token prefixes up to and including the block (to
    blake2b-128 collision odds — the standard prefix-cache trade, same
    as vLLM's). A trailing partial block has no identity: only full
    blocks are ever shared. ``seed`` scopes the chain — the engine
    passes the pinned snapshot version, because cached K/V bytes are a
    function of (token prefix, params version)."""
    arr = np.ascontiguousarray(np.asarray(tokens, np.int32).ravel())
    out: List[bytes] = []
    h = seed
    for k in range(arr.shape[0] // block_size):
        d = hashlib.blake2b(h, digest_size=16)
        d.update(arr[k * block_size:(k + 1) * block_size].tobytes())
        h = d.digest()
        out.append(h)
    return out


class BlockPool:
    """Refcounted free-list allocator over ``n_blocks`` usable KV blocks.

    Block ids run ``1 .. n_blocks`` (id 0 is the scratch block). The
    engine allocates a sequence's whole reservation up front at
    admission (``prompt + max_new`` worth of positions, LESS any blocks
    found in the prefix cache) and ``decref``s it at eos/completion, so
    pool occupancy — not slot geometry — is what bounds concurrency,
    and shared prefixes occupy their blocks once.
    """

    def __init__(self, n_blocks: int, block_size: int,
                 name: str = "") -> None:
        if n_blocks < 1:
            raise ValueError(f"BlockPool needs >= 1 usable block, "
                             f"got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.capacity = int(n_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(n_blocks, 0, -1))  # pop() -> 1 first
        self._ref: Dict[int, int] = {}       # live block -> refcount >= 1
        self._n_shared = 0                   # live blocks with refcount >= 2
        # content index: chain hash <-> block id (live OR cached), plus
        # the cached-LRU order (oldest first; eviction pops the front)
        self._index: Dict[bytes, int] = {}
        self._hash_of: Dict[int, bytes] = {}
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        self._lock = lockwatch.lock("serving.BlockPool._lock")
        self.allocs = 0                # blocks taken off the free list
        self.frees = 0                 # blocks returned to the free list
        self.hits = 0                  # prefix-cache block hits (monotonic)
        self.misses = 0                # full blocks looked up and absent
        self.evictions = 0             # cached blocks reclaimed by pressure
        label = name or "pool"
        self.free_gauge = Dashboard.get_or_create_gauge(
            f"KV_BLOCKS_FREE[{label}]")
        self.live_gauge = Dashboard.get_or_create_gauge(
            f"KV_BLOCKS_LIVE[{label}]")
        self.shared_gauge = Dashboard.get_or_create_gauge(
            f"KV_BLOCKS_SHARED[{label}]")
        self.alloc_counter = Dashboard.get_or_create_counter(
            f"BLOCK_ALLOC[{label}]")
        self.free_counter = Dashboard.get_or_create_counter(
            f"BLOCK_FREE[{label}]")
        self.hit_counter = Dashboard.get_or_create_counter(
            f"PREFIX_HITS[{label}]")
        self.miss_counter = Dashboard.get_or_create_counter(
            f"PREFIX_MISSES[{label}]")
        self.evict_counter = Dashboard.get_or_create_counter(
            f"PREFIX_EVICTIONS[{label}]")
        self.free_gauge.set(float(n_blocks))
        self.live_gauge.set(0.0)
        self.shared_gauge.set(0.0)

    # -- sizing -------------------------------------------------------------
    def blocks_needed(self, n_tokens: int) -> int:
        """Blocks covering ``n_tokens`` cache positions."""
        return -(-int(n_tokens) // self.block_size)

    def covers(self, n_tokens: int) -> bool:
        """Whether the pool could EVER hold ``n_tokens`` positions
        (capacity check — the submit-time shed gate)."""
        return self.blocks_needed(n_tokens) <= self.capacity

    # -- allocation ---------------------------------------------------------
    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_live(self) -> int:
        with self._lock:
            return len(self._ref)

    @property
    def n_cached(self) -> int:
        with self._lock:
            return len(self._cached)

    @property
    def n_shared(self) -> int:
        with self._lock:
            return self._n_shared

    def can_alloc(self, n: int) -> bool:
        """Cached blocks count: they are reclaimable on demand."""
        with self._lock:
            return n <= len(self._free) + len(self._cached)

    def _evict_one_locked(self) -> None:
        """Reclaim the least-recently-used cached block: drop its
        content identity and return it to the free list."""
        block, _ = self._cached.popitem(last=False)
        h = self._hash_of.pop(block)
        del self._index[h]
        self._free.append(block)
        self.evictions += 1
        self.frees += 1

    def alloc(self, n: int) -> List[int]:
        """Hand out ``n`` fresh block ids (refcount 1), evicting LRU
        cached blocks under free-list pressure; raises if even the
        cache cannot cover it (callers gate on :meth:`can_alloc` —
        running dry mid-admission is an accounting bug, not an
        overload condition)."""
        with self._lock:
            evicted0 = self.evictions
            if n > len(self._free) + len(self._cached):
                raise RuntimeError(
                    f"BlockPool: alloc({n}) with only {len(self._free)} "
                    f"free + {len(self._cached)} cached of {self.capacity}")
            while len(self._free) < n:
                self._evict_one_locked()
            blocks = [self._free.pop() for _ in range(n)]
            for b in blocks:
                self._ref[b] = 1
            self.allocs += n
            evicted = self.evictions - evicted0
            self._update_gauges_locked()
        self.alloc_counter.inc(n)
        if evicted:
            self.evict_counter.inc(evicted)
            self.free_counter.inc(evicted)
        return blocks

    def free(self, blocks: Iterable[int]) -> None:
        """Hard-return sole-owner blocks to the pool (the strict,
        pre-refcount API): a shared block, a cached block, or a foreign
        id raises. Refcount-aware callers use :meth:`decref`."""
        blocks = list(blocks)
        with self._lock:
            for b in blocks:
                r = self._ref.get(b)
                if r is None:
                    raise RuntimeError(
                        f"BlockPool: freeing block {b} that is not live "
                        f"(double-free or foreign id)")
                if r != 1:
                    raise RuntimeError(
                        f"BlockPool: freeing block {b} with refcount {r} "
                        f"(shared; use decref)")
                del self._ref[b]
                h = self._hash_of.pop(b, None)
                if h is not None:
                    del self._index[h]
                self._free.append(b)
            self.frees += len(blocks)
            self._update_gauges_locked()
        self.free_counter.inc(len(blocks))

    # -- sharing ------------------------------------------------------------
    def decref(self, blocks: Iterable[int]) -> None:
        """Drop one holder per block. A block reaching refcount 0 goes
        **cached** if it is content-addressed (most-recent end of the
        LRU) or back to the free list otherwise."""
        blocks = list(blocks)
        freed = 0
        with self._lock:
            for b in blocks:
                r = self._ref.get(b)
                if r is None:
                    raise RuntimeError(
                        f"BlockPool: decref on block {b} that is not live "
                        f"(double-decref or foreign id)")
                if r > 1:
                    self._ref[b] = r - 1
                    if r == 2:
                        self._n_shared -= 1
                    continue
                del self._ref[b]
                if b in self._hash_of:
                    self._cached[b] = None       # most-recently released
                else:
                    self._free.append(b)
                    freed += 1
            self.frees += freed
            self._update_gauges_locked()
        if freed:
            self.free_counter.inc(freed)

    # -- content addressing -------------------------------------------------
    def register(self, block: int, chain_hash: bytes) -> bool:
        """Index a live, fully-written block under its chain hash.

        Returns False (a no-op) when the hash is already indexed — a
        concurrent sequence registered identical content first, and one
        copy is all the cache wants. Registering a block that already
        carries a DIFFERENT identity raises: content is immutable once
        addressed (that is what makes sharing safe)."""
        with self._lock:
            if block not in self._ref:
                raise RuntimeError(
                    f"BlockPool: registering block {block} that is not live")
            if chain_hash in self._index:
                return False
            if block in self._hash_of:
                raise RuntimeError(
                    f"BlockPool: block {block} already content-addressed")
            self._index[chain_hash] = block
            self._hash_of[block] = chain_hash
        return True

    def peek(self, hashes: Sequence[bytes]) -> int:
        """Longest indexed prefix of ``hashes`` — no refcount changes,
        no hit/miss accounting (the admission gate polls this every
        loop pass while a request waits for blocks)."""
        return self.peek_counts(hashes)[0]

    def peek_counts(self, hashes: Sequence[bytes]) -> tuple:
        """``(matched, matched_cached)`` for the longest indexed prefix
        of ``hashes``. The second count is what the admission gate's
        capacity arithmetic needs: a matched block currently in the
        CACHED tier still satisfies the hit, but claiming it consumes
        one unit of the reclaimable (free + cached) supply — unlike a
        live-shared hit, which costs nothing."""
        with self._lock:
            m = cached = 0
            for h in hashes:
                b = self._index.get(h)
                if b is None:
                    break
                m += 1
                if b in self._cached:
                    cached += 1
        return m, cached

    def lookup(self, hashes: Sequence[bytes]) -> List[int]:
        """Claim the longest cached prefix: each matched block gains a
        holder (cached blocks reactivate at refcount 1) and the match
        list splices into the caller's block table. Counts one hit per
        matched block and one miss per full block past the match."""
        matched: List[int] = []
        with self._lock:
            for h in hashes:
                b = self._index.get(h)
                if b is None:
                    break
                if b in self._cached:
                    del self._cached[b]
                    self._ref[b] = 1
                else:
                    r = self._ref[b]
                    self._ref[b] = r + 1
                    if r == 1:
                        self._n_shared += 1
                matched.append(b)
            self.hits += len(matched)
            self.misses += len(hashes) - len(matched)
            self._update_gauges_locked()
        if matched:
            self.hit_counter.inc(len(matched))
        if len(hashes) > len(matched):
            self.miss_counter.inc(len(hashes) - len(matched))
        return matched

    def indexed_hashes(self, limit: Optional[int] = None) -> List[bytes]:
        """Chain hashes currently content-addressed here (live OR
        cached), insertion order, optionally capped. This is the
        decode replica's dedup ADVERTISEMENT: the heartbeat ships it so
        the prefill side can skip shipping blocks the receiver already
        holds (kv_transfer source-side dedup). A capped list is a
        weaker advertisement, never a wrong one — an unadvertised block
        just crosses the wire and dedups on arrival instead."""
        with self._lock:
            out = list(self._index)
        return out if limit is None else out[:int(limit)]

    def flush_cache(self) -> int:
        """Drop every content identity and free all cached blocks (the
        engine calls this when the pinned snapshot moves: cached K/V
        computed under the old params is garbage to the new ones).
        Live blocks keep running but lose their index entries. Returns
        the number of blocks freed."""
        with self._lock:
            freed = len(self._cached)
            for b in self._cached:
                self._free.append(b)
            self._cached.clear()
            self._index.clear()
            self._hash_of.clear()
            self.frees += freed
            self._update_gauges_locked()
        if freed:
            self.free_counter.inc(freed)
        return freed

    def _update_gauges_locked(self) -> None:
        self.free_gauge.set(float(len(self._free)))
        self.live_gauge.set(float(len(self._ref)))
        self.shared_gauge.set(float(self._n_shared))

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "block_size": self.block_size,
                "free": len(self._free),
                "live": len(self._ref),
                "cached": len(self._cached),
                "blocks_shared": self._n_shared,
                "allocs": self.allocs,
                "frees": self.frees,
                "prefix_hits": self.hits,
                "prefix_misses": self.misses,
                "prefix_evictions": self.evictions,
            }

    def drift(self) -> Optional[str]:
        """Invariant scan -> violation description, or None when the
        books balance. The watchdog's poll entry point: unlike
        :meth:`check` it never raises (and never depends on ``assert``
        surviving ``-O``), so a corrupted pool yields a diagnosis
        instead of an exception inside the health thread. Refcounted
        sharing and the cached state are PART of the invariants, not
        violations: free + live + cached partition the capacity, and a
        cached block is exactly a refcount-0 content-addressed one."""
        with self._lock:
            free = set(self._free)
            if len(free) != len(self._free):
                return (f"duplicate ids in free list "
                        f"({len(self._free)} entries, {len(free)} unique)")
            live = set(self._ref)
            cached = set(self._cached)
            for a, b, what in ((free, live, "free and live"),
                               (free, cached, "free and cached"),
                               (live, cached, "live and cached")):
                both = a & b
                if both:
                    return (f"{len(both)} id(s) both {what}: "
                            f"{sorted(both)[:8]}")
            if len(free) + len(live) + len(cached) != self.capacity:
                return (f"leak: {len(free)} free + {len(live)} live + "
                        f"{len(cached)} cached != capacity {self.capacity}")
            if any(SCRATCH_BLOCK in s for s in (free, live, cached)):
                return "scratch block entered circulation"
            bad = [b for b, r in self._ref.items() if r < 1]
            if bad:
                return f"live block(s) with refcount < 1: {sorted(bad)[:8]}"
            shared = sum(1 for r in self._ref.values() if r >= 2)
            if shared != self._n_shared:
                return (f"shared-count skew: {self._n_shared} tracked, "
                        f"{shared} actual")
            if set(self._hash_of) != {b for b in self._index.values()}:
                return "content index and hash map disagree on blocks"
            for h, b in self._index.items():
                if self._hash_of.get(b) != h:
                    return f"content index not a bijection at block {b}"
            unindexed = cached - set(self._hash_of)
            if unindexed:
                return (f"cached block(s) without a content identity: "
                        f"{sorted(unindexed)[:8]}")
            stray = set(self._hash_of) - live - cached
            if stray:
                return (f"content-addressed block(s) neither live nor "
                        f"cached: {sorted(stray)[:8]}")
        return None

    def check(self) -> None:
        """Invariant check (tests): free + live + cached == capacity,
        pairwise disjoint, index consistent. Raises ``AssertionError``
        on the first violation."""
        msg = self.drift()
        if msg is not None:
            raise AssertionError(f"BlockPool: {msg}")
